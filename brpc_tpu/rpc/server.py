"""Server: service registry + lifecycle over any transport.

Reference: src/brpc/server.{h,cpp} (StartInternal :741, AddService :1477,
AddBuiltinServices :459, BuildAcceptor :567).  A server listens on one or
more endpoints (mem://name for in-process, tcp host:port for DCN, ici://
via the device fabric), exposes registered services through every server
protocol, tracks per-method status, and optionally mounts the builtin admin
service set.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..butil.endpoint import EndPoint, parse_endpoint, SCHEME_MEM, SCHEME_TCP
from ..butil import logging as log
from .. import bvar
from . import errors
from .input_messenger import InputMessenger
from .method_status import MethodStatus
from .service import MethodDescriptor, Service


@dataclass
class ServerOptions:
    max_concurrency: int = 0            # 0 = unlimited; else ELIMIT beyond
    method_max_concurrency: Dict[str, Any] = field(default_factory=dict)
    auth: Any = None                    # Authenticator
    enable_builtin_services: bool = True
    # display name on /status (reference server.h server_info_name)
    server_info_name: str = ""
    # close connections with no READ/WRITE activity for this many
    # seconds (reference server.h idle_timeout_sec semantics: a handler
    # still computing counts as idle — size this above your slowest
    # handler); -1 = never
    idle_timeout_s: int = -1
    # when >= 0: builtin/admin pages are served ONLY on this extra TCP
    # port, and the public port refuses them (reference server.h
    # internal_port — keeps /flags, /pprof etc. off the service VIP)
    internal_port: int = -1
    concurrency_limiter: str = ""       # "", "constant", "auto", "timeout"
    # Run user handlers directly on the delivering thread for loopback/ici
    # transports (the reference's default runs usercode in the IO bthread;
    # its usercode_in_pthread flag is the inverse).  Minimal latency; only
    # safe when handlers are fast/non-blocking.
    usercode_inline: bool = False
    # The reference's usercode_in_pthread analogue: run user handlers on
    # a dedicated backup THREAD pool instead of scheduler workers.  The
    # scheduler compensates for workers parked in butexes, but a
    # CPU-BOUND (GIL-holding) handler never parks — enough of them
    # occupy every worker and stall unrelated sockets' reads (the
    # docs/en/io.md hazard).  With the pool, scheduler workers only
    # parse/dispatch and stay available no matter what usercode does.
    usercode_in_pthread: bool = False
    usercode_backup_threads: int = 8
    ssl_context: Any = None             # ssl.SSLContext for TLS listeners
    # per-RPC session data: factory() -> object, pooled across requests
    # (reference server.h:146-150 session_local_data_factory; reached via
    # Controller.session_local_data() inside handlers)
    session_local_data_factory: Any = None
    # per-worker-thread data: factory() -> object (server.h
    # thread_local_data_factory; reached via Server.thread_local_data())
    thread_local_data_factory: Any = None
    # restful mappings (reference restful.cpp): url path -> method
    #   {"/v1/echo": "EchoService.Echo"}
    restful_mappings: Dict[str, str] = field(default_factory=dict)
    # ici:// servers also open the native-datapath front door (the C++
    # plane in native/rpc.cpp; in-process channels prefer it).  The Python
    # IciListener stays registered either way — it serves fabric peers and
    # non-tpu_std protocols.  Disable to force the pure-Python plane.
    native_ici: bool = True


class Server:
    def __init__(self, options: Optional[ServerOptions] = None):
        self.options = options or ServerOptions()
        self._services: Dict[str, Service] = {}
        self._methods: Dict[str, MethodDescriptor] = {}
        self._method_status: Dict[str, MethodStatus] = {}
        self._started = False
        self._listen_endpoints: List[EndPoint] = []
        self._mem_listener = None
        self._acceptor = None
        self.messenger = InputMessenger(server=self)
        self._server_concurrency = 0
        self._conc_lock = threading.Lock()
        self._stopped = threading.Event()
        self.version = ""
        self._connections: List[Any] = []
        self._conn_lock = threading.Lock()
        self._session_data_pool: List[Any] = []
        self._session_data_lock = threading.Lock()
        self._thread_local = threading.local()
        self.usercode_pool = None        # usercode_in_pthread backup pool

    # ---- registry -----------------------------------------------------
    def add_service(self, svc) -> int:
        if self._started:
            raise RuntimeError("cannot add service after start")
        # RedisService / ThriftService dispatchers register as connection-
        # level protocol handlers (duck-typed to avoid policy import cycles)
        if hasattr(svc, "dispatch") and hasattr(svc, "add_handler"):
            self.redis_service = svc
            return 0
        if hasattr(svc, "handle") and hasattr(svc, "add_method"):
            self.thrift_service = svc
            return 0
        if getattr(svc, "SERVICE_NAME", None) == "mongo" and \
                hasattr(svc, "process"):
            self._mongo_service = svc
            return 0
        # NsheadService / adaptors (nova, public_pbrpc, ubrpc ride on this):
        # exactly one may own the connection's nshead frames
        if getattr(svc, "SERVICE_NAME", None) == "nshead" and \
                hasattr(svc, "process_nshead_request"):
            if getattr(self, "_nshead_service", None) is not None:
                return errors.EINVAL
            self._nshead_service = svc
            return 0
        # RtmpService: per-connection stream factory (rtmp.h RtmpService);
        # the rtmp protocol only claims connections when one is registered
        if getattr(svc, "SERVICE_NAME", None) == "rtmp" and \
                hasattr(svc, "new_stream"):
            if getattr(self, "_rtmp_service", None) is not None:
                return errors.EINVAL
            self._rtmp_service = svc
            return 0
        # EspService raw handler (same single-owner rule)
        if getattr(svc, "SERVICE_NAME", None) == "esp" and \
                hasattr(svc, "process_esp_request"):
            if getattr(self, "_esp_service", None) is not None:
                return errors.EINVAL
            self._esp_service = svc
            return 0
        name = svc.service_name()
        if name in self._services:
            return errors.EINVAL
        self._services[name] = svc
        from ..butil import flags as _flags
        for mname, md in svc.methods().items():
            self._methods[md.full_name] = md
            limiter = self._make_limiter(md.full_name)
            self._method_status[md.full_name] = MethodStatus(md.full_name,
                                                             limiter)
        return 0

    def _make_limiter(self, full_name: str):
        mc = self.options.method_max_concurrency.get(full_name)
        kind = self.options.concurrency_limiter
        from ..policy import limiters
        if isinstance(mc, int) and mc > 0:
            return limiters.ConstantConcurrencyLimiter(mc)
        if mc == "auto" or kind == "auto":
            return limiters.AutoConcurrencyLimiter()
        if kind == "timeout":
            return limiters.TimeoutConcurrencyLimiter()
        if kind == "constant" and self.options.max_concurrency > 0:
            return limiters.ConstantConcurrencyLimiter(
                self.options.max_concurrency)
        return None

    def find_method(self, full_name: str) -> Optional[MethodDescriptor]:
        return self._methods.get(full_name)

    def method_status(self, full_name: str) -> Optional[MethodStatus]:
        return self._method_status.get(full_name)

    def services(self) -> Dict[str, Service]:
        return dict(self._services)

    def method_statuses(self) -> List[MethodStatus]:
        return list(self._method_status.values())

    # ---- server-level concurrency (reference max_concurrency) ---------
    def on_request_in(self) -> bool:
        mc = self.options.max_concurrency
        with self._conc_lock:
            if mc > 0 and self._server_concurrency >= mc:
                return False
            self._server_concurrency += 1
            return True

    def on_request_out(self) -> None:
        with self._conc_lock:
            self._server_concurrency -= 1

    # ---- per-RPC / per-thread user data (server.h:126-150) ------------
    def _get_session_data(self) -> Any:
        if self.options.session_local_data_factory is None:
            return None
        with self._session_data_lock:
            if self._session_data_pool:
                return self._session_data_pool.pop()
        return self.options.session_local_data_factory()

    def _return_session_data(self, data: Any) -> None:
        if data is None:
            return
        with self._session_data_lock:
            if len(self._session_data_pool) < 1024:
                self._session_data_pool.append(data)

    def thread_local_data(self) -> Any:
        """Data attached to the calling worker thread, created on first
        use by options.thread_local_data_factory."""
        factory = self.options.thread_local_data_factory
        if factory is None:
            return None
        data = getattr(self._thread_local, "data", None)
        if data is None:
            data = self._thread_local.data = factory()
        return data

    # ---- lifecycle ----------------------------------------------------
    def start(self, addr: Any = None, options: Optional[ServerOptions] = None) -> int:
        if options is not None:
            self.options = options
        if self._started:
            return errors.EINVAL
        self._stopped.clear()           # restartable after stop():
        self._listen_endpoints = []     # fresh run, fresh addresses
        with self._conn_lock:
            self._connections = []
        if self.options.usercode_in_pthread and self.usercode_pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self.usercode_pool = ThreadPoolExecutor(
                max_workers=max(self.options.usercode_backup_threads, 1),
                thread_name_prefix="usercode")
        if self.options.enable_builtin_services:
            from .builtin import register_builtin_services
            register_builtin_services(self)
        if addr is None:
            addr = "mem://server"
        if isinstance(addr, int):
            ep = EndPoint(scheme=SCHEME_TCP, host="0.0.0.0", port=addr)
        elif isinstance(addr, str):
            # A port-less bare name is unambiguous on the LISTEN side (you
            # can't listen on tcp without a port), so any such name — even
            # dotted or all-digits ones parse_endpoint would reject as
            # probable client-side typos — is an in-process registry.
            if ":" not in addr and "://" not in addr:
                addr = "mem://" + addr
            ep = parse_endpoint(addr)
        else:
            ep = addr
        if ep.scheme == SCHEME_MEM:
            from .mem_transport import mem_listen
            self._mem_listener = mem_listen(ep.host, self._on_accept)
        elif ep.scheme == SCHEME_TCP:
            from .tcp_transport import Acceptor
            self._acceptor = Acceptor(self._on_accept,
                                      ssl_context=self.options.ssl_context)
            port = self._acceptor.start(ep.host or "0.0.0.0", ep.port)
            ep = EndPoint(scheme=SCHEME_TCP, host=ep.host or "0.0.0.0",
                          port=port)
        elif ep.scheme == "ici":
            from ..ici.transport import ici_listen
            self._ici_listener = ici_listen(ep.device_id, self._on_accept)
            if self.options.native_ici:
                try:
                    from ..ici import native_plane
                    if native_plane.available():
                        self._native_ici = native_plane.ServerBinding(
                            self, ep.device_id)
                except Exception as e:   # native plane is an accelerator,
                    log.warning(         # not a requirement
                        "native ici plane unavailable (%s); "
                        "Python datapath only", e)
        else:
            raise ValueError(f"cannot listen on scheme {ep.scheme}")
        try:
            if self.options.internal_port >= 0:
                from .tcp_transport import Acceptor
                # same bind address and TLS posture as the main listener:
                # a loopback-restricted service must not grow a
                # world-reachable plaintext admin port
                self._internal_acceptor = Acceptor(
                    self._on_accept_internal,
                    ssl_context=self.options.ssl_context)
                # same bind host as a TCP main listener; for mem://
                # and ici:// servers (no network host) the admin port
                # stays on loopback — never a surprise 0.0.0.0 listener
                host = ep.host if ep.scheme == SCHEME_TCP and ep.host \
                    else "127.0.0.1"
                self._internal_port = self._internal_acceptor.start(
                    host, self.options.internal_port)
            if self.options.idle_timeout_s > 0:
                self._start_idle_reaper()
        except Exception:
            # a half-started server must not leak its live listeners: a
            # retry of start() would otherwise double-bind
            self._teardown_listeners()
            raise
        self._listen_endpoints.append(ep)
        self._started = True
        log.info("Server started on %s with %d services", ep,
                 len(self._services))
        # version ping, off unless the trackme_server flag is set
        # (reference server.cpp StartInternal → trackme.cpp:36)
        from .trackme import start_trackme
        start_trackme(str(ep))
        return 0

    def _on_accept(self, sock) -> None:
        sock.messenger = self.messenger
        sock.usercode_inline = self.options.usercode_inline
        with self._conn_lock:
            self._connections = [s for s in self._connections if not s.failed]
            self._connections.append(sock)

    def _on_accept_internal(self, sock) -> None:
        sock.internal_only = True       # admin pages only (http checks)
        self._on_accept(sock)

    @property
    def internal_port(self) -> int:
        return getattr(self, "_internal_port", -1)

    def _start_idle_reaper(self) -> None:
        import time as _time

        def reap() -> None:
            period = max(0.5, self.options.idle_timeout_s / 2.0)
            while not self._stopped.wait(period):
                cutoff = _time.monotonic() - self.options.idle_timeout_s
                with self._conn_lock:
                    conns = list(self._connections)
                for s in conns:
                    if getattr(s, "last_active", cutoff + 1) <= cutoff:
                        s.set_failed(errors.ECLOSE,
                                     f"idle > {self.options.idle_timeout_s}s")

        t = threading.Thread(target=reap, name="idle_reaper", daemon=True)
        t.start()

    @property
    def listen_endpoint(self) -> Optional[EndPoint]:
        return self._listen_endpoints[0] if self._listen_endpoints else None

    @property
    def listen_port(self) -> int:
        ep = self.listen_endpoint
        return ep.port if ep else 0

    def is_running(self) -> bool:
        return self._started and not self._stopped.is_set()

    def _teardown_listeners(self) -> None:
        if self._mem_listener is not None:
            from .mem_transport import mem_unlisten
            mem_unlisten(self._mem_listener.name)
            self._mem_listener = None
        if self._acceptor is not None:
            self._acceptor.stop()
            self._acceptor = None
        if getattr(self, "_internal_acceptor", None) is not None:
            self._internal_acceptor.stop()
            self._internal_acceptor = None
        if getattr(self, "_ici_listener", None) is not None:
            from ..ici.transport import ici_unlisten
            ici_unlisten(self._ici_listener.device_id)
            self._ici_listener = None
        if getattr(self, "_native_ici", None) is not None:
            self._native_ici.stop()
            self._native_ici = None

    def stop(self) -> int:
        if not self._started:
            return 0
        self._teardown_listeners()
        with self._conn_lock:
            conns = list(self._connections)
        for s in conns:
            # graceful h2 shutdown: GOAWAY first so the peer knows which
            # streams were processed and retries the rest safely
            if getattr(s, "_h2_conn", None) is not None:
                try:
                    from ..policy.grpc import send_goaway
                    send_goaway(s)
                except Exception:
                    pass
            s.set_failed(errors.ELOGOFF, "server stopping")
        pool, self.usercode_pool = self.usercode_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        self._stopped.set()
        self._started = False
        return 0

    def join(self, timeout: Optional[float] = None) -> None:
        self._stopped.wait(timeout)

    def connections(self) -> List[Any]:
        with self._conn_lock:
            return [s for s in self._connections if not s.failed]
