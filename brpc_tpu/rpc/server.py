"""Server: service registry + lifecycle over any transport.

Reference: src/brpc/server.{h,cpp} (StartInternal :741, AddService :1477,
AddBuiltinServices :459, BuildAcceptor :567).  A server listens on one or
more endpoints (mem://name for in-process, tcp host:port for DCN, ici://
via the device fabric), exposes registered services through every server
protocol, tracks per-method status, and optionally mounts the builtin admin
service set.
"""
from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..butil.endpoint import EndPoint, parse_endpoint, SCHEME_MEM, SCHEME_TCP
from ..butil import logging as log
from . import errors
from .input_messenger import InputMessenger
from .method_status import MethodStatus
from .service import MethodDescriptor, Service


@dataclass
class ServerOptions:
    max_concurrency: int = 0            # 0 = unlimited; else ELIMIT beyond
    method_max_concurrency: Dict[str, Any] = field(default_factory=dict)
    auth: Any = None                    # Authenticator
    enable_builtin_services: bool = True
    # display name on /status (reference server.h server_info_name)
    server_info_name: str = ""
    # close connections with no READ/WRITE activity for this many
    # seconds (reference server.h idle_timeout_sec semantics: a handler
    # still computing counts as idle — size this above your slowest
    # handler); -1 = never
    idle_timeout_s: int = -1
    # when >= 0: builtin/admin pages are served ONLY on this extra TCP
    # port, and the public port refuses them (reference server.h
    # internal_port — keeps /flags, /pprof etc. off the service VIP)
    internal_port: int = -1
    concurrency_limiter: str = ""       # "", "constant", "auto", "timeout"
    # Run user handlers directly on the delivering thread for loopback/ici
    # transports (the reference's default runs usercode in the IO bthread;
    # its usercode_in_pthread flag is the inverse).  Minimal latency; only
    # safe when handlers are fast/non-blocking.
    usercode_inline: bool = False
    # The reference's usercode_in_pthread analogue: run user handlers on
    # a dedicated backup THREAD pool instead of scheduler workers.  The
    # scheduler compensates for workers parked in butexes, but a
    # CPU-BOUND (GIL-holding) handler never parks — enough of them
    # occupy every worker and stall unrelated sockets' reads (the
    # docs/en/io.md hazard).  With the pool, scheduler workers only
    # parse/dispatch and stay available no matter what usercode does.
    usercode_in_pthread: bool = False
    usercode_backup_threads: int = 8
    # Isolation backend for the backup pool (rpc/usercode_pool.py,
    # ROADMAP 4c): "auto" uses subinterpreter workers when the
    # interpreter supports them (free-threading builds scale on plain
    # threads), "pthread" pins the plain backup pool (byte-identical to
    # the pre-pool behavior), "subinterp" requires isolation and raises
    # when unavailable.  Only REGISTERED isolated handlers
    # (Server.register_isolated) run isolated; regular handlers always
    # use the backup threads.
    usercode_pool_kind: str = "auto"
    ssl_context: Any = None             # ssl.SSLContext for TLS listeners
    # per-RPC session data: factory() -> object, pooled across requests
    # (reference server.h:146-150 session_local_data_factory; reached via
    # Controller.session_local_data() inside handlers)
    session_local_data_factory: Any = None
    # per-worker-thread data: factory() -> object (server.h
    # thread_local_data_factory; reached via Server.thread_local_data())
    thread_local_data_factory: Any = None
    # restful mappings (reference restful.cpp): url path -> method
    #   {"/v1/echo": "EchoService.Echo"}
    restful_mappings: Dict[str, str] = field(default_factory=dict)
    # ici:// servers also open the native-datapath front door (the C++
    # plane in native/rpc.cpp; in-process channels prefer it).  The Python
    # IciListener stays registered either way — it serves fabric peers and
    # non-tpu_std protocols.  Disable to force the pure-Python plane.
    native_ici: bool = True
    # Lame-duck drain window applied by stop() when no explicit grace is
    # passed (reference Server::Stop(closewait_ms)): listeners close and
    # the server flips to draining — /health reports it, GOODBYE goes out
    # on fabric/ici sockets, new requests bounce with retryable ELOGOFF —
    # then in-flight handlers, open streams, queued usercode, and posted
    # device-plane transfers get this many seconds to complete before
    # stragglers are failed.  0 = the historical immediate stop.
    graceful_shutdown_s: float = 0.0
    # Install a process-wide SIGTERM hook that drains this server with
    # graceful_shutdown_s before the process exits (reference
    # -graceful_quit_on_sigterm): a deploy's TERM becomes invisible to
    # callers.  A second TERM during the drain kills immediately.
    graceful_quit_on_sigterm: bool = False
    # Overload admission control (rpc/admission.py): priority/deadline-
    # aware shed-before-queue with per-tenant weighted fair queueing in
    # front of the usercode pool, on all three call planes.  True uses
    # AdmissionOptions defaults; pass an AdmissionOptions to tune bands,
    # queue bound, and tenant weights.  None/False keeps the historical
    # reject-at-gate behavior.
    admission: Any = None


class Server:
    def __init__(self, options: Optional[ServerOptions] = None):
        self.options = options or ServerOptions()
        self._services: Dict[str, Service] = {}
        self._methods: Dict[str, MethodDescriptor] = {}
        self._method_status: Dict[str, MethodStatus] = {}
        self._started = False
        self._listen_endpoints: List[EndPoint] = []
        self._mem_listener = None
        self._acceptor = None
        self.messenger = InputMessenger(server=self)
        self._server_concurrency = 0
        self._usercode_queued = 0        # queued/running backup-pool work
        self._conc_lock = threading.Lock()
        self._stopped = threading.Event()
        self._draining = False
        self._stop_lock = threading.Lock()
        self._stop_in_progress = False
        self._stopping_thread: Optional[threading.Thread] = None
        self._reaper_thread: Optional[threading.Thread] = None
        self.version = ""
        self._connections: List[Any] = []
        self._conn_lock = threading.Lock()
        self._session_data_pool: List[Any] = []
        self._session_data_lock = threading.Lock()
        self._thread_local = threading.local()
        self.usercode_pool = None        # usercode_in_pthread backup pool
        self._isolated: Dict[str, tuple] = {}   # full -> (src, att_mode)
        self.admission = None            # AdmissionController when enabled
        self._collective_regs: List[str] = []   # register_collective names
        self._collective_served: List[int] = []  # devices marked serving

    # ---- registry -----------------------------------------------------
    def register_isolated(self, method_full_name: str, src: str,
                          att: str = "echo") -> None:
        """Register a method served by the ISOLATED usercode pool
        (rpc/usercode_pool.py): ``src`` is handler SOURCE defining
        ``handle(payload: bytes) -> bytes`` — the request payload
        crosses as bytes, the return value is the serialized response
        payload, and nothing else crosses (the share-nothing contract;
        the pool refuses anything else with a TypeError).  ``att``
        says what happens to a parked request-attachment handle:
        "echo" passes it through to the response (the zero-copy
        shape), "drop" disposes it.  Requires
        ``usercode_in_pthread=True``; without isolation support the
        handler still runs (on the backup threads — the capability
        fallback), just without GIL-free scaling."""
        if att not in ("echo", "drop"):
            raise ValueError(f"unknown isolated att mode {att!r}")
        if self._started and not self.options.usercode_in_pthread:
            # without the pool the method has no dispatch route at all:
            # callers would get a misleading ENOMETHOD
            raise ValueError(
                "register_isolated requires usercode_in_pthread=True "
                "(isolated methods dispatch through the usercode pool)")
        self._isolated[method_full_name] = (src, att)
        if self.usercode_pool is not None:
            self.usercode_pool.register(method_full_name, src)

    def register_collective(self, method_full_name: str, handler,
                            merge: str = "gather", mapping: str = "shard",
                            takes_index: bool = False) -> None:
        """Attach a DEVICE-SIDE handler body to a served method: the
        compiled fan-out plane (channels/collective_fanout.py) runs it
        as one shard of the single SPMD program a Parallel/Partition
        call lowers to, with ``merge``/``mapping`` the collective
        contract the client's merger/mapper must match.  The normal
        (wire) service method stays the fallback body — the per-member
        RPC loop any degrade completes on.  When this server starts on
        ``ici://k``, device k advertises the capability (and the pod
        record carries it to remote members)."""
        from ..channels import collective_fanout as _cf
        _cf.register_device_handler(method_full_name, handler,
                                    merge=merge, mapping=mapping,
                                    takes_index=takes_index)
        self._collective_regs.append(method_full_name)
        if self._started:
            for ep in self._listen_endpoints:
                if ep.scheme == "ici" \
                        and ep.device_id not in self._collective_served:
                    _cf.registry().serve(ep.device_id)
                    self._collective_served.append(ep.device_id)

    def add_service(self, svc) -> int:
        if self._started:
            raise RuntimeError("cannot add service after start")
        # RedisService / ThriftService dispatchers register as connection-
        # level protocol handlers (duck-typed to avoid policy import cycles)
        if hasattr(svc, "dispatch") and hasattr(svc, "add_handler"):
            self.redis_service = svc
            return 0
        if hasattr(svc, "handle") and hasattr(svc, "add_method"):
            self.thrift_service = svc
            return 0
        if getattr(svc, "SERVICE_NAME", None) == "mongo" and \
                hasattr(svc, "process"):
            self._mongo_service = svc
            return 0
        # NsheadService / adaptors (nova, public_pbrpc, ubrpc ride on this):
        # exactly one may own the connection's nshead frames
        if getattr(svc, "SERVICE_NAME", None) == "nshead" and \
                hasattr(svc, "process_nshead_request"):
            if getattr(self, "_nshead_service", None) is not None:
                return errors.EINVAL
            self._nshead_service = svc
            return 0
        # RtmpService: per-connection stream factory (rtmp.h RtmpService);
        # the rtmp protocol only claims connections when one is registered
        if getattr(svc, "SERVICE_NAME", None) == "rtmp" and \
                hasattr(svc, "new_stream"):
            if getattr(self, "_rtmp_service", None) is not None:
                return errors.EINVAL
            self._rtmp_service = svc
            return 0
        # EspService raw handler (same single-owner rule)
        if getattr(svc, "SERVICE_NAME", None) == "esp" and \
                hasattr(svc, "process_esp_request"):
            if getattr(self, "_esp_service", None) is not None:
                return errors.EINVAL
            self._esp_service = svc
            return 0
        name = svc.service_name()
        if name in self._services:
            return errors.EINVAL
        self._services[name] = svc
        for mname, md in svc.methods().items():
            self._methods[md.full_name] = md
            limiter = self._make_limiter(md.full_name)
            self._method_status[md.full_name] = MethodStatus(md.full_name,
                                                             limiter)
        return 0

    def _make_limiter(self, full_name: str):
        mc = self.options.method_max_concurrency.get(full_name)
        kind = self.options.concurrency_limiter
        from ..policy import limiters
        if isinstance(mc, int) and mc > 0:
            return limiters.ConstantConcurrencyLimiter(mc)
        if mc == "auto" or kind == "auto":
            return limiters.AutoConcurrencyLimiter()
        if kind == "timeout":
            return limiters.TimeoutConcurrencyLimiter()
        if kind == "constant" and self.options.max_concurrency > 0:
            return limiters.ConstantConcurrencyLimiter(
                self.options.max_concurrency)
        return None

    def find_method(self, full_name: str) -> Optional[MethodDescriptor]:
        return self._methods.get(full_name)

    def method_status(self, full_name: str) -> Optional[MethodStatus]:
        return self._method_status.get(full_name)

    def services(self) -> Dict[str, Service]:
        return dict(self._services)

    def method_statuses(self) -> List[MethodStatus]:
        return list(self._method_status.values())

    # ---- server-level concurrency (reference max_concurrency) ---------
    def on_request_in(self) -> bool:
        mc = self.options.max_concurrency
        with self._conc_lock:
            if mc > 0 and self._server_concurrency >= mc:
                return False
            self._server_concurrency += 1
            return True

    def on_request_out(self) -> None:
        with self._conc_lock:
            self._server_concurrency -= 1
        adm = self.admission
        if adm is not None:
            # a slot just freed: the admission queue's release pump
            # (records a service-rate sample and dispatches the next
            # queued request off this thread)
            adm.on_release()

    def on_request_rollback(self) -> None:
        """Undo on_request_in for a request that was never admitted (the
        method gate refused after the server gate passed).  Unlike
        on_request_out this does NOT pump the admission queue or record
        a service-rate sample: a rollback is not a completion — pumping
        here would recurse (pump → gate → rollback → pump) and the
        microsecond-spaced phantom 'releases' would inflate the observed
        service rate, collapsing retry_after_ms into the synchronized
        retry storm it exists to prevent."""
        with self._conc_lock:
            self._server_concurrency -= 1

    # usercode_in_pthread backlog accounting (InputMessenger): a request
    # QUEUED on the backup pool has not yet passed on_request_in, so the
    # drain gate needs its own counter to see it
    def on_usercode_queued(self) -> None:
        with self._conc_lock:
            self._usercode_queued += 1

    def on_usercode_done(self) -> None:
        with self._conc_lock:
            self._usercode_queued -= 1

    def inflight_requests(self) -> int:
        """Requests currently admitted or queued — the drain/join gate
        and the /status count.  One request can appear in several
        counters (tpu_std increments both the server and its method's
        concurrency; a pooled request is also in the usercode backlog
        while running), so the counters are combined with max(): still
        zero exactly when everything finished, without double-counting a
        single request as 2-3 on /status."""
        with self._conc_lock:
            server_n = self._server_concurrency
            queued_n = self._usercode_queued
        method_n = sum(ms.concurrency
                       for ms in self._method_status.values())
        return max(server_n, method_n, queued_n)

    # ---- per-RPC / per-thread user data (server.h:126-150) ------------
    def _get_session_data(self) -> Any:
        if self.options.session_local_data_factory is None:
            return None
        with self._session_data_lock:
            if self._session_data_pool:
                return self._session_data_pool.pop()
        return self.options.session_local_data_factory()

    def _return_session_data(self, data: Any) -> None:
        if data is None:
            return
        with self._session_data_lock:
            if len(self._session_data_pool) < 1024:
                self._session_data_pool.append(data)

    def thread_local_data(self) -> Any:
        """Data attached to the calling worker thread, created on first
        use by options.thread_local_data_factory."""
        factory = self.options.thread_local_data_factory
        if factory is None:
            return None
        data = getattr(self._thread_local, "data", None)
        if data is None:
            data = self._thread_local.data = factory()
        return data

    # ---- lifecycle ----------------------------------------------------
    def start(self, addr: Any = None, options: Optional[ServerOptions] = None) -> int:
        if options is not None:
            self.options = options
        if self._started:
            return errors.EINVAL
        # restartable after stop(): a FRESH event per run is the idle
        # reaper's generation guard — the old reaper holds the prior
        # run's (set) event and exits, instead of surviving a fast
        # stop()->start() cycle that cleared the shared flag before it
        # woke (which left two reapers running)
        self._stopped = threading.Event()
        self._draining = False
        self._listen_endpoints = []     # fresh run, fresh addresses
        if self._isolated and not self.options.usercode_in_pthread:
            # isolated methods only have a dispatch route through the
            # usercode pool; starting without it would answer them
            # with a misleading ENOMETHOD
            raise ValueError(
                "register_isolated requires usercode_in_pthread=True "
                "(isolated methods dispatch through the usercode pool)")
        with self._conn_lock:
            self._connections = []
        if self.options.usercode_in_pthread and self.usercode_pool is None:
            from .usercode_pool import UsercodePool
            self.usercode_pool = UsercodePool(
                kind=self.options.usercode_pool_kind,
                workers=max(self.options.usercode_backup_threads, 1))
            for full, (src, _att) in self._isolated.items():
                self.usercode_pool.register(full, src)
        if self.options.admission:
            from .admission import AdmissionController, AdmissionOptions
            if self.admission is None:
                aopts = self.options.admission if isinstance(
                    self.options.admission, AdmissionOptions) else None
                self.admission = AdmissionController(self, aopts)
            else:
                self.admission.reset()   # restart lifts the stop refusal
        if self.options.enable_builtin_services:
            from .builtin import register_builtin_services
            register_builtin_services(self)
        if addr is None:
            addr = "mem://server"
        if isinstance(addr, int):
            ep = EndPoint(scheme=SCHEME_TCP, host="0.0.0.0", port=addr)
        elif isinstance(addr, str):
            # A port-less bare name is unambiguous on the LISTEN side (you
            # can't listen on tcp without a port), so any such name — even
            # dotted or all-digits ones parse_endpoint would reject as
            # probable client-side typos — is an in-process registry.
            if ":" not in addr and "://" not in addr:
                addr = "mem://" + addr
            ep = parse_endpoint(addr)
        else:
            ep = addr
        if ep.scheme == SCHEME_MEM:
            from .mem_transport import mem_listen
            self._mem_listener = mem_listen(ep.host, self._on_accept)
            # loopback fast plane: in-process tpu_std channels dispatch
            # straight into this server's method table (loopback.py)
            from . import loopback
            loopback.register_server(ep.host, self)
        elif ep.scheme == SCHEME_TCP:
            from .tcp_transport import Acceptor
            self._acceptor = Acceptor(self._on_accept,
                                      ssl_context=self.options.ssl_context)
            port = self._acceptor.start(ep.host or "0.0.0.0", ep.port)
            ep = EndPoint(scheme=SCHEME_TCP, host=ep.host or "0.0.0.0",
                          port=port)
        elif ep.scheme == "ici":
            from ..ici.transport import ici_listen
            self._ici_listener = ici_listen(ep.device_id, self._on_accept)
            if self.options.native_ici:
                try:
                    from ..ici import native_plane
                    if native_plane.available():
                        self._native_ici = native_plane.ServerBinding(
                            self, ep.device_id)
                except Exception as e:   # native plane is an accelerator,
                    log.warning(         # not a requirement
                        "native ici plane unavailable (%s); "
                        "Python datapath only", e)
        else:
            raise ValueError(f"cannot listen on scheme {ep.scheme}")
        try:
            if self.options.internal_port >= 0:
                from .tcp_transport import Acceptor
                # same bind address and TLS posture as the main listener:
                # a loopback-restricted service must not grow a
                # world-reachable plaintext admin port
                self._internal_acceptor = Acceptor(
                    self._on_accept_internal,
                    ssl_context=self.options.ssl_context)
                # same bind host as a TCP main listener; for mem://
                # and ici:// servers (no network host) the admin port
                # stays on loopback — never a surprise 0.0.0.0 listener
                host = ep.host if ep.scheme == SCHEME_TCP and ep.host \
                    else "127.0.0.1"
                self._internal_port = self._internal_acceptor.start(
                    host, self.options.internal_port)
            if self.options.idle_timeout_s > 0:
                self._start_idle_reaper()
        except Exception:
            # a half-started server must not leak its live listeners: a
            # retry of start() would otherwise double-bind
            self._teardown_listeners()
            raise
        self._listen_endpoints.append(ep)
        self._started = True
        from . import lameduck
        lameduck.clear_local_draining(ep)   # restart lifts the drain mark
        try:
            # pod membership: a joined pod advertises the serving device
            # (epoch bump); no-op for non-ici servers / no pod
            from ..ici import pod as _pod
            _pod.on_server_started(ep)
        except Exception:
            pass
        if self._collective_regs and ep.scheme == "ici" \
                and ep.device_id not in self._collective_served:
            # compiled fan-out capability: this device serves the
            # registered device handlers (epoch bump — a degraded
            # collective route re-probes on the revival advertise)
            from ..channels import collective_fanout as _cf
            _cf.registry().serve(ep.device_id)
            self._collective_served.append(ep.device_id)
        if self.options.graceful_quit_on_sigterm:
            if not lameduck.enable_graceful_quit(self):
                # the hook only installs from the main thread — the
                # operator must know deploys will NOT drain
                log.warning(
                    "graceful_quit_on_sigterm requested but the SIGTERM "
                    "hook could not be installed (server started off "
                    "the main thread): TERM will not drain this server")
        log.info("Server started on %s with %d services", ep,
                 len(self._services))
        # version ping, off unless the trackme_server flag is set
        # (reference server.cpp StartInternal → trackme.cpp:36)
        from .trackme import start_trackme
        start_trackme(str(ep))
        return 0

    def _on_accept(self, sock) -> None:
        sock.messenger = self.messenger
        sock.usercode_inline = self.options.usercode_inline
        with self._conn_lock:
            self._connections = [s for s in self._connections if not s.failed]
            self._connections.append(sock)

    def _on_accept_internal(self, sock) -> None:
        sock.internal_only = True       # admin pages only (http checks)
        self._on_accept(sock)

    @property
    def internal_port(self) -> int:
        return getattr(self, "_internal_port", -1)

    def _start_idle_reaper(self) -> None:
        # bind THIS run's stop event: the reaper's generation guard (see
        # start() — each run gets a fresh event, so a reaper from a
        # previous run observes its own set event and exits even when a
        # new run is already up)
        stopped = self._stopped

        def reap() -> None:
            period = max(0.5, self.options.idle_timeout_s / 2.0)
            while not stopped.wait(period):
                cutoff = _time.monotonic() - self.options.idle_timeout_s
                with self._conn_lock:
                    conns = list(self._connections)
                for s in conns:
                    if getattr(s, "last_active", cutoff + 1) <= cutoff:
                        s.set_failed(errors.ECLOSE,
                                     f"idle > {self.options.idle_timeout_s}s")

        t = threading.Thread(target=reap, name="idle_reaper", daemon=True)
        self._reaper_thread = t
        t.start()

    @property
    def listen_endpoint(self) -> Optional[EndPoint]:
        return self._listen_endpoints[0] if self._listen_endpoints else None

    @property
    def listen_port(self) -> int:
        ep = self.listen_endpoint
        return ep.port if ep else 0

    def is_running(self) -> bool:
        return self._started and not self._stopped.is_set()

    def is_draining(self) -> bool:
        """Lame-duck state: listeners are closed and new requests bounce
        with retryable ELOGOFF while in-flight work completes."""
        return self._draining

    def _teardown_listeners(self, keep_native: bool = False) -> None:
        if self._mem_listener is not None:
            from .mem_transport import mem_unlisten
            from . import loopback
            mem_unlisten(self._mem_listener.name)
            if keep_native:
                # lame-duck drain: the loopback front door stays open so
                # in-process callers get the retryable ELOGOFF bounce
                # (mirrors the native ici door below); phase-2 teardown
                # unregisters it
                self._drain_loopback_name = self._mem_listener.name
            else:
                loopback.unregister_server(self._mem_listener.name, self)
            self._mem_listener = None
        if not keep_native and getattr(self, "_drain_loopback_name", None):
            from . import loopback
            loopback.unregister_server(self._drain_loopback_name, self)
            self._drain_loopback_name = None
        if self._acceptor is not None:
            self._acceptor.stop()
            self._acceptor = None
        if getattr(self, "_internal_acceptor", None) is not None:
            self._internal_acceptor.stop()
            self._internal_acceptor = None
        if getattr(self, "_ici_listener", None) is not None:
            from ..ici.transport import ici_unlisten
            ici_unlisten(self._ici_listener.device_id)
            self._ici_listener = None
        if not keep_native and getattr(self, "_native_ici", None) is not None:
            # during a lame-duck drain the native front door stays up so
            # in-flight native calls complete (new ones bounce ELOGOFF in
            # ServerBinding._process); phase-2 teardown closes it
            self._native_ici.stop()
            self._native_ici = None

    def stop(self, grace_s: Optional[float] = None) -> int:
        """Stop the server.  ``grace_s > 0`` (default: ``ServerOptions.
        graceful_shutdown_s``) drains first — lame-duck mode (reference
        Server::Stop(closewait_ms)):

          1. listeners close and the server flips to *draining*: /health
             reports it, the mesh:// naming source drops the endpoint,
             fabric/ici sockets send GOODBYE so peers pull the endpoint
             from their LBs proactively, and NEW requests on still-open
             connections bounce with retryable ELOGOFF;
          2. in-flight handlers, queued usercode, open streams, and
             posted device-plane transfers complete inside the grace
             window (pins release at completion — never leaked);
          3. only stragglers past the window are failed: streams get a
             flush + orderly CLOSE instead of a RST, connections fail
             with ELOGOFF, and unmatched device-plane sends are failed
             so their pins release.
        """
        if grace_s is None:
            grace_s = self.options.graceful_shutdown_s or 0.0
        with self._stop_lock:
            if not self._started:
                return 0
            if self._stop_in_progress:
                # another thread is mid-drain: WAIT for it rather than
                # return success on a server that is still half-up (the
                # caller would rebind the port / exit the process under
                # the live drain).  Reentrancy (stop from a thread the
                # drain itself runs) just returns.
                stopping, stopped = self._stopping_thread, self._stopped
            else:
                self._stop_in_progress = True
                self._stopping_thread = threading.current_thread()
                stopping = None
        if stopping is not None:
            if stopping is not threading.current_thread():
                stopped.wait()
            return 0
        try:
            self._stop_locked(grace_s)
        finally:
            with self._stop_lock:
                self._stop_in_progress = False
                self._stopping_thread = None
            if not self._stopped.is_set():
                # _stop_locked raised midway: the error propagates to
                # THIS caller, but concurrent stop() callers parked on
                # the event and join() must still unblock — a failed
                # stop may leave debris, never a wedged process
                self._draining = False
                self._started = False
                self._stopped.set()
        return 0

    def _stop_locked(self, grace_s: float) -> None:
        from . import lameduck
        drained = True
        if grace_s > 0:
            # the local drain mark lives ONLY for the drain window: it
            # pulls the endpoint from mesh:// membership while in-flight
            # work completes.  Once the server is fully stopped, liveness
            # is the health checker's concern again (and the GOODBYE
            # peer-side mark persists until revival) — a lasting local
            # mark would make topology-derived membership lie forever
            # about an endpoint nothing is draining.
            self._draining = True
            drain_start_ns = _time.monotonic_ns()
            for ep in self._listen_endpoints:
                lameduck.mark_local_draining(ep)
                try:
                    # pod membership drain mark: pod:// naming drops the
                    # device even for processes holding no socket to us
                    # (the GOODBYE signal generalized)
                    from ..ici import pod as _pod
                    _pod.on_server_draining(ep)
                except Exception:
                    pass
            if self.admission is not None:
                # queued-not-started admission entries bounce with
                # retryable ELOGOFF at drain start (the PR-8 batch-queue
                # discipline): callers fail over instantly instead of
                # waiting out a grace window they may not survive
                self.admission.fail_all(errors.ELOGOFF,
                                        "server is draining (lame duck)")
            self._teardown_listeners(keep_native=True)
            self._send_goodbyes()
            drained = self._drain_until(_time.monotonic() + grace_s)
        if self.admission is not None:
            self.admission.fail_all(errors.ELOGOFF, "server stopping")
        self._teardown_listeners()
        with self._conn_lock:
            conns = list(self._connections)
        if grace_s > 0:
            # stragglers past the window: an orderly CLOSE (flushed on
            # the still-live connection) instead of the RST the socket
            # failure below would imply
            self._close_server_streams(conns)
            if not drained:
                self._fail_pending_device_transfers(drain_start_ns)
        # loopback stragglers (past the grace window, or any in-flight on
        # an immediate stop) fail exactly like the wire connections
        # below: claimed with retryable ELOGOFF, the still-running
        # handler's late done() is dropped
        from . import loopback
        loopback.fail_inflight(self, errors.ELOGOFF, "server stopping")
        for s in conns:
            # graceful h2 shutdown: GOAWAY first so the peer knows which
            # streams were processed and retries the rest safely
            if getattr(s, "_h2_conn", None) is not None:
                try:
                    from ..policy.grpc import send_goaway
                    send_goaway(s)
                except Exception:
                    pass
            s.set_failed(errors.ELOGOFF, "server stopping")
        # deterministic shutdown ordering: fabric reader threads are
        # quiesced here, not left to race interpreter/static teardown
        for s in conns:
            q = getattr(s, "quiesce_reader", None)
            if q is not None:
                try:
                    q(0.5)
                except Exception:
                    pass
        pool, self.usercode_pool = self.usercode_pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        reaper, self._reaper_thread = self._reaper_thread, None
        self._stopped.set()
        if reaper is not None and reaper is not threading.current_thread():
            reaper.join(1.0)         # woken by the event: prompt exit
        self._started = False
        self._draining = False
        for ep in self._listen_endpoints:
            lameduck.clear_local_draining(ep)
            try:
                from ..ici import pod as _pod
                _pod.on_server_stopped(ep)
            except Exception:
                pass
        if self._collective_served:
            from ..channels import collective_fanout as _cf
            served, self._collective_served = self._collective_served, []
            for dev in served:
                _cf.registry().withdraw(dev)

    # ---- drain machinery ----------------------------------------------
    def _send_goodbyes(self) -> None:
        """Proactive lame-duck notification on every connection whose
        transport supports it (fabric control frame / in-process ici):
        peers pull this endpoint from their LBs NOW instead of at the
        next health-check probe."""
        with self._conn_lock:
            conns = list(self._connections)
        for s in conns:
            fn = getattr(s, "send_goodbye", None)
            if fn is not None:
                try:
                    fn()
                except Exception:
                    pass

    def _drain_until(self, deadline: float) -> bool:
        """Block until in-flight handlers, queued usercode, open streams,
        and posted device-plane transfers are all done, or the deadline
        passes.  Returns True when fully drained."""
        while True:
            if (self.inflight_requests() == 0
                    and not self._open_server_streams()
                    and self._device_plane_active() == 0):
                return True
            if _time.monotonic() >= deadline:
                return False
            _time.sleep(0.005)

    def _open_server_streams(self) -> List[Any]:
        try:
            from .stream import live_streams
        except Exception:
            return []
        with self._conn_lock:
            conns = {id(s) for s in self._connections if not s.failed}
        return [st for st in live_streams()
                if not st.closed and st.socket is not None
                and id(st.socket) in conns]

    def _close_server_streams(self, conns: List[Any]) -> None:
        conn_ids = {id(s) for s in conns if not s.failed}
        try:
            from .stream import live_streams
        except Exception:
            return
        for st in live_streams():
            if not st.closed and st.socket is not None \
                    and id(st.socket) in conn_ids:
                try:
                    st.close()
                except Exception:
                    pass

    @staticmethod
    def _device_plane_active() -> int:
        """Posted-but-incomplete device-plane transfers in this process;
        0 when the plane was never instantiated (no import side effects
        for pure-TCP servers)."""
        try:
            from ..ici.device_plane import DevicePlane
        except Exception:
            return 0
        plane = DevicePlane._instance
        return plane.active_transfers() if plane is not None else 0

    @staticmethod
    def _fail_pending_device_transfers(posted_before_ns: int) -> None:
        """Grace expired with transfers still posted: fail the ones that
        were already posted when the drain began (and so sat unmatched
        through the whole window) so completions fire and source pins
        release — a lame-duck stop may strand a straggler RPC, never an
        HBM pin.  Newer posts belong to other live traffic in this
        process and are left to their own lifecycle."""
        try:
            from ..ici.device_plane import DevicePlane
        except Exception:
            return
        plane = DevicePlane._instance
        if plane is not None:
            plane.fail_pending("server stopped before rendezvous "
                               "(lame-duck grace expired)",
                               posted_before_ns=posted_before_ns)

    def join(self, timeout: Optional[float] = None) -> None:
        """Block until the server has stopped AND its in-flight handlers
        have finished — not just until the stop flag flipped (reference
        Server::Join runs after Stop's close-wait)."""
        deadline = None if timeout is None else _time.monotonic() + timeout
        if not self._stopped.wait(timeout):
            return
        while self.inflight_requests() > 0:
            if deadline is not None and _time.monotonic() >= deadline:
                return
            _time.sleep(0.002)

    def connections(self) -> List[Any]:
        with self._conn_lock:
            return [s for s in self._connections if not s.failed]
