"""Compression registry (reference: src/brpc/compress.{h,cpp} + policy
gzip/snappy).  Types: 0=none, 1=gzip, 2=zlib (the snappy slot — snappy
itself isn't in the image, zlib-raw fills the fast-codec role)."""
from __future__ import annotations

import gzip as _gzip
import zlib as _zlib
from typing import Callable, Dict, Tuple

COMPRESS_TYPE_NONE = 0
COMPRESS_TYPE_GZIP = 1
COMPRESS_TYPE_ZLIB = 2

_codecs: Dict[int, Tuple[Callable[[bytes], bytes], Callable[[bytes], bytes]]] = {
    COMPRESS_TYPE_GZIP: (_gzip.compress, _gzip.decompress),
    COMPRESS_TYPE_ZLIB: (_zlib.compress, _zlib.decompress),
}


def register_compression(ctype: int, compressor, decompressor) -> None:
    _codecs[ctype] = (compressor, decompressor)


def compress(ctype: int, data: bytes) -> bytes:
    if ctype == COMPRESS_TYPE_NONE:
        return data
    try:
        return _codecs[ctype][0](data)
    except KeyError:
        raise ValueError(f"unknown compress_type {ctype}")


def decompress(ctype: int, data: bytes) -> bytes:
    if ctype == COMPRESS_TYPE_NONE:
        return data
    try:
        return _codecs[ctype][1](data)
    except KeyError:
        raise ValueError(f"unknown compress_type {ctype}")
