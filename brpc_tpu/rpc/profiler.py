"""Profiling: CPU hotspots + lock-contention sampling.

Reference: src/brpc/builtin/hotspots_service.cpp (gperftools ProfilerStart /
pprof rendering) and the contention profiler inside src/bthread/mutex.cpp:
107-313 (lock-wait edges sampled through the bvar Collector).

TPU build equivalents:
  * CPU hotspots: stdlib cProfile driven start/stop, rendered as pprof-ish
    text (callers sorted by cumulative time) — served by /hotspots with
    ?seconds=N.
  * Contention: ``ContentionMutex`` wraps a lock; acquisition waits above a
    microsecond floor are sampled (speed-limited) with the blocking call
    site, aggregated into a contention profile — the exact mechanism of the
    reference's bthread_mutex hook.
  * Device hotspots: jax profiler hooks (trace to a dir) when available —
    the piece CPU-only bRPC has no analogue for.
"""
from __future__ import annotations

import cProfile
import io
import pstats
import threading
import time
import traceback
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from .. import bvar

# ---- CPU hotspots -----------------------------------------------------

_profile_lock = threading.Lock()


def profile_for(seconds: float = 1.0, top: int = 40) -> str:
    """Profile the whole process for ``seconds`` and render hotspots."""
    with _profile_lock:
        pr = cProfile.Profile()
        pr.enable()
        # the sleep IS the sampled window; the lock exists precisely to
        # serialize concurrent profilers over process-global cProfile
        # state, so holding it across the window is the point
        time.sleep(seconds)  # fablint: ignore[blocking-under-lock] the lock serializes the process-global profiler; the sleep is the sampling window itself
        pr.disable()
    out = io.StringIO()
    stats = pstats.Stats(pr, stream=out)
    stats.sort_stats("cumulative").print_stats(top)
    return out.getvalue()


def profile_call(fn, *args, top: int = 40, **kwargs) -> Tuple[object, str]:
    pr = cProfile.Profile()
    pr.enable()
    try:
        result = fn(*args, **kwargs)
    finally:
        pr.disable()
    out = io.StringIO()
    pstats.Stats(pr, stream=out).sort_stats("cumulative").print_stats(top)
    return result, out.getvalue()


# ---- contention profiler ---------------------------------------------

_contention_enabled = False
_contention_limit = bvar.CollectorSpeedLimit(max_samples_per_second=200)
_contention_lock = threading.Lock()
_contention_samples: Dict[str, List[float]] = defaultdict(list)
contention_sample_count = bvar.Adder("lock_contention_samples")

CONTENTION_FLOOR_US = 50        # waits shorter than this are never sampled


def enable_contention_profiler(enabled: bool = True) -> None:
    global _contention_enabled
    _contention_enabled = enabled
    if not enabled:
        with _contention_lock:
            _contention_samples.clear()


def contention_profile() -> List[Tuple[str, int, float]]:
    """(call_site, samples, total_wait_s) sorted by total wait."""
    with _contention_lock:
        rows = [(site, len(waits), sum(waits))
                for site, waits in _contention_samples.items()]
    return sorted(rows, key=lambda r: -r[2])


def _record_contention(wait_s: float) -> None:
    if not _contention_limit.is_sampled():
        return
    # the blocking call site: skip our own frames
    stack = traceback.extract_stack(limit=6)
    site = "?"
    for frame in reversed(stack):
        if "profiler.py" not in frame.filename:
            site = f"{frame.filename}:{frame.lineno} {frame.name}"
            break
    with _contention_lock:
        _contention_samples[site].append(wait_s)
    contention_sample_count << 1


class ContentionMutex:
    """A mutex whose contended acquisitions feed the contention profiler
    (reference bthread_mutex with g_cp sampling, mutex.cpp:107)."""

    def __init__(self):
        self._lock = threading.Lock()

    def acquire(self, timeout: Optional[float] = None) -> bool:
        if self._lock.acquire(blocking=False):
            return True
        t0 = time.monotonic()
        ok = self._lock.acquire(timeout=timeout if timeout is not None else -1)
        wait = time.monotonic() - t0
        if _contention_enabled and wait * 1e6 >= CONTENTION_FLOOR_US:
            _record_contention(wait)
        return ok

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "ContentionMutex":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


# ---- device profiling (jax tracer) ------------------------------------

def start_device_trace(log_dir: str) -> bool:
    try:
        import jax
        jax.profiler.start_trace(log_dir)
        return True
    except Exception:
        return False


def stop_device_trace() -> bool:
    try:
        import jax
        jax.profiler.stop_trace()
        return True
    except Exception:
        return False
