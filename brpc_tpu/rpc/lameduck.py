"""Lame-duck registry: which endpoints are draining, and who must know.

Reference: ``Server::Stop(closewait_ms)``/``Join`` plus the
``-graceful_quit_on_sigterm`` doctrine (src/brpc/server.cpp,
docs/cn/server.md "优雅退出"): a *planned* shutdown is not a crash — the
server first flips to draining so every discovery surface pulls the
endpoint, then in-flight work completes inside a grace window, and only
stragglers are failed.

Two marks live here, both keyed by EndPoint:

  * **local** — a server in THIS process called ``stop(grace_s)`` and is
    draining (or has finished draining and not restarted).  Consulted by
    the ``mesh://`` naming service so topology-derived membership drops
    the endpoint immediately, and by ``/health`` via the owning server.
  * **peer** — a remote peer told us it is draining via the fabric/ici
    ``GOODBYE`` control frame.  Registering a peer mark *proactively*
    pulls the endpoint from every live load balancer (no probe-timeout
    wait — the point of GOODBYE) and hands it to the health checker,
    whose successful probe after the peer's restart clears the mark and
    re-admits the endpoint everywhere.
"""
from __future__ import annotations

import signal
import threading
import time
import weakref
from typing import Dict, List

from ..butil import logging as log
from ..butil.endpoint import EndPoint

_lock = threading.Lock()
_local: Dict[EndPoint, float] = {}      # ep -> drain start (monotonic)
_peer: Dict[EndPoint, float] = {}       # ep -> GOODBYE receipt (monotonic)


# ---- local (this process's servers) -----------------------------------

def mark_local_draining(ep: EndPoint) -> None:
    with _lock:
        _local[ep] = time.monotonic()


def clear_local_draining(ep: EndPoint) -> None:
    with _lock:
        _local.pop(ep, None)


def local_draining() -> List[EndPoint]:
    with _lock:
        return list(_local)


# ---- peer (GOODBYE senders) -------------------------------------------

def notify_peer_draining(ep: EndPoint) -> bool:
    """A peer announced it is draining (GOODBYE).  Pull ``ep`` from every
    live load balancer NOW — before any health-check probe could have
    noticed — and register for revival.  Idempotent (GOODBYE may arrive
    on several sockets to the same server); returns True on the first
    registration."""
    with _lock:
        if ep in _peer:
            return False
        _peer[ep] = time.monotonic()
    log.info("lame duck: peer %s draining — pulled from load balancers", ep)
    _exclude_everywhere(ep, float("inf"))
    try:
        from .health_check import start_health_check
        start_health_check(ep, on_revived=_on_peer_revived)
    except Exception:
        pass
    return True


def clear_peer_draining(ep: EndPoint) -> None:
    with _lock:
        _peer.pop(ep, None)
    _exclude_everywhere(ep, 0.0)


def _on_peer_revived(ep: EndPoint) -> None:
    log.info("lame duck: %s revived — re-admitted to load balancers", ep)
    clear_peer_draining(ep)


def _exclude_everywhere(ep: EndPoint, until_ts: float) -> None:
    from ..policy.load_balancers import live_load_balancers
    for lb in live_load_balancers():
        try:
            lb.exclude(ep, until_ts)
        except Exception:
            pass


def is_draining(ep: EndPoint) -> bool:
    with _lock:
        return ep in _local or ep in _peer


# ---- graceful_quit_on_sigterm -----------------------------------------
# One process-wide SIGTERM hook draining every registered server, so a
# deploy's TERM is invisible to callers: the handler flips servers to
# lame-duck (GOODBYE goes out, /health flips, new requests bounce with
# retryable ELOGOFF) and drains them; a main thread blocked in
# Server.join() then unblocks and the process exits on its own.  The
# default disposition is restored afterwards, so a SECOND TERM kills
# immediately (the escalation contract).

_sig_servers: "weakref.WeakSet" = weakref.WeakSet()
_sig_installed = False


def enable_graceful_quit(server) -> bool:
    """Register ``server`` with the process SIGTERM drain hook, installing
    the hook on first use.  Returns False when the handler cannot be
    installed (not the main thread) — the server still drains via an
    explicit ``stop(grace_s)``."""
    global _sig_installed
    with _lock:
        _sig_servers.add(server)
        if _sig_installed:
            return True
        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            return False               # not the main thread
        _sig_installed = True
    return True


def _on_sigterm(signum, frame) -> None:
    # restore default FIRST: a second TERM during a long drain must kill
    # immediately instead of queueing another drain.  NO locks here — a
    # signal handler interrupts the main thread at an arbitrary point,
    # possibly while it holds this module's lock (self-deadlock).
    global _sig_installed
    try:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
    except ValueError:
        pass
    _sig_installed = False
    try:
        servers = list(_sig_servers)
    except RuntimeError:        # registration raced the iteration
        servers = []
    # drain off the signal frame: stop(grace) blocks for the grace window
    t = threading.Thread(target=_drain_servers, args=(servers,),
                         name="graceful_quit", daemon=True)
    t.start()


def _drain_servers(servers) -> None:
    # every server flips to draining IMMEDIATELY (GOODBYE out, /health
    # flipped, ELOGOFF bouncing) — a sequential stop would leave later
    # servers advertising healthy through every earlier server's grace
    # window, and an orchestrator kill-timeout would SIGKILL them
    # mid-traffic; total shutdown is max-of-graces, not sum
    def one(s):
        try:
            grace = getattr(s.options, "graceful_shutdown_s", 0.0) or 0.0
            s.stop(grace)
        except Exception:
            log.error("graceful_quit: drain failed", exc_info=True)

    threads = [threading.Thread(target=one, args=(s,),
                                name="graceful_quit_drain", daemon=True)
               for s in servers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
