"""mem:// loopback transport: in-process socket pairs.

The reference tests all "distributed" behavior against real servers on
localhost TCP in the same process (SURVEY.md §4).  The TPU build adds this
zero-dependency loopback so protocol/flow-control logic is testable anywhere
(and it is the fixture CI uses): a MemSocket pair moves IOBuf bytes through
an in-memory inbox with the same readiness semantics (readable events, EOF
on peer close, EAGAIN when drained) the fd transports have.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from ..butil.iobuf import IOBuf, IOPortal
from ..butil.endpoint import EndPoint, SCHEME_MEM
from . import errors
from .socket import Socket


class MemSocket(Socket):
    def __init__(self, remote_side: Optional[EndPoint] = None):
        super().__init__(remote_side)
        self.peer: Optional["MemSocket"] = None
        self._inbox = IOBuf()
        self._inbox_lock = threading.Lock()
        self._peer_closed = False

    # transport hooks ---------------------------------------------------
    def _do_write(self, data: IOBuf) -> int:
        peer = self.peer
        if peer is None or peer.failed:
            raise ConnectionError("peer closed")
        n = len(data)
        chunk = data.cut(n)
        with peer._inbox_lock:
            peer._inbox.append(chunk)
        # responses (client-side peer) process inline on this thread —
        # framework code, bounded latency; requests (server-side peer)
        # go to a tasklet so user handlers can't block the writer
        inline = (not peer.is_server_side
                  or getattr(peer, "usercode_inline", False))
        peer.start_input_event(inline=inline)
        return n

    def _do_read(self, portal: IOPortal, max_count: int) -> int:
        with self._inbox_lock:
            avail = len(self._inbox)
            if avail == 0:
                return 0 if self._peer_closed else -1
            n = min(avail, max_count)
            self._inbox.cutn(portal, n)
            return n

    def _transport_close(self) -> None:
        peer = self.peer
        if peer is not None and not peer.failed:
            if self.failed_error == errors.ELOGOFF:
                # lame-duck hard stop: the peer's in-flight calls fail
                # with the SERVER'S code (retryable ELOGOFF skips the
                # client's connection-failure backoff) — but only AFTER
                # the peer drained responses already in its inbox, or a
                # completed non-idempotent call would be retried
                # elsewhere (duplicate execution).  The EOF path applies
                # the code (input_messenger).
                peer._eof_error_code = errors.ELOGOFF
            with peer._inbox_lock:
                peer._peer_closed = True
            peer.start_input_event()    # let it observe EOF


def new_mem_pair() -> tuple:
    a, b = MemSocket(), MemSocket()
    a.peer, b.peer = b, a
    return a, b


# ---- listener registry (the "network namespace" for mem://) -----------

_listeners: Dict[str, "MemListener"] = {}
_listeners_lock = threading.Lock()


class MemListener:
    """Server side of mem://name; hands accepted sockets to the server's
    acceptor logic (on_accept(server_socket))."""

    def __init__(self, name: str, on_accept):
        self.name = name
        self.on_accept = on_accept

    def connect(self, client_remote: EndPoint) -> MemSocket:
        client, serv = new_mem_pair()
        client.remote_side = client_remote
        serv.remote_side = EndPoint(scheme=SCHEME_MEM, host=self.name + "#client")
        serv.is_server_side = True
        self.on_accept(serv)
        return client


def mem_listen(name: str, on_accept) -> MemListener:
    with _listeners_lock:
        if name in _listeners:
            raise OSError(errors.EINVAL, f"mem://{name} already listening")
        l = MemListener(name, on_accept)
        _listeners[name] = l
        return l


def mem_unlisten(name: str) -> None:
    with _listeners_lock:
        _listeners.pop(name, None)


def mem_connect(name: str) -> MemSocket:
    with _listeners_lock:
        l = _listeners.get(name)
    if l is None:
        raise ConnectionRefusedError(f"no server at mem://{name}")
    return l.connect(EndPoint(scheme=SCHEME_MEM, host=name))
