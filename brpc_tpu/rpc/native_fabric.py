"""Native-datapath Server/Channel facades.

This is the deployment shape SURVEY.md §7 calls for ("host runtime must be
C++ with Python bindings on the control plane only"): the RPC hot path —
TRPC framing, epoll loop, method dispatch, response correlation — runs in
native/rpc.cpp; Python supplies service registration and (optionally) user
handlers.  Two handler tiers:

* **native echo methods** (``register_native_echo``): served entirely in
  C++, zero Python in the loop — the <10 µs tier (the reference's C++
  handlers are this tier; echo/relay/byte-oriented services qualify).
* **Python services** (``add_service`` with regular ``rpc.Service``
  classes): the native server upcalls into Python once per request with
  the cut payload; protobuf parse + user code + respond happen under the
  GIL, everything else stays native.

Wire format is byte-identical to ``policy/tpu_std.py`` frames, so native
servers serve Python ``rpc.Channel`` clients over tcp:// and native
channels call Python ``rpc.Server``s (tests/test_native_rpc.py proves both
directions).

Reference anchors: server hot path baidu_rpc_protocol.cpp:312, client
correlation controller.cpp:568.
"""
from __future__ import annotations

import ctypes
import threading
from typing import Any, Callable, Dict, Optional, Type

from ..butil import logging as log
from ..butil import native
from ..butil.native import _ASYNC_CB, _NREQ_FN
from . import errors
from .controller import Controller
from .service import MethodDescriptor, Service


class NativeServer:
    """Server whose datapath (accept/read/frame/dispatch/write) is native.

    Python handlers run via a single upcall per request; ``done()`` sends
    the response from whichever thread calls it (the native side serializes
    per-connection writes).
    """

    def __init__(self, usercode_inline: bool = True):
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError("native core unavailable")
        self._handle = 0
        self._methods: Dict[str, MethodDescriptor] = {}
        self._native_echo: set = set()
        # keep the callback object alive for the server's lifetime
        self._cb = _NREQ_FN(self._on_request)
        self._lock = threading.Lock()
        # True (default): handlers run on the upcalling epoll-loop thread
        # (minimal latency; handlers must be fast).  False: handlers park
        # on bthread tasklets — a blocking handler then stalls only its
        # tasklet, not the connection loop (the tail-isolation doctrine;
        # the Python Server's default).
        self.usercode_inline = usercode_inline

    # ---- control plane ------------------------------------------------

    def add_service(self, service: Service) -> None:
        for md in service.methods().values():
            if md.full_name in self._methods:
                raise ValueError(f"duplicate method {md.full_name}")
            self._methods[md.full_name] = md

    def register_native_echo(self, full_method: str) -> None:
        """Serve `full_method` natively: response body = request body (the
        reference's C++ echo handler tier; no Python per request)."""
        self._native_echo.add(full_method)

    def start(self, port: int = 0) -> int:
        h = self._lib.brpc_tpu_nserver_start(port)
        if h == 0:
            raise RuntimeError(f"cannot bind port {port}")
        self._handle = h
        for m in self._native_echo:
            self._lib.brpc_tpu_nserver_register_echo(h, m.encode())
        if self._methods:
            self._lib.brpc_tpu_nserver_set_handler(h, self._cb)
        self.port = self._lib.brpc_tpu_nserver_port(h)
        log.info("NativeServer started on port %d (%d py methods, %d native)",
                 self.port, len(self._methods), len(self._native_echo))
        return self.port

    def stop(self) -> None:
        with self._lock:
            if self._handle:
                self._lib.brpc_tpu_nserver_stop(self._handle)
                self._handle = 0

    def requests(self) -> int:
        return self._lib.brpc_tpu_nserver_requests(self._handle)

    # ---- data plane upcall --------------------------------------------

    def _respond(self, token: int, err: int, err_text: str,
                 payload: bytes, att: bytes) -> None:
        p = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload) \
            if payload else None
        a = (ctypes.c_uint8 * len(att)).from_buffer_copy(att) if att else None
        self._lib.brpc_tpu_nserver_respond(
            token, err, err_text.encode() if err_text else b"", p,
            len(payload), a, len(att))

    def _on_request(self, token, method, payload_p, payload_len,
                    att_p, att_len, log_id):
        try:
            full = method.decode()
            # copies happen HERE, inside the upcall — the native buffers
            # are only valid until we return
            payload = ctypes.string_at(payload_p, payload_len) \
                if payload_len else b""
            att = ctypes.string_at(att_p, att_len) if att_len else b""
            if not self.usercode_inline:
                from ..bthread import scheduler
                scheduler.start_background(
                    self._handle_request, token, full, payload, att,
                    log_id, name=f"nreq:{full}")
                return
            self._handle_request(token, full, payload, att, log_id)
        except Exception as e:          # never let an exception cross ctypes
            self._last_resort_error(token, e)

    def _last_resort_error(self, token, e) -> None:
        """Catch-all for request processing: the token must be answered
        (or at least attempted) no matter what blew up — on the upcall
        thread this also keeps the exception from crossing ctypes."""
        log.error("native-server request failed: %s", e, exc_info=True)
        try:
            self._respond(token, errors.EINTERNAL, str(e), b"", b"")
        except Exception:
            pass

    def _handle_request(self, token, full, payload, att, log_id):
        try:
            md = self._methods.get(full)
            if md is None:
                self._respond(token, errors.ENOMETHOD,
                              f"no method {full}", b"", b"")
                return
            cntl = Controller()
            cntl.log_id = log_id
            if att:
                cntl.request_attachment.append(att)
            try:
                request = md.request_cls()
                request.ParseFromString(payload)
            except Exception as e:
                self._respond(token, errors.EREQUEST,
                              f"fail to parse request: {e}", b"", b"")
                return
            response = md.response_cls()
            done_called = [False]

            def done() -> None:
                if done_called[0]:
                    return
                done_called[0] = True
                if cntl.failed():
                    self._respond(token, cntl.error_code_, cntl.error_text_,
                                  b"", b"")
                    return
                self._respond(token, 0, "", response.SerializeToString(),
                              cntl.response_attachment.to_bytes())

            cntl.set_server_done(done)
            try:
                md.invoke(cntl, request, response, done)
            except Exception as e:
                log.error("native-server method %s raised: %s", full, e,
                          exc_info=True)
                if not done_called[0]:
                    cntl.set_failed(errors.EINTERNAL,
                                    f"{type(e).__name__}: {e}")
                    done()
        except Exception as e:
            self._last_resort_error(token, e)


def _marshal_sync_call(lib, call_fn, handle, full_name: str,
                       cntl: Controller, request: Any,
                       response_cls: Optional[Type]):
    """Shared ctypes marshalling for the sync native call ABIs (channel
    and pool take identical argument/output shapes)."""
    if hasattr(request, "SerializeToString"):
        req = request.SerializeToString()
    else:
        req = bytes(request) if request is not None else b""
    att = cntl.request_attachment.to_bytes() \
        if len(cntl.request_attachment) else b""
    u8p = ctypes.POINTER(ctypes.c_uint8)
    reqb = ctypes.cast(req, u8p) if req else None
    attb = ctypes.cast(att, u8p) if att else None
    resp_p, resp_len = u8p(), ctypes.c_uint64()
    ratt_p, ratt_len = u8p(), ctypes.c_uint64()
    err_text = ctypes.c_char_p()
    timeout_us = int((cntl.timeout_ms or 5000) * 1000)
    rc = call_fn(
        handle, full_name.encode(), reqb, len(req), attb, len(att),
        timeout_us, ctypes.byref(resp_p), ctypes.byref(resp_len),
        ctypes.byref(ratt_p), ctypes.byref(ratt_len),
        ctypes.byref(err_text))
    try:
        if rc != 0:
            text = err_text.value.decode() if err_text.value else \
                errors.berror(int(rc))
            cntl.set_failed(int(rc), text)
            return None
        payload = ctypes.string_at(resp_p, resp_len.value) \
            if resp_len.value else b""
        if ratt_len.value:
            cntl.response_attachment.append(
                ctypes.string_at(ratt_p, ratt_len.value))
        if response_cls is None:
            return payload
        response = response_cls()
        response.ParseFromString(payload)
        return response
    finally:
        if resp_p:
            lib.brpc_tpu_buf_free(resp_p)
        if ratt_p:
            lib.brpc_tpu_buf_free(ratt_p)
        if err_text:
            lib.brpc_tpu_buf_free(err_text)


class NativeChannel:
    """Client whose datapath is native: serialize in Python once, then the
    frame/write/read/correlate cycle runs in C++ with the GIL released."""

    def __init__(self):
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError("native core unavailable")
        self._handle = 0

    def init(self, address: str) -> None:
        """address: "host:port" or "ntcp://host:port"."""
        addr = address.split("://", 1)[-1]
        host, _, port = addr.rpartition(":")
        h = self._lib.brpc_tpu_nchannel_connect(host.encode() or b"127.0.0.1",
                                                int(port))
        if h == 0:
            raise ConnectionError(f"cannot connect {address}")
        self._handle = h

    def close(self) -> None:
        if self._handle:
            self._lib.brpc_tpu_nchannel_close(self._handle)
            self._handle = 0

    def call_method(self, full_name: str, cntl: Controller, request: Any,
                    response_cls: Optional[Type] = None):
        """Synchronous call over the native datapath.  Fills cntl error
        state and response_attachment; returns the parsed response."""
        return _marshal_sync_call(self._lib, self._lib.brpc_tpu_nchannel_call,
                                  self._handle, full_name, cntl, request,
                                  response_cls)

    # ---- async completion API (reference: CallMethod with done) -------

    def call_method_async(self, full_name: str, cntl: Controller,
                          request: Any,
                          response_cls: Optional[Type] = None,
                          done: Optional[Callable] = None
                          ) -> "NativeCallFuture":
        """Fire the call and return a future; `done(cntl)` (if given)
        runs on the channel's native reader thread when the response,
        timeout, or failure arrives.  The reference's async CallMethod
        with a done closure."""
        if hasattr(request, "SerializeToString"):
            req = request.SerializeToString()
        else:
            req = bytes(request) if request is not None else b""
        att = cntl.request_attachment.to_bytes() \
            if len(cntl.request_attachment) else b""
        fut = NativeCallFuture(cntl, response_cls, done)
        _inflight_futures[id(fut)] = fut   # pinned until completion: the
        # native side holds only the raw trampoline pointer
        u8p = ctypes.POINTER(ctypes.c_uint8)
        reqb = ctypes.cast(req, u8p) if req else None
        attb = ctypes.cast(att, u8p) if att else None
        timeout_us = int((cntl.timeout_ms or 5000) * 1000)
        # the trampoline AND the request bytes must outlive the call:
        # pinned on the future until completion
        fut._pin = (req, att)
        rc = self._lib.brpc_tpu_nchannel_call_async(
            self._handle, full_name.encode(), reqb, len(req), attb,
            len(att), timeout_us, fut._cb, None)
        # rc != 0 means the failure completed synchronously — the
        # callback already fired and the future is done; callers can
        # check fut.done() to distinguish written-vs-failed-before-write
        return fut


_inflight_futures: Dict[int, "NativeCallFuture"] = {}


class NativeCallFuture:
    """Completion handle for call_method_async: wait() blocks; or poll
    done(); the optional user callback runs on the reader thread."""

    def __init__(self, cntl: Controller, response_cls: Optional[Type],
                 user_done: Optional[Callable]):
        self.cntl = cntl
        self.response = None
        self._response_cls = response_cls
        self._user_done = user_done
        self._event = threading.Event()
        self._cb = _ASYNC_CB(self._on_complete)   # pinned for lifetime
        self._pin = None
        self._once = threading.Lock()
        self._completed = False

    def _on_complete(self, _user, err, err_text, resp_p, resp_len,
                     att_p, att_len):
        # one-shot: belt-and-braces against any native double-fire — the
        # user's done must never run twice
        with self._once:
            if self._completed:
                return
            self._completed = True
        try:
            if err != 0:
                text = err_text.decode() if err_text else \
                    errors.berror(int(err))
                self.cntl.set_failed(int(err), text)
            else:
                payload = ctypes.string_at(resp_p, resp_len) \
                    if resp_len else b""
                if att_len:
                    self.cntl.response_attachment.append(
                        ctypes.string_at(att_p, att_len))
                if self._response_cls is not None:
                    try:
                        resp = self._response_cls()
                        resp.ParseFromString(payload)
                        self.response = self.cntl.response = resp
                    except Exception as e:
                        self.cntl.set_failed(
                            errors.ERESPONSE, f"bad response: {e}")
                else:
                    self.response = self.cntl.response = payload
        finally:
            self._pin = None
            _inflight_futures.pop(id(self), None)
            self._event.set()
            if self._user_done is not None:
                try:
                    self._user_done(self.cntl)
                except Exception as e:     # never raise across ctypes
                    log.error("async done callback raised: %s", e,
                              exc_info=True)

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)


class NativePooledChannel:
    """N native connections round-robined per call (reference pooled
    sockets, socket.h:256-262): concurrent large requests overlap in the
    kernel instead of serializing on one stream."""

    def __init__(self):
        self._lib = native.load()
        if self._lib is None:
            raise RuntimeError("native core unavailable")
        self._handle = 0

    def init(self, address: str, nconns: int = 4) -> None:
        addr = address.split("://", 1)[-1]
        host, _, port = addr.rpartition(":")
        h = self._lib.brpc_tpu_npool_connect(
            host.encode() or b"127.0.0.1", int(port), nconns)
        if h == 0:
            raise ConnectionError(f"cannot connect {address}")
        self._handle = h

    def close(self) -> None:
        if self._handle:
            self._lib.brpc_tpu_npool_close(self._handle)
            self._handle = 0

    def call_method(self, full_name: str, cntl: Controller, request: Any,
                    response_cls: Optional[Type] = None):
        return _marshal_sync_call(self._lib, self._lib.brpc_tpu_npool_call,
                                  self._handle, full_name, cntl, request,
                                  response_cls)
