"""Per-method accounting: concurrency, qps, latency, errors.

Reference: src/brpc/details/method_status.{h,cpp} — every server method owns
a MethodStatus that the concurrency limiter consults (OnRequested /
OnResponded) and the /status builtin renders.
"""
from __future__ import annotations

import threading

from .. import bvar


class MethodStatus:
    def __init__(self, full_name: str, limiter=None):
        safe = bvar.to_underscored_name(full_name)
        self.full_name = full_name
        self.latency_rec = bvar.LatencyRecorder(f"rpc_method_{safe}")
        self.error_count = bvar.Adder(f"rpc_method_{safe}_error")
        self._concurrency = 0
        self._lock = threading.Lock()
        self.limiter = limiter          # ConcurrencyLimiter or None

    def on_requested(self) -> bool:
        """False → reject with ELIMIT (limiter says no)."""
        with self._lock:
            if self.limiter is not None and not self.limiter.on_requested(
                    self._concurrency):
                return False
            self._concurrency += 1
            return True

    def on_responded(self, error_code: int, latency_us: int) -> None:
        with self._lock:
            self._concurrency -= 1
        if error_code == 0:
            self.latency_rec << latency_us
        else:
            self.error_count << 1
        if self.limiter is not None:
            self.limiter.on_responded(error_code, latency_us)

    @property
    def concurrency(self) -> int:
        return self._concurrency

    def describe(self) -> dict:
        return {
            "method": self.full_name,
            "count": self.latency_rec.count(),
            "qps": round(self.latency_rec.qps(), 2),
            "latency_us": round(self.latency_rec.latency(), 1),
            "max_latency_us": self.latency_rec.max_latency(),
            "concurrency": self.concurrency,
            "errors": self.error_count.get_value(),
        }
