"""Per-method accounting: concurrency, qps, latency, errors.

Reference: src/brpc/details/method_status.{h,cpp} — every server method owns
a MethodStatus that the concurrency limiter consults (OnRequested /
OnResponded) and the /status builtin renders.
"""
from __future__ import annotations

import threading

from .. import bvar
from . import errors

# Admission-layer rejection codes: shed traffic, not method failures.
# Feeding these into AutoConcurrencyLimiter.add_sample would have
# FAIL_PUNISH_RATIO treat every shed as a slow failure — under overload
# (exactly when sheds are plentiful) the punished latency mass poisons
# the learned no-load floor and walks the limit down, amplifying the
# overload it should absorb.  They are also excluded from the per-method
# error count (the method never ran) and tracked in their own counter.
#
# Scope note: gate/admission rejections null `status` before responding
# and never reach on_responded at all — what this classification ALSO
# covers is an EXECUTED handler that completes with ELIMIT/ELOGOFF (a
# proxy propagating a downstream shed, a handler bouncing during its own
# drain).  That is deliberate: punishing the LOCAL limiter's floor for a
# DOWNSTREAM's overload would collapse local concurrency exactly when
# the downstream is shedding, and a go-elsewhere signal is not a failure
# of this method.  Such completions stay visible in shed_count.
SHED_CODES = frozenset((errors.ELIMIT, errors.ELOGOFF))


class MethodStatus:
    def __init__(self, full_name: str, limiter=None):
        safe = bvar.to_underscored_name(full_name)
        self.full_name = full_name
        self.latency_rec = bvar.LatencyRecorder(f"rpc_method_{safe}")
        self.error_count = bvar.Adder(f"rpc_method_{safe}_error")
        self.shed_count = bvar.Adder(f"rpc_method_{safe}_shed")
        self._concurrency = 0
        self._lock = threading.Lock()
        self.limiter = limiter          # ConcurrencyLimiter or None

    def on_requested(self) -> bool:
        """False → reject with ELIMIT (limiter says no)."""
        with self._lock:
            if self.limiter is not None and not self.limiter.on_requested(
                    self._concurrency):
                return False
            self._concurrency += 1
            return True

    def on_responded(self, error_code: int, latency_us: int) -> None:
        with self._lock:
            self._concurrency -= 1
        if error_code == 0:
            self.latency_rec << latency_us
        elif error_code in SHED_CODES:
            # admission shed / lame-duck bounce: not a method failure,
            # and NOT a limiter sample (see SHED_CODES above)
            self.shed_count << 1
            return
        else:
            self.error_count << 1
        if self.limiter is not None:
            self.limiter.on_responded(error_code, latency_us)

    @property
    def concurrency(self) -> int:
        return self._concurrency

    def describe(self) -> dict:
        return {
            "method": self.full_name,
            "count": self.latency_rec.count(),
            "qps": round(self.latency_rec.qps(), 2),
            "latency_us": round(self.latency_rec.latency(), 1),
            "max_latency_us": self.latency_rec.max_latency(),
            "concurrency": self.concurrency,
            "errors": self.error_count.get_value(),
            "shed": self.shed_count.get_value(),
        }
