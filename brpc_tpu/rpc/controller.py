"""Controller: per-RPC state machine for both client and server sides.

Reference: src/brpc/controller.{h,cpp} + the client call flow of SURVEY.md
§3.3.  Client-side lifecycle:

  Channel.call_method
    → correlation id created ranged over max_retry+1 try-versions
      (channel.cpp:442): try k sends version k; a *retry* advances the
      current version so older tries' responses fail to lock (ignored); a
      *backup request* leaves older versions valid so the first response
      wins (backup_request.md semantics).
    → timeout / backup timers through TimerThread (channel.cpp:537-574)
    → issue_rpc: pick socket, pack, Socket.write (controller.cpp:985-1144)
    → completion funnels through the correlation id's on_error/lock — the
      single synchronization point (OnVersionedRPCReturned controller.cpp:568)

Server side carries request metadata (deadline, attachment, peer) and the
response sender closure.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Optional

from ..butil.iobuf import IOBuf
from ..butil.endpoint import EndPoint
from ..butil import custody_ledger as _ledger
from ..bthread import id as bthread_id
from ..bthread.timer_thread import TimerThread
from . import errors


class _LazyField:
    """Non-data descriptor: materializes a per-instance default on first
    READ (the instance dict shadows it afterwards, so steady-state access
    is a plain attribute load).  This is what makes Controller
    construction and pool reset nearly free: a request that never touches
    its attachments never pays for their IOBufs."""
    __slots__ = ("name", "factory")

    def __init__(self, name: str, factory):
        self.name = name
        self.factory = factory

    def __get__(self, obj, owner=None):
        if obj is None:
            return self
        val = obj.__dict__[self.name] = self.factory()
        return val


class Controller:
    # Every scalar default lives on the CLASS: __init__ sets nothing, so
    # construction is an empty-dict object and a pooled reset is one
    # ``__dict__.clear()`` — the "thin shim that inflates on first
    # access" design (reference Controller + ResetPods).  Writes shadow
    # the class default in the instance dict as usual; only the mutable
    # containers (attachments, excluded-server set) need the lazy
    # descriptor above.
    # common
    error_code_: int = 0
    error_text_: str = ""
    log_id: int = 0
    # admission-control propagation (rpc/admission.py): priority band
    # (0=critical .. 3=sheddable; None = the server's default band) and
    # fair-queueing tenant, carried in RequestMeta on every plane.  On
    # the server side these are the DECODED request values (handlers may
    # read them); retry_after_ms is the shed backoff hint — written by
    # the server before a shed response, filled from ResponseMeta on the
    # client so callers (and the retry machinery) can honor it.
    priority: Optional[int] = None
    tenant: str = ""
    retry_after_ms: int = 0
    deadline_left_ms: int = 0       # server side: budget at arrival
    # compiled fan-out call state (channels/collective_fanout.py): the
    # typed array operand the caller scatters across a Parallel/
    # Partition fan-out, the merged result, and which route actually
    # carried the call ("collective" = one compiled SPMD program,
    # "rpc" = the per-member loop, "" = not an operand fan-out) — the
    # route assertion surface for bench/tools/tests
    fanout_operand: Any = None
    fanout_result: Any = None
    fanout_route: str = ""
    request_attachment = _LazyField("request_attachment", IOBuf)
    # the response factory is swapped to ici/native_plane.py's
    # ResponseAttachment once that module loads (ISSUE 13): identical
    # to a plain IOBuf except that appending a whole, untouched
    # NativeAttachment view into it while empty ADOPTS the parked
    # native handle (the PR-8 echo idiom stops materializing)
    response_attachment = _LazyField("response_attachment", IOBuf)
    remote_side: Optional[EndPoint] = None
    local_side: Optional[EndPoint] = None
    auth_token: str = ""
    compress_type: int = 0
    # tracing
    trace_id: int = 0
    span_id: int = 0
    parent_span_id: int = 0
    span = None
    # client call state
    timeout_ms: Optional[int] = None
    max_retry: Optional[int] = None
    backup_request_ms: Optional[int] = None
    retry_on_timeout: Optional[bool] = None
    retry_backoff_ms: Optional[int] = None
    retried_count: int = 0
    current_try: int = 0
    latency_us: int = 0
    response: Any = None
    _response_cls: Any = None
    _done: Optional[Callable[["Controller"], None]] = None
    _cid: int = 0
    _timeout_timer = None
    _backup_timer = None
    _channel = None                 # issuing channel (for re-issues)
    _method_full_name: str = ""
    _request_buf: Optional[IOBuf] = None
    _start_us: int = 0
    # lazy: ~3 µs of threading.Event construction per call that the
    # native ici fast path (sync, never joins) would pay for nothing
    _ended_ev: Optional[threading.Event] = None
    _excluded_servers = _LazyField("_excluded_servers", set)
    request_protocol: str = ""
    stream_creator = None           # set by stream.create on host RPC
    accepted_stream_id = 0
    # server side
    server = None
    _session_data: Any = None
    method_deadline: Optional[float] = None
    _server_done: Optional[Callable[[], None]] = None
    http_request = None
    http_response = None
    _recycle_pool = None            # ControllerPool that owns this shim

    # ---- attachment peeks (hot paths) ---------------------------------
    # Reading request_attachment/response_attachment MATERIALIZES the
    # IOBuf; presence checks on hot paths use these instead so an
    # attachment-less echo never allocates either buffer.
    def _peek_request_attachment(self) -> Optional[IOBuf]:
        return self.__dict__.get("request_attachment")

    def _peek_response_attachment(self) -> Optional[IOBuf]:
        return self.__dict__.get("response_attachment")

    # ---- per-RPC session data (reference Controller::session_local_data,
    # backed by ServerOptions.session_local_data_factory's pool) ---------
    def session_local_data(self) -> Any:
        if self._session_data is None and self.server is not None:
            self._session_data = self.server._get_session_data()
        return self._session_data

    def _release_session_data(self) -> None:
        # idempotent: called from MethodDescriptor.invoke's wrapped done
        if self._session_data is not None and self.server is not None:
            self.server._return_session_data(self._session_data)
            self._session_data = None

    _ended_create_lock = threading.Lock()

    @property
    def _ended(self) -> threading.Event:
        """Completion event, created on first touch (double-checked under
        a class lock: a completer's set() and a joiner's wait() may both
        be the first toucher, and each building its own Event would park
        the joiner forever).  The native ici fast path completes calls
        without ever touching this."""
        ev = self._ended_ev
        if ev is None:
            with Controller._ended_create_lock:
                ev = self._ended_ev
                if ev is None:
                    ev = self._ended_ev = threading.Event()
        return ev

    # ---- error surface (reference Controller::SetFailed/Failed) -------
    def set_failed(self, code: int, text: str = "") -> None:
        self.error_code_ = code
        self.error_text_ = text or errors.berror(code)

    def failed(self) -> bool:
        return self.error_code_ != 0

    @property
    def error_code(self) -> int:
        return self.error_code_

    @property
    def error_text(self) -> str:
        return self.error_text_

    def reset(self) -> None:
        # every field is a class default (see above): clearing the
        # instance dict restores pristine state in one C-level op
        self.__dict__.clear()

    def _maybe_recycle(self) -> None:
        """Return a pool-acquired server-side Controller to its pool once
        the response is fully sent (the protocol-agnostic recycle point —
        called by MethodDescriptor.invoke's wrapped done and by the
        pre-invoke error paths).  No-op for plain Controllers."""
        pool = self.__dict__.get("_recycle_pool")
        if pool is not None:
            pool.release(self)

    # ---- client call orchestration ------------------------------------
    def _start_call(self, channel, method_full_name: str, request_buf: IOBuf,
                    response_cls, done) -> None:
        self._channel = channel
        self._method_full_name = method_full_name
        self._request_buf = request_buf
        self._response_cls = response_cls
        self._done = done
        self._start_us = time.monotonic_ns() // 1000
        opts = channel.options
        if self.timeout_ms is None:
            self.timeout_ms = opts.timeout_ms
        if self.max_retry is None:
            self.max_retry = opts.max_retry
        if self.backup_request_ms is None:
            self.backup_request_ms = opts.backup_request_ms
        if self.retry_on_timeout is None:
            self.retry_on_timeout = opts.retry_on_timeout
        if self.retry_backoff_ms is None:
            self.retry_backoff_ms = getattr(opts, "retry_backoff_ms", 0)
        # +1: versions are try indices 0..max_retry
        self._cid = bthread_id.create_ranged(
            self, self._on_rpc_event, self.max_retry + 1)
        needs_backup = (self.backup_request_ms and self.backup_request_ms > 0
                        and self.backup_request_ms < (self.timeout_ms or 1 << 30))
        if needs_backup:
            # hedging must be armed before the first try leaves
            self._backup_timer = TimerThread.instance().schedule_after(
                self._handle_backup_request, self.backup_request_ms / 1000.0)
        self._issue_rpc()
        # deadline timer is only needed if the call is still in flight —
        # inline loopback/device completions skip the timer heap entirely
        if (self.timeout_ms and self.timeout_ms > 0
                and not self._ended.is_set()):
            self._schedule_try_timer()

    def _timeout_hedging(self) -> bool:
        """Per-try deadline hedging is active only when opted in via
        ChannelOptions.retry_on_timeout, and backup_request_ms is unset
        (that is already an explicit hedging schedule — running both would
        double-hedge and burn the retry budget)."""
        return bool(self.retry_on_timeout) and not self.backup_request_ms

    def _schedule_try_timer(self) -> None:
        """Arm the deadline timer for the current try.

        Default (reference semantics, controller.cpp HandleTimeout):
        timeout_ms is a single overall deadline and ERPCTIMEDOUT is final.
        With retry_on_timeout opted in, the deadline is instead split
        evenly over the tries that remain: a try that produces neither a
        response nor a connection error gets remaining/tries_left ms before
        the correlation id is poked with ERPCTIMEDOUT, where the funnel
        hedges a fresh try instead of failing (see _on_rpc_event).  The
        total deadline is always honored.
        """
        if self._timeout_timer is not None:
            TimerThread.instance().unschedule(self._timeout_timer)
            self._timeout_timer = None
        if not self.timeout_ms or self.timeout_ms <= 0 or self._ended.is_set():
            return
        elapsed_ms = (time.monotonic_ns() // 1000 - self._start_us) / 1000.0
        remaining = max(0.0, self.timeout_ms - elapsed_ms)
        if self._timeout_hedging():
            tries_left = max(1, (self.max_retry or 0) - self.current_try + 1)
            remaining = remaining / tries_left
        # Bind the try version NOW: unschedule() can't stop a timer that
        # already popped from the heap, and a stale tasklet reading
        # current_try at run time would poke the *live* try with
        # ERPCTIMEDOUT long before its deadline.  A version-bound stale
        # timer instead fails to lock (after reset_version) or is dropped
        # by the straggler guard.
        ver = self.current_try
        self._timeout_timer = TimerThread.instance().schedule_after(
            lambda: self._handle_timeout(ver), remaining / 1000.0)

    def current_cid(self) -> int:
        return bthread_id.with_version(self._cid, self.current_try)

    def _issue_rpc(self) -> None:
        try:
            self._channel._issue_rpc(self)
        except Exception as e:
            bthread_id.error(self.current_cid(),
                             errors.EFAILEDSOCKET)

    # timer callbacks ---------------------------------------------------
    def _handle_timeout(self, ver: int) -> None:
        # ver is bound at arm time by _schedule_try_timer — never read
        # current_try here (a stale pop would shoot the live try).
        bthread_id.error(bthread_id.with_version(self._cid, ver),
                         errors.ERPCTIMEDOUT)

    def _handle_backup_request(self) -> None:
        bthread_id.error(bthread_id.with_version(self._cid, self.current_try),
                         errors.EBACKUPREQUEST)

    # the correlation-id funnel (always entered with the id locked) ------
    def _on_rpc_event(self, data, cid: int, error_code: int) -> None:
        """on_error callback: timeout, backup trigger, send failure, or
        remote response error all land here — the retry decision point."""
        ver = bthread_id.get_version(cid)
        if ver < self.current_try and error_code not in (
                errors.EBACKUPREQUEST, errors.ECANCELED):
            # A straggler: an older hedge try died *after* a newer try was
            # issued (hedging keeps old versions lockable so their slow
            # responses can still win — but their failures must not decide
            # the call while the live try is in flight, nor blacklist the
            # live try's server).
            bthread_id.unlock(cid)
            return
        if error_code == errors.EBACKUPREQUEST:
            # hedge: issue one more try; older versions stay valid so the
            # first response to arrive wins.
            if self.current_try < self.max_retry:
                self.current_try += 1
                self.retried_count += 1
                # the deadline timer is version-bound; re-arm it at the
                # new current version or the straggler guard would swallow
                # the overall deadline after this hedge
                self._schedule_try_timer()
                self._issue_rpc()
            bthread_id.unlock(cid)
            return
        if error_code == errors.ERPCTIMEDOUT:
            elapsed_ms = (time.monotonic_ns() // 1000
                          - self._start_us) / 1000.0
            remaining = (self.timeout_ms or 0) - elapsed_ms
            if (self._timeout_hedging() and remaining > 1.0
                    and self.current_try < self.max_retry):
                # This try's share of the deadline elapsed with no reply:
                # hedge a fresh try.  Old versions stay valid (no
                # reset_version) so a merely-slow response still wins; the
                # silent server is excluded so an LB steers elsewhere.
                sel = getattr(self, "_selected_endpoint", None)
                if sel is not None:
                    self._excluded_servers.add(sel)
                self.current_try += 1
                self.retried_count += 1
                self._schedule_try_timer()
                self._issue_rpc()
                bthread_id.unlock(cid)
                return
            self.set_failed(errors.ERPCTIMEDOUT,
                            f"reached timeout={self.timeout_ms}ms")
            self._end_rpc(cid)
            return
        # send/socket failure or server-pushed error: retry if allowed
        if self._retryable(error_code) and self.current_try < self.max_retry:
            sel = getattr(self, "_selected_endpoint", None)
            if sel is not None:
                self._excluded_servers.add(sel)   # per-call blacklist
            self.current_try += 1
            self.retried_count += 1
            bthread_id.reset_version(self._cid, self.current_try)  # stale old tries
            self._schedule_try_timer()
            # a lame-duck rejection (ELOGOFF) is the peer explicitly
            # saying "go elsewhere" — an instant failover, not an outage:
            # it must not consume the connection-failure backoff budget
            delay_s = 0.0 if error_code == errors.ELOGOFF \
                else self._retry_backoff_s()
            if delay_s > 0:
                # spaced retry: the endpoint may be DOWN rather than
                # flaky — immediate re-connects would burn the whole
                # retry budget in microseconds, while spaced ones ride
                # out an outage until health-check revival brings the
                # peer back.  The deadline timer armed above still
                # bounds the call; a delay past it just loses to
                # ERPCTIMEDOUT, which is correct.
                from ..bthread import scheduler as _sched
                TimerThread.instance().schedule_after(
                    lambda: _sched.start_background(
                        self._issue_rpc, name="retry_backoff"),
                    delay_s)
            else:
                self._issue_rpc()
            bthread_id.unlock(cid)
            return
        self.set_failed(error_code)
        self._end_rpc(cid)

    def _retry_backoff_s(self) -> float:
        """Exponential backoff with deterministic per-call jitter for
        connection-failure retries; 0 when the channel didn't opt in."""
        base_ms = self.retry_backoff_ms or 0
        if base_ms <= 0:
            return 0.0
        delay_ms = min(base_ms * (2 ** (self.retried_count - 1)),
                       1000.0)
        rng = random.Random((self._cid << 8) ^ self.retried_count)
        return delay_ms * (1.0 + 0.25 * rng.random()) / 1000.0

    @staticmethod
    def _retryable(error_code: int) -> bool:
        return error_code in (errors.EFAILEDSOCKET, errors.EEOF,
                              errors.ELOGOFF, errors.ECONNREFUSED,
                              errors.ECONNRESET, errors.EAGAIN)

    def handle_response(self, cid: int, meta, payload: IOBuf) -> None:
        """Called by the protocol with the correlation id locked and
        validated (stale tries never get here)."""
        rmeta = meta.response
        if rmeta.error_code != 0:
            if bthread_id.get_version(cid) < self.current_try:
                # Under hedging old versions stay lockable so a slow
                # *success* can still win — but an abandoned try's error
                # response must not decide the call or stale the live
                # hedge (same rule as the straggler guard in
                # _on_rpc_event).
                bthread_id.unlock(cid)
                return
            err = rmeta.error_code
            self.set_failed(err, rmeta.error_text)
            hint_ms = getattr(rmeta, "retry_after_ms", 0)
            if hint_ms:
                self.retry_after_ms = hint_ms
            # an admission shed (ELIMIT + retry_after_ms) is retryable —
            # but only after the server's hint: the server said exactly
            # how long its backlog needs, and an immediate re-dispatch
            # (or a hedge) would be the retry storm the shed exists to
            # prevent
            shed_retry = err == errors.ELIMIT and hint_ms > 0
            if (self._retryable(err) or shed_retry) \
                    and self.current_try < self.max_retry:
                # the retry must land on a DIFFERENT replica: a server
                # that pushed a retryable error (lame-duck ELOGOFF most
                # of all) will push it again — the reference's per-call
                # blacklist applies to server-pushed errors too
                sel = getattr(self, "_selected_endpoint", None)
                if sel is not None:
                    self._excluded_servers.add(sel)
                self.error_code_ = 0
                self.error_text_ = ""
                self.current_try += 1
                self.retried_count += 1
                bthread_id.reset_version(self._cid, self.current_try)
                self._schedule_try_timer()
                if shed_retry:
                    # honor the hint via the shared shed-backoff policy
                    # (admission.shed_backoff_s: hint + above-only
                    # jitter).  A delay past the overall deadline just
                    # loses to ERPCTIMEDOUT, which is the correct bound.
                    from .admission import shed_backoff_s
                    delay_s = shed_backoff_s(
                        hint_ms, seed=(self._cid << 8)
                        ^ self.retried_count)
                    from ..bthread import scheduler as _sched
                    TimerThread.instance().schedule_after(
                        lambda: _sched.start_background(
                            self._issue_rpc, name="shed_retry"),
                        delay_s)
                else:
                    self._issue_rpc()
                bthread_id.unlock(cid)
                return
            self._end_rpc(cid)
            return
        try:
            att_size = meta.attachment_size
            body = payload
            if att_size:
                att = IOBuf()
                keep = len(body) - att_size
                tmp = body.cut(keep)
                body.cutn(att, att_size)
                body = tmp
                self.response_attachment = att
            data = body.to_bytes()
            if meta.compress_type:
                from .compress import decompress
                data = decompress(meta.compress_type, data)
            if self._response_cls is not None:
                resp = self._response_cls()
                resp.ParseFromString(data)
                self.response = resp
            else:
                self.response = data
        except Exception as e:
            self.set_failed(errors.ERESPONSE, f"fail to parse response: {e}")
        self._end_rpc(cid)

    def finish_parsed_response(self, cid: int) -> None:
        """Completion for protocols that parse the response themselves
        (http/redis/memcache): cntl.response is already set."""
        self._end_rpc(cid)

    def handle_parsed_http_response(self, cid: int, http_msg) -> None:
        """HTTP client completion: response object was already parsed by the
        protocol (json2pb); just record and finish."""
        self.http_response = http_msg
        self._end_rpc(cid)

    def _end_rpc(self, cid: int) -> None:
        if self._timeout_timer is not None:
            TimerThread.instance().unschedule(self._timeout_timer)
        if self._backup_timer is not None:
            TimerThread.instance().unschedule(self._backup_timer)
        self.latency_us = time.monotonic_ns() // 1000 - self._start_us
        chan = self._channel
        if chan is not None:
            try:
                chan._on_call_end(self)
            except Exception:
                pass
        if self.span is not None:
            from .span import end_client_span
            end_client_span(self)
        done = self._done
        bthread_id.unlock_and_destroy(cid)   # wakes sync joiner
        self._ended.set()
        if done is not None:
            from ..bthread import scheduler
            scheduler.start_background(done, self, name="rpc_done")

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for RPC completion (sync calls).  When the caller is a
        scheduler tasklet, compensate the blocked worker so server-side
        processing can't be starved by sync callers (the reference blocks
        on a butex, which yields the bthread worker for free)."""
        from ..bthread import scheduler
        state = self.__dict__.get("_loopback_state")
        if state is not None:
            ev = state.wait_begin()
            if ev is None:
                return                   # already completed
            scheduler.note_worker_blocked()
            try:
                if not ev.wait(timeout):
                    raise TimeoutError("RPC join timed out")
            finally:
                scheduler.note_worker_unblocked()
            return
        scheduler.note_worker_blocked()
        try:
            if not self._ended.wait(timeout):
                raise TimeoutError("RPC join timed out")
        finally:
            scheduler.note_worker_unblocked()

    def cancel(self) -> None:
        """Cancel the in-flight call (reference StartCancel/CancelRPC): the
        caller completes with ECANCELED; a late response is dropped by the
        correlation id (wire path) or the loopback claim."""
        if self.__dict__.get("_loopback_state") is not None:
            from . import loopback
            loopback.cancel(self)
            return
        if self._cid and not self._ended.is_set():
            bthread_id.error(
                bthread_id.with_version(self._cid, self.current_try),
                errors.ECANCELED)

    # ---- server side ---------------------------------------------------
    def set_server_done(self, fn: Callable[[], None]) -> None:
        self._server_done = fn

    def send_response(self) -> None:
        if self._server_done is not None:
            fn, self._server_done = self._server_done, None
            fn()


class ControllerPool:
    """Server-side Controller pool (reference: brpc keeps the whole
    server path allocation-free; src/butil/resource_pool.h).

    In-use shims are tracked through a versioned-id
    :class:`~brpc_tpu.butil.resource_pool.ResourcePool` — ``live()`` and
    ``live_controllers()`` are the census/debug enumeration, and a
    double release is rejected by the id version instead of corrupting
    the free list.  Reset is ``Controller.reset()`` (one dict clear), so
    a recycled shim can never leak request k's error code, attachment,
    or span into request k+1 — the classic pool bug, pinned by
    tests/test_controller_pool.py."""

    _GUARDED_BY = {"_free": "_lock"}

    # fablint custody contract (ISSUE 20): a pooled shim handed out by
    # acquire() comes back through release() exactly once; the id
    # version makes a double release a no-op, the ledger makes a NO
    # release attributable to its acquiring call site.
    _CUSTODY = {"acquire": ("release",)}

    def __init__(self, capacity: int = 1024):
        from ..butil import debug_sync as _dbg
        from ..butil.resource_pool import ResourcePool
        self.capacity = capacity
        self._ids: "ResourcePool[Controller]" = ResourcePool()
        self._free: list = []
        self._lock = _dbg.make_lock("ControllerPool._lock")

    def acquire(self) -> Controller:
        with self._lock:
            c = self._free.pop() if self._free else None
        if c is None:
            c = Controller()
        d = c.__dict__
        d["_pool_rid"] = self._ids.get_resource(c)
        d["_recycle_pool"] = self
        _ledger.acquire("cntl", (id(self), d["_pool_rid"]))
        return c

    def release(self, c: Controller) -> None:
        rid = c.__dict__.get("_pool_rid", 0)
        if not rid or not self._ids.return_resource(rid):
            return                   # not ours / already released: drop
        _ledger.release("cntl", (id(self), rid))
        # native att custody (ISSUE 12): pool-recycle is the blessed
        # drop point for an attachment view whose handle never exited
        # (handler ignored it / response failed before the pass-back) —
        # duck-typed so this module never imports the ici plane.  Both
        # hooks are idempotent; plain IOBufs don't carry them.
        d = c.__dict__
        att = d.get("request_attachment")
        if att is not None:
            fn = getattr(att, "_dispose_native", None)
            if fn is not None:
                fn()
        att = d.get("response_attachment")
        if att is not None:
            fn = getattr(att, "_dispose_native", None)
            if fn is not None:
                fn()
        c.reset()
        with self._lock:
            if len(self._free) < self.capacity:
                self._free.append(c)

    def live(self) -> int:
        """Controllers currently handed out (in-flight requests)."""
        return self._ids.size()

    def live_controllers(self) -> list:
        return self._ids.live_payloads()

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)


# The process-wide server-side pool: every server protocol that
# constructs per-request Controllers (tpu_std, the native ici upcall
# tier, the loopback plane) draws from it.
server_controller_pool = ControllerPool()
