"""rpc — core runtime (reference: src/brpc/, SURVEY.md §2.4)."""
from . import errors
from .errors import RpcError, berror
from .protocol import (Protocol, ParseResult, ParseResultType,
                       register_protocol, find_protocol, list_protocols)
from .socket import Socket, SocketStat, WriteRequest, list_sockets
from .input_messenger import InputMessenger
from .controller import Controller
from .service import Service, method, MethodDescriptor
from .server import Server, ServerOptions
from .channel import Channel, ChannelOptions
from .socket_map import SocketMap
from .method_status import MethodStatus
from . import compress
from . import span
from .stream import (Stream, StreamOptions, StreamInputHandler, stream_create,
                     stream_accept, find_stream)
from .circuit_breaker import CircuitBreaker, ClusterRecoverPolicy, BreakerRegistry
from .health_check import start_health_check, probe_endpoint, HealthCheckTask
from .progressive import (ProgressiveReader, ProgressiveAttachment,
                          response_will_be_read_progressively,
                          create_progressive_attachment)
from . import profiler
from . import rpc_dump
