"""Streaming RPC: ordered byte/message streams attached to an RPC.

Reference: src/brpc/stream.{h,cpp} + policy/streaming_rpc_protocol.cpp
(SURVEY.md §3.4).  Semantics kept:

  * StreamCreate (client, stream.cpp:732) / StreamAccept (server, :756):
    the stream rides the host RPC's connection; ids are exchanged through
    RpcMeta.stream_settings (the reference's handshake).
  * Sliding window with consumed-bytes feedback: a writer may have at most
    ``max_buf_size`` unconsumed bytes in flight (AppendIfNotFull :274);
    the receiver reports consumption watermarks (SendFeedback :572) which
    wake blocked writers (SetRemoteConsumed :307).
  * Delivery through a per-stream ExecutionQueue so user handlers see
    ordered batches without blocking the socket reader (Consume :526).

Frames are tpu_std RpcMeta envelopes with ``stream_settings.frame_type``:
DATA / FEEDBACK / CLOSE; tpu_std routes them here from both server and
client parse paths.
"""
from __future__ import annotations

import struct
import time
from typing import List, Optional

from ..butil import flags as _flags
from ..butil.iobuf import IOBuf
from ..butil import debug_sync as _dbg
from ..butil.resource_pool import ResourcePool
from ..butil import custody_ledger as _ledger
from ..bthread.butex import Butex
from ..bthread.execution_queue import ExecutionQueue
from . import errors

FRAME_DATA = 0
FRAME_FEEDBACK = 1
FRAME_RST = 2
FRAME_CLOSE = 3
# DATA whose payload rode the fabric BULK plane: the control frame body
# is a 16-byte <u64 bulk uuid><u64 byte length> descriptor, the payload
# bytes move out-of-band on the dedicated bulk connection
# (native/fabric.cpp).  frame_type 4 is the tpu_std stream handshake.
FRAME_DATA_BULK = 5
# DATA whose payload rode the same-host SHM RING tier: identical
# 16-byte descriptor, bytes move through the mmap'd ring (one sender
# copy, zero-copy claim, no syscalls).  Which plane a frame rode is
# explicit in the frame type because the route can change mid-stream
# (plane death falls back tier by tier).
FRAME_DATA_SHM = 6
# N shm DATA frames announced by ONE control frame: the body is a
# CONCATENATION of 16-byte descriptors, in stream order.  On the ring
# tier the bytes are PUBLISHED before their descriptor is even queued
# (a memcpy, not a drained writev), so descriptors can coalesce without
# delaying any byte — and the per-frame control cost (RpcMeta pack +
# socket write on the sender, recv + protobuf parse + dispatch on the
# receiver) amortizes across the batch.  Measured: the 256KB-chunk
# cross-process stream tier is CONTROL-bound, not byte-bound, once the
# ring removes the copies.
FRAME_DATA_SHM_BATCH = 7

_BULK_DESC = struct.Struct("<QQ")

DEFAULT_MAX_BUF_SIZE = 2 * 1024 * 1024

# DATA frames at least this large ride the bulk fast plane when the
# socket binds one (ici:// cross-process FabricSocket); below it the
# descriptor + claim round trip costs more than the inline copy.  The
# stream's credit window and seq-ordered delivery are unchanged either
# way — only the byte transport differs.
_flags.define_flag("ici_stream_bulk_threshold", 64 * 1024,
                   "min stream DATA frame bytes routed over the fabric "
                   "bulk plane", _flags.positive_integer)
# Descriptor coalescing on the shm ring route: up to this many DATA
# frames share one control frame (1 = a descriptor per frame, the bulk
# tier's behavior).  Pending descriptors flush when the batch fills,
# when any OTHER frame must go out on the stream (ordering), before the
# writer parks on a full window (the receiver cannot return credits for
# frames it has not been told about), and after a short linger so a
# bursty-then-idle writer never strands a tail.  The effective batch is
# also bounded by the stream window (window-full forces a flush), so 32
# in practice means "amortize control across the in-flight window";
# latency-sensitive streams are bounded by the linger, not the batch.
_flags.define_flag("ici_stream_desc_batch", 32,
                   "max shm stream DATA descriptors coalesced into one "
                   "control frame", _flags.positive_integer)
_flags.define_flag("ici_stream_desc_flush_us", 1000,
                   "linger before a partial shm descriptor batch is "
                   "flushed", _flags.positive_integer)


class StreamOptions:
    def __init__(self, handler: Optional["StreamInputHandler"] = None,
                 max_buf_size: int = DEFAULT_MAX_BUF_SIZE,
                 messages_in_batch: int = 64):
        self.handler = handler
        self.max_buf_size = max_buf_size
        self.messages_in_batch = messages_in_batch


class StreamInputHandler:
    """User callback interface (reference StreamInputHandler)."""

    def on_received_messages(self, stream_id: int,
                             messages: List[IOBuf]) -> None:
        raise NotImplementedError

    def on_idle_timeout(self, stream_id: int) -> None:
        pass

    def on_closed(self, stream_id: int) -> None:
        pass


class Stream:
    # fablint guarded-state contract: flow-control counters under the
    # flow lock, lifecycle transitions + lazy queue under the state
    # lock, frame sequencing under the wire lock (see __init__ notes)
    # _flush_gen is deliberately NOT in this map: writes happen under
    # _wire_lock, but the linger timer's staleness probe reads it
    # lock-free on the shared TimerThread (a blocking acquire there
    # would stall every RPC deadline behind a writer parked in an shm
    # send) — GIL-atomic int read, false positives only spawn a no-op
    # flush tasklet.
    _GUARDED_BY = {
        "_produced": "_flow_lock",
        "_remote_consumed": "_flow_lock",
        "_exec": "_state_lock",
        "_sock_failed_cb": "_state_lock",
        "_seq": "_wire_lock",
        "_pending_desc": "_wire_lock",
    }

    def __init__(self, options: StreamOptions, is_client: bool):
        self.options = options
        self.is_client = is_client
        self.sid: int = 0               # local id (pool id)
        self.remote_sid: int = 0        # peer's id, set after handshake
        self.socket = None              # host connection
        self.connected = False
        self._conn_butex = Butex(0)
        # flow control (sender side)
        self._produced = 0
        self._remote_consumed = 0
        self._flow_lock = _dbg.make_lock("Stream._flow_lock")
        self._writable_butex = Butex(0)
        # receiver side
        self._local_consumed = 0
        self._last_feedback = 0
        self.closed = False
        self._seq = 0
        self._sock_failed_cb = None     # registered at mark_connected
        # guards the connected/closed transitions and the lazy _exec
        # creation: on_remote_close is runnable from ANY thread (socket
        # on_failed callbacks), and mark_connected has two concurrent
        # callers (the RPC response tasklet and a racing first stream
        # frame on the parse path) — unsynchronized check-then-act on
        # either flag double-registers callbacks or double-fires
        # on_closed (review findings)
        self._state_lock = _dbg.make_lock("Stream._state_lock")
        # serializes frame emission: seq assignment, the out-of-band bulk
        # post, and the control write must stay one atomic step so frame
        # k's bulk bytes can never trail frame k+1's descriptor
        self._wire_lock = _dbg.make_lock("Stream._wire_lock")
        # shm descriptor coalescing (FRAME_DATA_SHM_BATCH): published-
        # but-unannounced ring frames, flushed per the batch policy.
        # _flush_gen invalidates stale linger timers.
        self._pending_desc: List = []
        self._flush_gen = 0
        self._exec: Optional[ExecutionQueue] = None

    # -- sender ---------------------------------------------------------
    def writable_bytes(self) -> int:
        with self._flow_lock:
            return self.options.max_buf_size - (self._produced
                                                - self._remote_consumed)

    def append_if_not_full(self, data: IOBuf) -> int:
        """0 ok; EAGAIN window full; EINVAL closed (stream.cpp:274)."""
        n = len(data)
        with self._flow_lock:
            if self.closed:
                return errors.EINVAL
            if self._produced - self._remote_consumed + n \
                    > self.options.max_buf_size:
                return errors.EAGAIN
            self._produced += n
        self._send_frame(FRAME_DATA, data)
        return 0

    def write(self, data: IOBuf, timeout: Optional[float] = None) -> int:
        """Blocking write: waits for window space (StreamWrite +
        StreamWait)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rc = self.append_if_not_full(data)
            if rc != errors.EAGAIN:
                return rc
            # about to park on a full window: the receiver can only
            # return credits for frames it has been TOLD about — flush
            # any coalesced shm descriptors first or the wait deadlocks
            # until the linger timer fires
            self._flush_pending()
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return errors.ETIMEDOUT
            self._writable_butex.set_value(0)
            if self.writable_bytes() > len(data) or self.closed:
                continue
            self._writable_butex.wait(0, remaining if remaining is not None
                                      else 1.0)

    def set_remote_consumed(self, consumed: int) -> None:
        """Feedback arrival: wake blocked writers (stream.cpp:307)."""
        with self._flow_lock:
            if consumed > self._remote_consumed:
                self._remote_consumed = consumed
        self._writable_butex.wake_all_and_set(1)

    # -- receiver -------------------------------------------------------
    _CLOSE_MARKER = object()

    def on_data(self, data: IOBuf) -> None:
        with self._state_lock:
            if self.closed:
                return              # frame raced a cross-thread close:
                # on_closed already fired (or is firing), so delivering
                # now would violate the no-messages-after-closed contract
            if self._exec is None:
                # the linger keeps one consumer hot while frames stream
                # in serially (one per claim on the fabric path) —
                # without it every frame pays a tasklet spawn + park/wake
                self._exec = ExecutionQueue(self._consume_batch,
                                            linger_s=0.005)
            ex = self._exec
        ex.execute(data)

    def _consume_batch(self, it) -> None:
        msgs = []
        fire_closed = False
        for m in it:
            if m is Stream._CLOSE_MARKER:
                fire_closed = True
            else:
                msgs.append(m)
        handler = self.options.handler
        if msgs and handler is not None:
            try:
                handler.on_received_messages(self.sid, msgs)
            except Exception:
                from ..butil import logging as log
                log.error("stream handler raised", exc_info=True)
        if msgs:
            consumed = sum(len(m) for m in msgs)
            self._local_consumed += consumed
            # feedback when half a window was consumed since the last report
            if (self._local_consumed - self._last_feedback
                    >= self.options.max_buf_size // 2):
                self.send_feedback()
        if fire_closed and handler is not None:
            try:
                handler.on_closed(self.sid)
            except Exception:
                pass

    def send_feedback(self) -> None:
        self._last_feedback = self._local_consumed
        self._send_frame(FRAME_FEEDBACK, None,
                         consumed_bytes=self._local_consumed)

    # -- lifecycle ------------------------------------------------------
    def wait_connected(self, timeout: float = 10.0) -> bool:
        if self.connected:
            return True
        self._conn_butex.wait(0, timeout)
        return self.connected

    def mark_connected(self, remote_sid: int, socket) -> None:
        with self._state_lock:
            if self.connected or self.closed:
                # connected: both the RPC-response path and a racing
                # first stream frame call this — a second registration
                # would append a duplicate on_failed callback that
                # close() can never remove.  closed: the user closed the
                # stream before the handshake response landed — a
                # registration now would never be removed (review
                # findings)
                return
            self.remote_sid = remote_sid
            self.socket = socket
            self.connected = True
            # a dying host connection must close every stream riding it —
            # without this, a socket failure (EOF, bulk-plane death,
            # parse error) would strand the stream's consumer waiting
            # forever for data or on_closed.  The callback is REMOVED
            # again when the stream closes; registration happens INSIDE
            # the state lock so a racing close cannot null the slot
            # between it and the append (review findings)
            self._sock_failed_cb = lambda _s: self.on_remote_close()
            socket.on_failed_callbacks.append(self._sock_failed_cb)
        if socket.failed:                # lost the race with set_failed
            self.on_remote_close()
        self._conn_butex.wake_all_and_set(1)

    def close(self) -> None:
        with self._state_lock:
            if self.closed:
                return
            self.closed = True           # exactly-once transition: the
            # losing on_remote_close/close caller returns above instead
            # of double-firing _on_closed_local (review finding)
        if self.connected:
            try:
                self._send_frame(FRAME_CLOSE, None)
            except Exception:
                pass
        self._on_closed_local()

    def _on_closed_local(self) -> None:
        # published-but-unannounced ring frames must still be announced:
        # the receiver's stale-stream discard path claims and RELEASES
        # them, returning the ring space (otherwise those slots stay
        # parked until the whole socket dies)
        self._flush_pending()
        with self._state_lock:
            cb, self._sock_failed_cb = self._sock_failed_cb, None
            sock = self.socket
        if cb is not None and sock is not None:
            try:
                sock.on_failed_callbacks.remove(cb)
            except ValueError:
                pass                     # set_failed already consumed it
        self._writable_butex.wake_all_and_set(1)
        with self._state_lock:
            # self.closed is already True (set by every caller), so no
            # NEW queue can appear after this read — on_data drops
            # late frames instead
            ex = self._exec
        if ex is not None:
            # ordered after every queued data batch, then the queue stops
            ex.execute(Stream._CLOSE_MARKER)
            ex.stop()
        else:
            h = self.options.handler
            if h is not None:
                try:
                    h.on_closed(self.sid)
                except Exception:
                    pass
        _pool_remove(self.sid)

    def on_remote_close(self) -> None:
        with self._state_lock:
            if self.closed:
                return
            self.closed = True
        self._on_closed_local()

    # -- wire -----------------------------------------------------------
    def _send_frame(self, frame_type: int, data: Optional[IOBuf],
                    consumed_bytes: int = 0) -> None:
        from ..proto import rpc_meta_pb2 as meta_pb
        from ..policy.tpu_std import pack_frame
        sock = self.socket
        if sock is None:
            raise ConnectionError("stream not connected")
        payload = data if data is not None else IOBuf()
        # large DATA payloads ride a fast plane when the socket binds
        # one: the bytes go out-of-band under a reserved uuid and only a
        # 16-byte descriptor rides the control channel.  The ROUTE
        # (same-host shm ring vs the socket bulk conn) is the socket's
        # route-table decision (ici/route.py); sockets without a fast
        # plane (mem://, tcp://, in-process ici, or a fabric peer that
        # lacks the native core) return uuid 0 and the frame stays
        # inline — byte-identical to the pre-bulk wire.
        bulk_uuid = 0
        bulk_route = None
        if (frame_type == FRAME_DATA and len(payload)
                >= _flags.get_flag("ici_stream_bulk_threshold")):
            fast = getattr(sock, "stream_fast_begin", None)
            if fast is not None:
                # the stream id pins a striped shm plane's stripe —
                # per-stream ordering is decided by ONE ring
                bulk_uuid, bulk_route = fast(len(payload),
                                             affinity=self.sid)
            else:
                begin = getattr(sock, "stream_bulk_begin", None)
                if begin is not None:
                    bulk_uuid = begin()
                    if bulk_uuid:
                        bulk_route = "bulk"
        meta = meta_pb.RpcMeta()
        ss = meta.stream_settings
        ss.stream_id = self.remote_sid       # addressed to receiver's id
        ss.remote_stream_id = self.sid
        if consumed_bytes:
            ss.consumed_bytes = consumed_bytes
        bulk_exc = None
        rc = 0
        with self._wire_lock:
            if bulk_route == "shm":
                # RING route: bytes FIRST — publishing is a memcpy, not
                # a drained writev, so the descriptor can coalesce into
                # a batch (FRAME_DATA_SHM_BATCH) without delaying any
                # byte.  And because nothing references the frame until
                # its descriptor goes out, a failed publish falls back
                # to the next tier for THIS SAME FRAME — ring death
                # costs the sender zero stream casualties.
                try:
                    sock.stream_fast_send("shm", bulk_uuid, payload)
                except Exception:
                    rc = self._flush_desc_locked(sock)
                    bulk_uuid, bulk_route = 0, None
                    if rc == 0:
                        fast = getattr(sock, "stream_fast_begin", None)
                        if fast is not None:
                            bulk_uuid, bulk_route = fast(
                                len(payload), affinity=self.sid)
                    if bulk_route == "shm":
                        # the ring re-attached between degrade and
                        # re-screen: one more try, else next tier
                        try:
                            sock.stream_fast_send("shm", bulk_uuid,
                                                  payload)
                        except Exception:
                            bulk_uuid, bulk_route = 0, None
            if rc == 0 and bulk_route == "shm":
                self._pending_desc.append((bulk_uuid, len(payload)))
                if (len(self._pending_desc)
                        >= _flags.get_flag("ici_stream_desc_batch")):
                    rc = self._flush_desc_locked(sock)
                else:
                    self._arm_flush_timer(sock)
            elif rc == 0 and bulk_uuid:
                # socket bulk tier: descriptor FIRST, bulk bytes second
                # — the receiver parses the frame and parks in the claim
                # while the writev is still draining, overlapping its
                # per-frame Python work with the transfer.  A send that
                # fails after the descriptor went out degrades the
                # plane, which fails the peer's claim (-2) and with it
                # THIS stream (descriptor-consistency: no silent gap in
                # the stream's byte sequence) — the socket survives and
                # later frames ride the next tier until revival.
                # Pending shm descriptors flush first (stream order).
                rc = self._flush_desc_locked(sock)
                if rc == 0:
                    self._seq += 1
                    ss.frame_seq = self._seq
                    ss.frame_type = FRAME_DATA_BULK
                    desc = IOBuf(_BULK_DESC.pack(bulk_uuid, len(payload)))
                    rc = sock.write(pack_frame(meta, desc))
                if rc == 0:
                    try:
                        fast_send = getattr(sock, "stream_fast_send",
                                            None)
                        if fast_send is not None:
                            fast_send(bulk_route, bulk_uuid, payload)
                        else:
                            sock.stream_bulk_send(bulk_uuid, payload)
                    except Exception as e:
                        # descriptor went out but the payload never will:
                        # the peer's claim fails when the dead bulk conn
                        # cascades, but THIS end must not stay open with
                        # the frame's phantom bytes held against the
                        # window.  Handled OUTSIDE the wire lock —
                        # close() re-enters _send_frame for FRAME_CLOSE
                        # and the lock is not reentrant (review finding)
                        bulk_exc = e
            elif rc == 0:
                # inline frame (small DATA, FEEDBACK, CLOSE, RST):
                # pending shm descriptors flush first — the receiver
                # must learn of every preceding DATA frame before this
                # one (stream order; CLOSE after unflushed data would
                # drop the tail)
                rc = self._flush_desc_locked(sock)
                if rc == 0:
                    self._seq += 1
                    ss.frame_seq = self._seq
                    ss.frame_type = frame_type
                    rc = sock.write(pack_frame(meta, payload))
        if bulk_exc is not None:
            # the descriptor is on the wire but the payload never went.
            # A native write error already degraded the plane, but a
            # PYTHON-side failure (e.g. materializing a device block)
            # leaves it alive — sever it explicitly so the peer's pending
            # claim fails promptly (-2) and closes the peer's stream,
            # instead of stalling its control loop for the full claim
            # timeout (review finding)
            try:
                fast_abort = getattr(sock, "stream_fast_abort", None)
                if fast_abort is not None:
                    fast_abort(bulk_route)
                else:
                    abort = getattr(sock, "stream_bulk_abort", None)
                    if abort is not None:
                        abort()
            except Exception:
                pass
            self.close()
            raise bulk_exc
        if rc != 0:
            if frame_type == FRAME_DATA:
                # a refused DATA frame breaks the stream's byte sequence
                # (and on the bulk path would orphan a parked frame
                # through endless retries): fail the stream.  FEEDBACK is
                # cumulative — a transiently overcrowded socket just
                # re-reports with the next watermark, so it must NOT kill
                # a healthy stream (review finding).
                self.close()
            raise ConnectionError(f"stream write failed: {rc}")

    # -- shm descriptor batching -----------------------------------------
    # fablint: lock-held(_wire_lock)
    def _flush_desc_locked(self, sock) -> int:
        """Announce every published-but-unannounced ring frame in ONE
        control frame.  Caller holds _wire_lock.  Returns the socket
        write rc (0 when there was nothing to flush)."""
        if not self._pending_desc:
            return 0
        from ..proto import rpc_meta_pb2 as meta_pb
        from ..policy.tpu_std import pack_frame
        pending, self._pending_desc = self._pending_desc, []
        self._flush_gen += 1            # a parked linger timer is stale
        meta = meta_pb.RpcMeta()
        ss = meta.stream_settings
        ss.stream_id = self.remote_sid
        ss.remote_stream_id = self.sid
        self._seq += 1
        ss.frame_seq = self._seq
        # a lone descriptor goes out as plain FRAME_DATA_SHM (identical
        # 16-byte body) — the batch type is reserved for actual batches
        ss.frame_type = FRAME_DATA_SHM if len(pending) == 1 \
            else FRAME_DATA_SHM_BATCH
        body = IOBuf(b"".join(_BULK_DESC.pack(u, ln)
                              for u, ln in pending))
        return sock.write(pack_frame(meta, body))

    def _flush_pending(self) -> None:
        """Flush from outside the wire lock (linger timer, a writer
        about to park on a full window).  Write failures surface at the
        NEXT frame; the stream is usually dying already."""
        sock = self.socket
        if sock is None:
            return
        try:
            with self._wire_lock:
                self._flush_desc_locked(sock)
        except Exception:
            pass

    # fablint: lock-held(_wire_lock)
    def _arm_flush_timer(self, sock) -> None:
        """Caller holds _wire_lock: linger-flush a partial batch so a
        bursty-then-idle writer never strands announced-to-nobody
        frames (the window could never drain).  Armed once per batch;
        the generation check makes a timer whose batch already flushed
        a no-op.  The flush itself runs on a tasklet — a socket write
        must never run on the shared TimerThread."""
        if len(self._pending_desc) != 1:
            return
        gen = self._flush_gen
        from ..bthread.timer_thread import TimerThread

        def fire():
            # NO lock here: every RPC deadline rides the shared
            # TimerThread, and _wire_lock can be held for up to the shm
            # send timeout by a writer parked on a full ring.  The
            # staleness check is a lock-free int read (GIL-atomic;
            # _flush_gen only ever increments under the lock) — a stale
            # positive merely spawns a tasklet whose locked flush
            # no-ops on an empty pending list.
            if self._flush_gen != gen:
                return
            from ..bthread import scheduler
            scheduler.start_background(self._flush_pending,
                                       name="stream_desc_flush")

        TimerThread.instance().schedule_after(
            fire, _flags.get_flag("ici_stream_desc_flush_us") / 1e6)


# ---- stream registry (versioned ids like SocketId) ---------------------

# fablint custody contract (ISSUE 20): a registry slot handed out by
# get_resource comes back through return_resource exactly once (the
# versioned id rejects doubles); _pool_remove is the single drop point
# every close path funnels through.
_CUSTODY = {"get_resource": ("return_resource",)}

_streams: ResourcePool = ResourcePool()


def _pool_remove(sid: int) -> None:
    _streams.return_resource(sid)
    _ledger.release("stream", (sid,))


def stream_create(cntl, options: Optional[StreamOptions] = None) -> Stream:
    """Client side, before issuing the host RPC (StreamCreate
    stream.cpp:732)."""
    s = Stream(options or StreamOptions(), is_client=True)
    s.sid = _streams.get_resource(s)
    _ledger.acquire("stream", (s.sid,))
    cntl.stream_creator = s
    return s


def stream_accept(cntl, options: Optional[StreamOptions] = None) -> Stream:
    """Server side, inside the handler before done() (StreamAccept
    stream.cpp:756)."""
    s = Stream(options or StreamOptions(), is_client=False)
    s.sid = _streams.get_resource(s)
    _ledger.acquire("stream", (s.sid,))
    cntl.accepted_stream_id = s.sid
    return s


def find_stream(sid: int) -> Optional[Stream]:
    return _streams.address(sid)


def live_streams() -> List[Stream]:
    """Every registered (not yet closed-and-removed) stream — the server
    drain gate filters these down to the ones riding its connections."""
    return [s for s in _streams.live_payloads() if isinstance(s, Stream)]


def on_stream_frame(meta, body: IOBuf, socket) -> None:
    """Entry from tpu_std for frames carrying stream_settings.  Runs in
    the socket's reader-order consumption path (process_inline), so
    frames — including bulk claims — are resolved in cut order, which IS
    the stream's seq/byte order."""
    ss = meta.stream_settings
    s = find_stream(ss.stream_id)
    if s is None:
        if ss.frame_type in (FRAME_DATA_BULK, FRAME_DATA_SHM,
                             FRAME_DATA_SHM_BATCH):
            _discard_bulk_frame(ss.frame_type, body, socket)
        return                           # stale frame for a closed stream
    if not s.connected:
        s.mark_connected(ss.remote_stream_id, socket)
    if ss.frame_type == FRAME_DATA:
        s.on_data(body)
    elif ss.frame_type == FRAME_DATA_SHM_BATCH:
        # N coalesced ring descriptors: claim and deliver in order.  A
        # claim failure mid-batch keeps the delivered prefix (stream
        # order) and fails the stream exactly like a single-frame claim
        # failure below.
        raw = body.to_bytes()
        ok = True
        for off in range(0, len(raw), _BULK_DESC.size):
            uuid, blen = _BULK_DESC.unpack_from(raw, off)
            try:
                data = socket.stream_shm_claim(uuid, blen)
            except Exception as e:
                from ..butil import logging as log
                log.error("stream %d shm batch frame %#x unclaimable: %s",
                          s.sid, uuid, e)
                degrade = getattr(socket, "shm_plane_failed", None)
                try:
                    if degrade is not None:
                        degrade()
                        try:
                            s._send_frame(FRAME_RST, None)
                        except Exception:
                            pass
                    else:
                        socket.set_failed(
                            errors.EFAILEDSOCKET,
                            f"stream shm batch claim failed: {e}")
                finally:
                    s.on_remote_close()
                ok = False
                break
            s.on_data(data)
        if not ok:
            return
    elif ss.frame_type in (FRAME_DATA_BULK, FRAME_DATA_SHM):
        is_shm = ss.frame_type == FRAME_DATA_SHM
        uuid, blen = _BULK_DESC.unpack(body.to_bytes())
        try:
            if is_shm:
                data = socket.stream_shm_claim(uuid, blen)
            else:
                data = socket.stream_bulk_claim(uuid, blen)
        except Exception as e:
            # the fast plane died under the stream: this descriptor's
            # bytes will never arrive, and dropping the frame would
            # silently corrupt the byte stream — so THIS stream fails
            # (descriptor-consistency rule).  The socket survives: the
            # control channel is intact, later/other streams fall back
            # to the next tier, and the plane re-establishes in the
            # background (bulk_plane_failed / shm_plane_failed).
            # Sockets without a degradation hook keep the old
            # plane-death==socket-death contract.
            from ..butil import logging as log
            log.error("stream %d %s frame %#x unclaimable: %s",
                      s.sid, "shm" if is_shm else "bulk", uuid, e)
            degrade = getattr(
                socket,
                "shm_plane_failed" if is_shm else "bulk_plane_failed",
                None)
            try:
                if degrade is not None:
                    degrade()
                    # the socket survives, so the WRITER must be told its
                    # stream died (its bytes are gone) — otherwise it
                    # keeps writing into the void until its window wedges
                    try:
                        s._send_frame(FRAME_RST, None)
                    except Exception:
                        pass
                else:
                    socket.set_failed(errors.EFAILEDSOCKET,
                                      f"stream bulk claim failed: {e}")
            finally:
                s.on_remote_close()
            return
        s.on_data(data)
    elif ss.frame_type == FRAME_FEEDBACK:
        s.set_remote_consumed(ss.consumed_bytes)
    elif ss.frame_type in (FRAME_CLOSE, FRAME_RST):
        s.on_remote_close()


def _discard_bulk_frame(frame_type: int, body: IOBuf, socket) -> None:
    """A fast-plane descriptor addressed to a closed stream still has
    its payload parked (native frame map / shm ring slot) — claim and
    drop it, or it would pin a window's worth of receive buffers (or
    ring space) until the conn dies."""
    claim = getattr(socket, "stream_bulk_claim"
                    if frame_type == FRAME_DATA_BULK
                    else "stream_shm_claim", None)
    if claim is None:
        return
    raw = body.to_bytes()
    if frame_type != FRAME_DATA_SHM_BATCH and len(raw) != _BULK_DESC.size:
        return
    for off in range(0, len(raw) - _BULK_DESC.size + 1, _BULK_DESC.size):
        uuid, blen = _BULK_DESC.unpack_from(raw, off)
        try:
            claim(uuid, blen)
        except Exception:
            pass
