"""Streaming RPC: ordered byte/message streams attached to an RPC.

Reference: src/brpc/stream.{h,cpp} + policy/streaming_rpc_protocol.cpp
(SURVEY.md §3.4).  Semantics kept:

  * StreamCreate (client, stream.cpp:732) / StreamAccept (server, :756):
    the stream rides the host RPC's connection; ids are exchanged through
    RpcMeta.stream_settings (the reference's handshake).
  * Sliding window with consumed-bytes feedback: a writer may have at most
    ``max_buf_size`` unconsumed bytes in flight (AppendIfNotFull :274);
    the receiver reports consumption watermarks (SendFeedback :572) which
    wake blocked writers (SetRemoteConsumed :307).
  * Delivery through a per-stream ExecutionQueue so user handlers see
    ordered batches without blocking the socket reader (Consume :526).

Frames are tpu_std RpcMeta envelopes with ``stream_settings.frame_type``:
DATA / FEEDBACK / CLOSE; tpu_std routes them here from both server and
client parse paths.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..butil.iobuf import IOBuf
from ..butil.resource_pool import ResourcePool
from ..bthread.butex import Butex
from ..bthread.execution_queue import ExecutionQueue
from . import errors

FRAME_DATA = 0
FRAME_FEEDBACK = 1
FRAME_RST = 2
FRAME_CLOSE = 3

DEFAULT_MAX_BUF_SIZE = 2 * 1024 * 1024


class StreamOptions:
    def __init__(self, handler: Optional["StreamInputHandler"] = None,
                 max_buf_size: int = DEFAULT_MAX_BUF_SIZE,
                 messages_in_batch: int = 64):
        self.handler = handler
        self.max_buf_size = max_buf_size
        self.messages_in_batch = messages_in_batch


class StreamInputHandler:
    """User callback interface (reference StreamInputHandler)."""

    def on_received_messages(self, stream_id: int,
                             messages: List[IOBuf]) -> None:
        raise NotImplementedError

    def on_idle_timeout(self, stream_id: int) -> None:
        pass

    def on_closed(self, stream_id: int) -> None:
        pass


class Stream:
    def __init__(self, options: StreamOptions, is_client: bool):
        self.options = options
        self.is_client = is_client
        self.sid: int = 0               # local id (pool id)
        self.remote_sid: int = 0        # peer's id, set after handshake
        self.socket = None              # host connection
        self.connected = False
        self._conn_butex = Butex(0)
        # flow control (sender side)
        self._produced = 0
        self._remote_consumed = 0
        self._flow_lock = threading.Lock()
        self._writable_butex = Butex(0)
        # receiver side
        self._local_consumed = 0
        self._last_feedback = 0
        self.closed = False
        self._seq = 0
        self._exec: Optional[ExecutionQueue] = None

    # -- sender ---------------------------------------------------------
    def writable_bytes(self) -> int:
        with self._flow_lock:
            return self.options.max_buf_size - (self._produced
                                                - self._remote_consumed)

    def append_if_not_full(self, data: IOBuf) -> int:
        """0 ok; EAGAIN window full; EINVAL closed (stream.cpp:274)."""
        n = len(data)
        with self._flow_lock:
            if self.closed:
                return errors.EINVAL
            if self._produced - self._remote_consumed + n \
                    > self.options.max_buf_size:
                return errors.EAGAIN
            self._produced += n
        self._send_frame(FRAME_DATA, data)
        return 0

    def write(self, data: IOBuf, timeout: Optional[float] = None) -> int:
        """Blocking write: waits for window space (StreamWrite +
        StreamWait)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rc = self.append_if_not_full(data)
            if rc != errors.EAGAIN:
                return rc
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return errors.ETIMEDOUT
            self._writable_butex.set_value(0)
            if self.writable_bytes() > len(data) or self.closed:
                continue
            self._writable_butex.wait(0, remaining if remaining is not None
                                      else 1.0)

    def set_remote_consumed(self, consumed: int) -> None:
        """Feedback arrival: wake blocked writers (stream.cpp:307)."""
        with self._flow_lock:
            if consumed > self._remote_consumed:
                self._remote_consumed = consumed
        self._writable_butex.wake_all_and_set(1)

    # -- receiver -------------------------------------------------------
    _CLOSE_MARKER = object()

    def on_data(self, data: IOBuf) -> None:
        if self._exec is None:
            self._exec = ExecutionQueue(self._consume_batch)
        self._exec.execute(data)

    def _consume_batch(self, it) -> None:
        msgs = []
        fire_closed = False
        for m in it:
            if m is Stream._CLOSE_MARKER:
                fire_closed = True
            else:
                msgs.append(m)
        handler = self.options.handler
        if msgs and handler is not None:
            try:
                handler.on_received_messages(self.sid, msgs)
            except Exception:
                from ..butil import logging as log
                log.error("stream handler raised", exc_info=True)
        if msgs:
            consumed = sum(len(m) for m in msgs)
            self._local_consumed += consumed
            # feedback when half a window was consumed since the last report
            if (self._local_consumed - self._last_feedback
                    >= self.options.max_buf_size // 2):
                self.send_feedback()
        if fire_closed and handler is not None:
            try:
                handler.on_closed(self.sid)
            except Exception:
                pass

    def send_feedback(self) -> None:
        self._last_feedback = self._local_consumed
        self._send_frame(FRAME_FEEDBACK, None,
                         consumed_bytes=self._local_consumed)

    # -- lifecycle ------------------------------------------------------
    def wait_connected(self, timeout: float = 10.0) -> bool:
        if self.connected:
            return True
        self._conn_butex.wait(0, timeout)
        return self.connected

    def mark_connected(self, remote_sid: int, socket) -> None:
        self.remote_sid = remote_sid
        self.socket = socket
        self.connected = True
        self._conn_butex.wake_all_and_set(1)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self.connected:
            try:
                self._send_frame(FRAME_CLOSE, None)
            except Exception:
                pass
        self._on_closed_local()

    def _on_closed_local(self) -> None:
        self._writable_butex.wake_all_and_set(1)
        if self._exec is not None:
            # ordered after every queued data batch, then the queue stops
            self._exec.execute(Stream._CLOSE_MARKER)
            self._exec.stop()
        else:
            h = self.options.handler
            if h is not None:
                try:
                    h.on_closed(self.sid)
                except Exception:
                    pass
        _pool_remove(self.sid)

    def on_remote_close(self) -> None:
        if not self.closed:
            self.closed = True
            self._on_closed_local()

    # -- wire -----------------------------------------------------------
    def _send_frame(self, frame_type: int, data: Optional[IOBuf],
                    consumed_bytes: int = 0) -> None:
        from ..proto import rpc_meta_pb2 as meta_pb
        from ..policy.tpu_std import pack_frame
        if self.socket is None:
            raise ConnectionError("stream not connected")
        meta = meta_pb.RpcMeta()
        ss = meta.stream_settings
        ss.stream_id = self.remote_sid       # addressed to receiver's id
        ss.remote_stream_id = self.sid
        ss.frame_type = frame_type
        self._seq += 1
        ss.frame_seq = self._seq
        if consumed_bytes:
            ss.consumed_bytes = consumed_bytes
        payload = data if data is not None else IOBuf()
        rc = self.socket.write(pack_frame(meta, payload))
        if rc != 0:
            raise ConnectionError(f"stream write failed: {rc}")


# ---- stream registry (versioned ids like SocketId) ---------------------

_streams: ResourcePool = ResourcePool()
_registry_lock = threading.Lock()


def _pool_remove(sid: int) -> None:
    _streams.return_resource(sid)


def stream_create(cntl, options: Optional[StreamOptions] = None) -> Stream:
    """Client side, before issuing the host RPC (StreamCreate
    stream.cpp:732)."""
    s = Stream(options or StreamOptions(), is_client=True)
    s.sid = _streams.get_resource(s)
    cntl.stream_creator = s
    return s


def stream_accept(cntl, options: Optional[StreamOptions] = None) -> Stream:
    """Server side, inside the handler before done() (StreamAccept
    stream.cpp:756)."""
    s = Stream(options or StreamOptions(), is_client=False)
    s.sid = _streams.get_resource(s)
    cntl.accepted_stream_id = s.sid
    return s


def find_stream(sid: int) -> Optional[Stream]:
    return _streams.address(sid)


def on_stream_frame(meta, body: IOBuf, socket) -> None:
    """Entry from tpu_std for frames carrying stream_settings."""
    ss = meta.stream_settings
    s = find_stream(ss.stream_id)
    if s is None:
        return                           # stale frame for a closed stream
    if not s.connected:
        s.mark_connected(ss.remote_stream_id, socket)
    if ss.frame_type == FRAME_DATA:
        s.on_data(body)
    elif ss.frame_type == FRAME_FEEDBACK:
        s.set_remote_consumed(ss.consumed_bytes)
    elif ss.frame_type in (FRAME_CLOSE, FRAME_RST):
        s.on_remote_close()
