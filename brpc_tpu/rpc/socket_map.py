"""SocketMap: process-global EndPoint → single-connection cache.

Reference: src/brpc/socket_map.{h,cpp} (SocketMapInsert :82,
SingleConnection :180).  Channels to the same endpoint share one "single"
connection; pooled and short connections hang off it (GetPooledSocket).
Failed sockets are replaced on next use and handed to the health checker.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..butil.endpoint import EndPoint, SCHEME_MEM, SCHEME_TCP, SCHEME_ICI
from .socket import Socket


class _SingleConnection:
    def __init__(self):
        self.socket: Optional[Socket] = None
        self.pooled: List[Socket] = []       # idle pooled connections
        self.lock = threading.Lock()


class SocketMap:
    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._map: Dict[tuple, _SingleConnection] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "SocketMap":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = SocketMap()
            return cls._instance

    def _entry(self, ep: EndPoint,
               group: Any = "") -> _SingleConnection:
        # key = (endpoint, channel signature): channels speaking different
        # protocols to one endpoint must not share a connection, because
        # the peer locks each connection to the first detected protocol
        # (reference channel.cpp ComputeChannelSignature folds protocol
        # and auth into the SocketMapKey)
        key = (ep, group)
        with self._lock:
            e = self._map.get(key)
            if e is None:
                e = _SingleConnection()
                self._map[key] = e
            return e

    def get_socket(self, ep: EndPoint, messenger=None,
                   ssl_context=None, group: Any = "",
                   connect_timeout: float = 5.0) -> Socket:
        """The shared 'single' connection to ep (creates/replaces lazily)."""
        e = self._entry(ep, group)
        with e.lock:
            if e.socket is not None and not e.socket.failed \
                    and not e.socket.logoff:
                return e.socket
            s = self._checked_connect(ep, ssl_context, connect_timeout)
            s.messenger = messenger
            e.socket = s
            return s

    def get_pooled_socket(self, ep: EndPoint, messenger=None,
                          group: Any = "", ssl_context=None,
                          connect_timeout: float = 5.0) -> Socket:
        """An exclusive connection from the pool (reference
        GetPooledSocket); return it with return_pooled_socket."""
        e = self._entry(ep, group)
        with e.lock:
            while e.pooled:
                s = e.pooled.pop()
                if not s.failed and not s.logoff:
                    return s
        s = self._checked_connect(ep, ssl_context, connect_timeout)
        s.messenger = messenger
        return s

    @classmethod
    def _checked_connect(cls, ep: EndPoint, ssl_context=None,
                         connect_timeout: float = 5.0) -> Socket:
        """_connect, but an unreachable endpoint is handed to the health
        checker before the error propagates: the reference starts a
        health check whenever a connect fails, which keeps a DOWN
        endpoint under backoff probing across the whole outage (a failed
        connect creates no socket, so the socket-failure hand-off alone
        would miss retries issued while the peer is gone)."""
        try:
            return cls._connect(ep, ssl_context, connect_timeout)
        except Exception:
            try:
                from .health_check import start_health_check
                start_health_check(ep)
            except Exception:
                pass
            raise

    def return_pooled_socket(self, ep: EndPoint, s: Socket,
                             group: Any = "") -> None:
        if s.failed or s.logoff:
            return
        # do NOT auto-create the entry: close_endpoint() pops it, and a
        # pooled socket checked out across the close must be failed on
        # return, not resurrect the mapping (review finding)
        with self._lock:
            e = self._map.get((ep, group))
        if e is None:
            from . import errors
            s.set_failed(errors.ECLOSE, "endpoint closed while checked out")
            return
        with e.lock:
            e.pooled.append(s)

    def get_short_socket(self, ep: EndPoint, messenger=None,
                         ssl_context=None,
                         connect_timeout: float = 5.0) -> Socket:
        s = self._checked_connect(ep, ssl_context, connect_timeout)
        s.messenger = messenger
        return s

    @staticmethod
    def _connect(ep: EndPoint, ssl_context=None,
                 connect_timeout: float = 5.0) -> Socket:
        if ep.scheme == SCHEME_MEM:
            from .mem_transport import mem_connect
            return mem_connect(ep.host)
        if ep.scheme == SCHEME_TCP:
            from .tcp_transport import tcp_connect
            return tcp_connect(ep, timeout=connect_timeout,
                               ssl_context=ssl_context)
        if ep.scheme == SCHEME_ICI:
            # routes in-process targets through the zero-copy IciSocket,
            # remote (other-controller) ones through the fabric
            from ..ici.fabric import connect_any
            return connect_any(ep)
        raise ValueError(f"unsupported scheme {ep.scheme}")

    def remove(self, ep: EndPoint, group: Any = "") -> None:
        with self._lock:
            self._map.pop((ep, group), None)

    def close_endpoint(self, ep: EndPoint, group: Any = "") -> None:
        """Fail and drop every connection held for (ep, group): client
        teardown (Channel.close).  ECLOSE keeps the endpoint out of
        health-check revival — this is a deliberate local close, not a
        peer failure."""
        with self._lock:
            e = self._map.pop((ep, group), None)
        if e is None:
            return
        with e.lock:
            socks = list(e.pooled)
            if e.socket is not None:
                socks.append(e.socket)
            e.socket = None
            e.pooled = []
        from . import errors
        for s in socks:
            try:
                s.set_failed(errors.ECLOSE, "channel closed")
            except Exception:
                pass

    def stats(self) -> Dict[EndPoint, int]:
        with self._lock:
            out: Dict[EndPoint, int] = {}
            for (ep, _group), e in self._map.items():
                out[ep] = out.get(ep, 0) + \
                    (0 if e.socket is None or e.socket.failed else 1) + \
                    len(e.pooled)
            return out
