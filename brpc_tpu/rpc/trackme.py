"""trackme — library-version pings to a central bulletin server.

Reference: src/brpc/trackme.cpp (TrackMe() at :36; pings are sent from a
dedicated channel on server start and then every `interval` seconds; the
server can answer with a severity + bulletin text + new interval).  The
reference ships pointing at a Baidu-internal address and is disabled
outside; this build keeps the capability but is OFF unless the
``trackme_server`` flag names a server (tools/trackme_server.py is the
receiving end, mirroring tools/trackme_server/)."""
from __future__ import annotations

import threading
from typing import Optional

from ..butil import flags as _flags
from ..butil import logging as log
from ..proto.trackme_pb2 import (TrackMeRequest, TrackMeResponse,
                                 TRACKME_FATAL, TRACKME_WARNING)

_flags.define_flag("trackme_server", "",
                   "address of the trackme bulletin server; empty = off")
_flags.define_flag("trackme_interval", 30,
                   "seconds between trackme pings")

RPC_VERSION = 1000          # bumped on wire-visible framework changes

_lock = threading.Lock()
_pinger: Optional["_Pinger"] = None


class _Pinger:
    def __init__(self, target: str, server_addr: str):
        self.target = target
        self.server_addr = server_addr
        self._stop = threading.Event()
        # fablint: thread-quiesced(stop() sets _stop; the ping loop waits on it between pings and exits promptly)
        self._thread = threading.Thread(target=self._run,
                                        name="trackme", daemon=True)
        self._thread.start()

    def _ping_once(self) -> Optional[int]:
        from .channel import Channel, ChannelOptions
        from .controller import Controller
        ch = Channel()
        ch.init(self.target, options=ChannelOptions(timeout_ms=2000))
        cntl = Controller()
        req = TrackMeRequest(rpc_version=RPC_VERSION,
                             server_addr=self.server_addr)
        resp = ch.call_method("TrackMeService.TrackMe", cntl, req,
                              TrackMeResponse)
        if cntl.failed():
            return None
        if resp.severity == TRACKME_FATAL:
            log.error("trackme bulletin (FATAL): %s", resp.error_text)
        elif resp.severity == TRACKME_WARNING:
            log.warning("trackme bulletin: %s", resp.error_text)
        return resp.new_interval or None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                new_interval = self._ping_once()
            except Exception as e:
                log.warning("trackme ping failed: %s", e)
                new_interval = None
            interval = new_interval or _flags.get_flag("trackme_interval")
            if self._stop.wait(max(1, int(interval))):
                return

    def stop(self) -> None:
        self._stop.set()


def start_trackme(server_addr: str = "") -> bool:
    """Called on Server.start (trackme.cpp StartTrackMe); no-op unless
    the trackme_server flag is set.  Returns True when a pinger runs."""
    global _pinger
    target = _flags.get_flag("trackme_server")
    if not target:
        return False
    with _lock:
        if _pinger is None:
            _pinger = _Pinger(target, server_addr)
    return True


def stop_trackme() -> None:
    global _pinger
    with _lock:
        if _pinger is not None:
            _pinger.stop()
            _pinger = None
