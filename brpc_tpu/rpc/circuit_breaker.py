"""Circuit breaker + cluster recover policy.

Reference: src/brpc/circuit_breaker.h:25-85 (EMA error windows, doubling
isolation) and cluster_recover_policy.h:39-82 (don't stampede a shrunken
cluster).  A breaker per endpoint tracks short/long EMA error rates; when
either trips, the node is isolated for ``isolation_duration`` (doubling up
to a cap on repeated trips, halving back after quiet recovery).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..butil.endpoint import EndPoint
from ..butil import flags as _flags

_flags.define_flag("circuit_breaker_short_window_size", 30,
                   "samples in the short EMA window", _flags.positive_integer)
_flags.define_flag("circuit_breaker_long_window_size", 300,
                   "samples in the long EMA window", _flags.positive_integer)
_flags.define_flag("circuit_breaker_max_error_rate", 0.5,
                   "short-window error rate that trips the breaker")
_flags.define_flag("circuit_breaker_long_error_rate", 0.2,
                   "long-window error rate that trips the breaker")
_flags.define_flag("circuit_breaker_min_isolation_duration_ms", 100,
                   "first isolation duration", _flags.positive_integer)
_flags.define_flag("circuit_breaker_max_isolation_duration_ms", 30000,
                   "isolation duration cap", _flags.positive_integer)


class CircuitBreaker:
    def __init__(self):
        self._short_ema = 0.0
        self._long_ema = 0.0
        self._short_alpha = 1.0 / _flags.get_flag(
            "circuit_breaker_short_window_size")
        self._long_alpha = 1.0 / _flags.get_flag(
            "circuit_breaker_long_window_size")
        self._lock = threading.Lock()
        self._isolated_until = 0.0
        self._isolation_ms = _flags.get_flag(
            "circuit_breaker_min_isolation_duration_ms")
        self._samples = 0

    def on_call_end(self, error_code: int) -> bool:
        """Record a call; returns False if this call TRIPPED the breaker."""
        err = 1.0 if error_code != 0 else 0.0
        with self._lock:
            self._samples += 1
            self._short_ema += self._short_alpha * (err - self._short_ema)
            self._long_ema += self._long_alpha * (err - self._long_ema)
            if self._samples < 5:
                return True
            if (self._short_ema > _flags.get_flag("circuit_breaker_max_error_rate")
                    or self._long_ema > _flags.get_flag(
                        "circuit_breaker_long_error_rate")):
                now = time.monotonic()
                if now >= self._isolated_until:
                    self._isolated_until = now + self._isolation_ms / 1000.0
                    self._isolation_ms = min(
                        self._isolation_ms * 2,
                        _flags.get_flag("circuit_breaker_max_isolation_duration_ms"))
                    self._short_ema = 0.0   # start fresh after isolation
                    self._samples = 0
                return False
            return True

    def is_isolated(self) -> bool:
        with self._lock:
            return time.monotonic() < self._isolated_until

    def isolated_until(self) -> float:
        with self._lock:
            return self._isolated_until

    def mark_recovered(self) -> None:
        with self._lock:
            self._isolated_until = 0.0
            self._isolation_ms = max(
                self._isolation_ms // 2,
                _flags.get_flag("circuit_breaker_min_isolation_duration_ms"))
            self._short_ema = self._long_ema = 0.0
            self._samples = 0


class ClusterRecoverPolicy:
    """Refuse to dogpile a cluster that shrank below min_working_instances
    (cluster_recover_policy.h)."""

    def __init__(self, min_working_instances: int = 1,
                 hold_seconds: float = 2.0):
        self.min_working = min_working_instances
        self.hold_seconds = hold_seconds
        self._recovering_since: Optional[float] = None
        self._lock = threading.Lock()

    def on_cluster_size(self, working: int, total: int) -> bool:
        """True → cluster usable; False → in recovery hold-off (callers
        should fail fast instead of stampeding)."""
        with self._lock:
            if working >= max(self.min_working, 1):
                self._recovering_since = None
                return True
            now = time.monotonic()
            if self._recovering_since is None:
                self._recovering_since = now
                return False
            return (now - self._recovering_since) >= self.hold_seconds


class BreakerRegistry:
    _instance = None
    _ilock = threading.Lock()

    def __init__(self):
        self._map: Dict[EndPoint, CircuitBreaker] = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls) -> "BreakerRegistry":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = BreakerRegistry()
            return cls._instance

    def breaker(self, ep: EndPoint) -> CircuitBreaker:
        with self._lock:
            b = self._map.get(ep)
            if b is None:
                b = CircuitBreaker()
                self._map[ep] = b
            return b
