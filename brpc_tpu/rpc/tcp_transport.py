"""TCP transport (the DCN / inter-slice path) + Acceptor.

Reference: Socket fd IO (socket.cpp DoWrite :1790 writev batching,
HandleEpollOut :1336) and Acceptor (acceptor.cpp OnNewConnections :243,327).
Non-blocking fds driven by the EventDispatcher; KeepWrite blocks on a butex
that EPOLLOUT wakes.  TLS (reference details/ssl_helper.cpp + Socket SSL
state machine): pass an ``ssl.SSLContext`` — the handshake runs blocking at
connect/accept, then the wrapped socket joins the normal non-blocking loop
(SSLWantRead/WriteError map to EAGAIN).
"""
from __future__ import annotations

import socket as pysocket
import threading
from typing import Callable, Optional

from ..butil.endpoint import EndPoint, SCHEME_TCP
from ..butil.iobuf import IOBuf, IOPortal
from ..bthread.butex import Butex
from . import errors
from .socket import Socket


class TcpSocket(Socket):
    def __init__(self, sock: pysocket.socket,
                 remote_side: Optional[EndPoint] = None):
        super().__init__(remote_side)
        self.sock = sock
        self.sock.setblocking(False)
        try:
            self.sock.setsockopt(pysocket.IPPROTO_TCP, pysocket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._writable_butex = Butex(0)
        try:
            h, p = sock.getsockname()[:2]
            self.local_side = EndPoint(scheme=SCHEME_TCP, host=h, port=p)
        except OSError:
            pass

    def register_with_dispatcher(self) -> None:
        from .event_dispatcher import get_global_dispatcher
        self._dispatcher = get_global_dispatcher(self.sock.fileno())
        self._dispatcher.add_consumer(self.sock.fileno(), self.id)

    # transport hooks ---------------------------------------------------
    def _do_write(self, data: IOBuf) -> int:
        import ssl as _ssl
        if isinstance(self.sock, _ssl.SSLSocket):
            # SSL sockets cannot writev raw fds: send per-view
            views = data.host_views()
            if not views:
                return 0
            try:
                n = self.sock.send(views[0])
            except (_ssl.SSLWantWriteError, _ssl.SSLWantReadError,
                    BlockingIOError, InterruptedError):
                return -1
            if n > 0:
                data.pop_front(n)
            return n
        try:
            return data.cut_into_file_descriptor(self.sock.fileno())
        except (BlockingIOError, InterruptedError):
            return -1

    def _do_read(self, portal: IOPortal, max_count: int) -> int:
        import ssl as _ssl
        if isinstance(self.sock, _ssl.SSLSocket):
            try:
                chunk = self.sock.recv(max_count)
            except (_ssl.SSLWantReadError, _ssl.SSLWantWriteError,
                    BlockingIOError, InterruptedError):
                return -1
            if not chunk:
                return 0
            portal.append(chunk)
            return len(chunk)
        return portal.append_from_socket(self.sock, max_count)

    def _wait_writable(self, timeout: float = 30.0) -> bool:
        self._writable_butex.set_value(0)
        self._dispatcher.add_epollout(self.sock.fileno(), self.id)
        rc = self._writable_butex.wait(0, timeout)
        return rc == 0 and not self.failed

    def handle_epollout(self) -> None:
        self._writable_butex.wake_all_and_set(1)

    def _transport_close(self) -> None:
        disp = getattr(self, "_dispatcher", None)
        if disp is not None:
            disp.remove_consumer(self.sock.fileno())
        self._writable_butex.wake_all_and_set(1)
        try:
            self.sock.close()
        except OSError:
            pass


def tcp_connect(ep: EndPoint, timeout: float = 5.0,
                ssl_context=None, server_hostname: str = "") -> TcpSocket:
    raw = pysocket.create_connection((ep.host, ep.port), timeout=timeout)
    if ssl_context is not None:
        raw.settimeout(timeout)
        raw = ssl_context.wrap_socket(
            raw, server_hostname=server_hostname or ep.host)
    s = TcpSocket(raw, remote_side=ep)
    s.register_with_dispatcher()
    return s


class Acceptor:
    """Listener: accepts until EAGAIN, wraps each connection in a TcpSocket
    bound to the server's InputMessenger (acceptor.cpp)."""

    def __init__(self, on_accept: Callable[[TcpSocket], None],
                 ssl_context=None):
        self.ssl_context = ssl_context
        self.on_accept = on_accept
        self.listen_sock: Optional[pysocket.socket] = None
        self.port = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self.connection_count = 0

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        ls = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
        ls.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_REUSEADDR, 1)
        ls.bind((host, port))
        ls.listen(128)
        self.listen_sock = ls
        self.port = ls.getsockname()[1]
        # a dedicated thread standing in for the listen-fd dispatcher event
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="acceptor", daemon=True)
        self._thread.start()
        return self.port

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                self.listen_sock.settimeout(0.5)
                conn, addr = self.listen_sock.accept()
            except pysocket.timeout:
                continue
            except OSError:
                return
            if self.ssl_context is not None:
                try:
                    conn.settimeout(5.0)
                    conn = self.ssl_context.wrap_socket(conn,
                                                        server_side=True)
                except Exception:
                    try:
                        conn.close()
                    except OSError:
                        pass
                    continue
            s = TcpSocket(conn, remote_side=EndPoint(
                scheme=SCHEME_TCP, host=addr[0], port=addr[1]))
            s.is_server_side = True
            self.connection_count += 1
            try:
                self.on_accept(s)
                s.register_with_dispatcher()
                s.start_input_event()   # data may already be buffered
            except Exception:
                s.set_failed(errors.EINTERNAL, "accept handling failed")

    def stop(self) -> None:
        self._stop = True
        if self.listen_sock is not None:
            try:
                self.listen_sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2)
