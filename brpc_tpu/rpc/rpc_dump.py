"""rpc_dump: sampled capture of inbound requests for replay debugging.

Reference: src/brpc/rpc_dump.{h,cpp} — when ``rpc_dump`` is on, a sampled
subset of requests (speed-limited through the bvar Collector) is appended to
size-capped files under ``rpc_dump_dir``; tools/rpc_replay reads them back
and fires them at a server.  The record format here is the tpu_std frame
itself (magic+meta+payload), so a dump file is literally a byte-stream a
socket could replay.
"""
from __future__ import annotations

import os
import threading
from typing import List, Optional

from ..butil import flags as _flags
from ..butil.iobuf import IOBuf
from .. import bvar

_flags.define_flag("rpc_dump", False, "capture sampled requests to disk")
_flags.define_flag("rpc_dump_dir", "./rpc_dump", "dump output directory")
_flags.define_flag("rpc_dump_max_files", 4, "rotated dump files kept",
                   _flags.positive_integer)
_flags.define_flag("rpc_dump_max_requests_in_one_file", 1000,
                   "requests per file before rotation",
                   _flags.positive_integer)

_speed_limit = bvar.CollectorSpeedLimit(max_samples_per_second=100)
_lock = threading.Lock()
_current_file = None
_current_count = 0
_file_index = 0
dumped_count = bvar.Adder("rpc_dump_count")


def dump_enabled() -> bool:
    return bool(_flags.get_flag("rpc_dump"))


def maybe_dump_request(frame: IOBuf) -> bool:
    """Called by protocols with the complete wire frame of a request."""
    global _current_file, _current_count, _file_index
    if not dump_enabled() or not _speed_limit.is_sampled():
        return False
    data = frame.to_bytes()
    with _lock:
        if _current_file is None or _current_count >= _flags.get_flag(
                "rpc_dump_max_requests_in_one_file"):
            _rotate_locked()
        try:
            _current_file.write(data)
            _current_file.flush()
            _current_count += 1
        except OSError:
            return False
    dumped_count << 1
    return True


def _rotate_locked() -> None:
    global _current_file, _current_count, _file_index
    d = _flags.get_flag("rpc_dump_dir")
    os.makedirs(d, exist_ok=True)
    if _current_file is not None:
        _current_file.close()
    path = os.path.join(d, f"requests.{_file_index:06d}")
    _current_file = open(path, "wb")
    _current_count = 0
    _file_index += 1
    # prune old files
    keep = _flags.get_flag("rpc_dump_max_files")
    files = sorted(f for f in os.listdir(d) if f.startswith("requests."))
    for old in files[:-keep] if len(files) > keep else []:
        try:
            os.unlink(os.path.join(d, old))
        except OSError:
            pass


def list_dump_files(directory: Optional[str] = None) -> List[str]:
    d = directory or _flags.get_flag("rpc_dump_dir")
    if not os.path.isdir(d):
        return []
    return sorted(os.path.join(d, f) for f in os.listdir(d)
                  if f.startswith("requests."))


# ---- fabric plane-frame traces (the plane-health A/B parity seam) ------
#
# The plane-health refactor promises the bulk/shm revival handshakes
# stay frame-for-frame identical on the wire.  That claim is PROVEN,
# not assumed: when ``rpc_dump`` is on, every plane-healing control
# frame a fabric socket sends or receives is appended (JSON lines) to
# ``fabric_planes.trace`` under ``rpc_dump_dir``; the parity test
# compares the recorded sequences against goldens.  The CALLER filters
# to the eight self-healing frame types (never DATA/CREDIT), so the
# hook costs one set-membership test per control frame when off.

_FABRIC_TRACE_NAME = "fabric_planes.trace"
_fab_trace_lock = threading.Lock()


def maybe_dump_fabric_frame(sock, direction: str, ftype: int,
                            body: bytes) -> bool:
    """Append one fabric plane-healing control frame to the trace
    (JSON line: socket id, direction "in"/"out", ftype, body hex)."""
    if not dump_enabled():
        return False
    import json
    rec = json.dumps({"sock": getattr(sock, "id", 0),
                      "dir": direction, "ftype": ftype,
                      "body": body.hex()})
    d = _flags.get_flag("rpc_dump_dir")
    with _fab_trace_lock:
        try:
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, _FABRIC_TRACE_NAME), "a") as f:
                f.write(rec + "\n")
        except OSError:
            return False
    return True


def load_fabric_trace(directory: Optional[str] = None) -> List[dict]:
    """Read the plane-frame trace back as dicts in wire order (empty
    when no trace was recorded)."""
    import json
    d = directory or _flags.get_flag("rpc_dump_dir")
    path = os.path.join(d, _FABRIC_TRACE_NAME)
    if not os.path.isfile(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def load_dumped_frames(path: str) -> List[bytes]:
    """Split a dump file back into frames (parse by header sizes)."""
    from ..policy.tpu_std import MAGIC, HEADER_SIZE
    frames = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos + HEADER_SIZE <= len(data):
        if data[pos:pos + 4] != MAGIC:
            break
        meta_size = int.from_bytes(data[pos + 4:pos + 8], "big")
        body_size = int.from_bytes(data[pos + 8:pos + 12], "big")
        end = pos + HEADER_SIZE + meta_size + body_size
        if end > len(data):
            break
        frames.append(data[pos:end])
        pos = end
    return frames
