"""Fault injection — drop/delay/sever writes to exercise resilience.

The reference ships no built-in fault injection (SURVEY.md §5.3: tests
kill in-process servers); this module goes one step further so retry,
backup-request, health-check, and circuit-breaker machinery can be
exercised deterministically.  Faults act at the Socket.write boundary —
the same place a lossy or partitioned network would.

    from brpc_tpu.rpc import fault_injection as fi
    with fi.inject(fi.FaultInjector(drop_ratio=1.0,
                                    match=lambda s: s.remote_side == ep)):
        ...   # every write toward ep silently vanishes

Deterministic given a seed; thread-safe; uninstalls on context exit.

Beyond the Socket.write boundary, ``FabricFaultPlan`` reaches the two
planes of a cross-process ici:// fabric socket (ici/fabric.py):

  * the CONTROL channel (sever after the Nth outbound frame, count
    inbound frames and kill the process — "peer crash"),
  * the native BULK plane (sever now / after a payload-byte watermark
    lands mid-``writev``, drop or delay parked frames — wired through
    ``native/fabric.cpp``'s ``brpc_tpu_fab_chaos``), and
  * the HELLO / bulk re-establishment handshakes (refuse the next N).

Every knob is a count, byte watermark, or seeded ratio — a plan with a
fixed seed injects the identical fault sequence on every run, which is
what lets the chaos tests drive recovery paths deterministically in
tier-1.  Plans are scoped with ``inject_fabric`` (or ``install_fabric``)
and leak no state once uninstalled.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

PASS = "pass"
DROP = "drop"          # bytes vanish (lossy link / partition)
ERROR = "error"        # connection severed (peer reset)


class FaultInjector:
    def __init__(self, drop_ratio: float = 0.0, error_ratio: float = 0.0,
                 delay_ms: float = 0.0, seed: int = 0,
                 match: Optional[Callable] = None):
        self.drop_ratio = drop_ratio
        self.error_ratio = error_ratio
        self.delay_ms = delay_ms
        self.match = match
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected = {DROP: 0, ERROR: 0, "delayed": 0}

    def decide(self, socket) -> str:
        if self.match is not None and not self.match(socket):
            return PASS
        with self._lock:
            r = self._rng.random()
            if r < self.drop_ratio:
                self.injected[DROP] += 1
                return DROP
            if r < self.drop_ratio + self.error_ratio:
                self.injected[ERROR] += 1
                return ERROR
            if self.delay_ms > 0:
                self.injected["delayed"] += 1
        if self.delay_ms > 0:
            time.sleep(self.delay_ms / 1000.0)
        return PASS


_active: Optional[FaultInjector] = None


def install(injector: Optional[FaultInjector]) -> None:
    global _active
    _active = injector


def active() -> Optional[FaultInjector]:
    return _active


class inject:
    """Context manager: install for the with-block, restore after."""

    def __init__(self, injector: FaultInjector):
        self.injector = injector
        self._prev: Optional[FaultInjector] = None

    def __enter__(self) -> FaultInjector:
        self._prev = _active
        install(self.injector)
        return self.injector

    def __exit__(self, *exc) -> None:
        install(self._prev)


# ---- fabric chaos plans -------------------------------------------------

# native chaos modes (native/fabric.cpp brpc_tpu_fab_chaos; the shm
# twin brpc_tpu_shm_chaos shares the numbering, DELAY excepted)
CHAOS_CLEAR = 0
CHAOS_SEVER_AFTER_OUT_BYTES = 1
CHAOS_DROP_FRAMES = 2
CHAOS_DELAY_PARK_MS = 3
CHAOS_SEVER_NOW = 4


class FabricFaultPlan:
    """A deterministic fault plan for ici:// fabric sockets.

    All knobs are counts/watermarks (exact) or ratios drawn from a
    seeded RNG (reproducible), and apply only to sockets accepted by
    ``match`` (default: every fabric socket).  Consulted by
    ``ici/fabric.py`` at well-defined points:

      control_sever_after_frames  sever the control TCP after this many
                                  outbound control frames (0/None = off)
      control_drop_ratio          seeded per-frame drop of outbound
                                  control frames (a lossy control link)
      die_after_control_frames    os._exit(137) after this many INBOUND
                                  control frames — the "peer process
                                  killed" fault, installed in the victim
      bulk_sever_now              sever the bulk conn the moment it is
                                  (re)attached — bulk-plane death with a
                                  live control channel
      bulk_sever_after_bytes      native watermark: the write that
                                  crosses it is truncated mid-writev
      bulk_drop_frames            native: next N received bulk frames
                                  vanish before parking (descriptor
                                  arrives, claim never satisfied)
      bulk_delay_park_ms          native: park received bulk frames only
                                  after this many ms (descriptor/claim
                                  skew)
      refuse_bulk_handshakes      refuse the next N bulk-plane
                                  (re)establishment handshakes
      refuse_hellos               server refuses the next N control
                                  HELLOs with HELLO_ERR
      device_plane_fail_posts     refuse the next N device-plane
                                  post_send WRs (before any descriptor
                                  exists) — forces the device plane to
                                  degrade to the bulk/inline fallback
      shm_kill_now                mark the shm ring segment dead the
                                  moment it is (re)attached — the
                                  "segment killed" fault; descriptors
                                  fall back to the socket bulk tier
      shm_sever_after_bytes       native watermark: the ring write that
                                  crosses it copies a PARTIAL slot and
                                  dies without publishing — the
                                  producer-crash-mid-slot shape
      shm_drop_frames             native: next N ring frames vanish at
                                  the receiver's scan (descriptor
                                  arrives, claim never satisfied)
      refuse_shm_handshakes       refuse the next N shm attach
                                  handshakes (HELLO piggyback or
                                  _F_SHM_REESTABLISH)
      collective_kill_device      refuse every compiled fan-out whose
                                  participant set contains this device —
                                  the "member killed mid-fan-out" fault:
                                  the collective route degrades in-call
                                  to per-member RPCs and revives only on
                                  an epoch bump (clear the plan + the
                                  member re-advertises)
      collective_fail_execs       refuse the next N compiled fan-out
                                  executions regardless of participants
                                  (transient execution failure)
      collective_drop_announces   silently swallow the next N fan-out
                                  announces (black-hole: the member
                                  never sees the call; the client times
                                  out with R_ANNOUNCE and degrades the
                                  collective route in-call)
      xfer_refuse_stages          refuse the next N transfer-server
                                  stages — the xfer route degrades
                                  in-frame to inline before any
                                  descriptor exists
      plane_slow_ms               {plane: ms} SLOW injector — one
                                  python-level sleep per op on that
                                  plane ("slow, not dead": a correct
                                  health machine must NOT degrade it)

    ``injected`` counts what actually fired, keyed by knob name."""

    def __init__(self, seed: int = 0,
                 match: Optional[Callable] = None,
                 control_sever_after_frames: int = 0,
                 control_drop_ratio: float = 0.0,
                 die_after_control_frames: int = 0,
                 bulk_sever_now: bool = False,
                 bulk_sever_after_bytes: int = 0,
                 bulk_drop_frames: int = 0,
                 bulk_delay_park_ms: int = 0,
                 refuse_bulk_handshakes: int = 0,
                 refuse_hellos: int = 0,
                 device_plane_fail_posts: int = 0,
                 shm_kill_now: bool = False,
                 shm_sever_after_bytes: int = 0,
                 shm_drop_frames: int = 0,
                 refuse_shm_handshakes: int = 0,
                 collective_kill_device: Optional[int] = None,
                 collective_fail_execs: int = 0,
                 collective_drop_announces: int = 0,
                 xfer_refuse_stages: int = 0,
                 plane_slow_ms: Optional[dict] = None):
        self.match = match
        self.control_sever_after_frames = control_sever_after_frames
        self.control_drop_ratio = control_drop_ratio
        self.die_after_control_frames = die_after_control_frames
        self.bulk_sever_now = bulk_sever_now
        self.bulk_sever_after_bytes = bulk_sever_after_bytes
        self.bulk_drop_frames = bulk_drop_frames
        self.bulk_delay_park_ms = bulk_delay_park_ms
        self._refuse_bulk = refuse_bulk_handshakes
        self._refuse_hellos = refuse_hellos
        self._fail_device_posts = device_plane_fail_posts
        self.shm_kill_now = shm_kill_now
        self.shm_sever_after_bytes = shm_sever_after_bytes
        self.shm_drop_frames = shm_drop_frames
        self._refuse_shm = refuse_shm_handshakes
        self.collective_kill_device = collective_kill_device
        self._fail_coll_execs = collective_fail_execs
        self._drop_announces = collective_drop_announces
        self._refuse_xfer = xfer_refuse_stages
        self.plane_slow_ms = dict(plane_slow_ms or {})
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._ctrl_out = 0           # outbound control frames seen
        self._ctrl_in = 0            # inbound control frames seen
        self.injected = {"control_sever": 0, "control_drop": 0,
                         "bulk_chaos": 0, "refuse_bulk": 0,
                         "refuse_hello": 0, "die": 0, "device_plane": 0,
                         "shm_chaos": 0, "refuse_shm": 0, "collective": 0,
                         "coll_announce_drop": 0, "xfer": 0,
                         "plane_slow": 0}

    def _matches(self, socket) -> bool:
        return self.match is None or bool(self.match(socket))

    # -- control channel hooks (called from FabricSocket) ----------------
    def on_control_send(self, socket) -> str:
        """PASS / DROP / ERROR for one outbound control frame."""
        if not self._matches(socket):
            return PASS
        with self._lock:
            self._ctrl_out += 1
            if (self.control_sever_after_frames
                    and self._ctrl_out >= self.control_sever_after_frames):
                self.control_sever_after_frames = 0   # fire once
                self.injected["control_sever"] += 1
                return ERROR
            if (self.control_drop_ratio
                    and self._rng.random() < self.control_drop_ratio):
                self.injected["control_drop"] += 1
                return DROP
        return PASS

    def on_control_recv(self, socket) -> None:
        """Counts inbound control frames; kills the process at the
        configured count (the deterministic "peer crash" fault)."""
        if not self.die_after_control_frames or not self._matches(socket):
            return
        with self._lock:
            self._ctrl_in += 1
            if self._ctrl_in < self.die_after_control_frames:
                return
            self.injected["die"] += 1
        import os
        os._exit(137)

    # -- bulk plane hooks ------------------------------------------------
    def on_bulk_attach(self, socket, lib, handle: int) -> None:
        """Applies the native chaos knobs to a just-attached bulk conn."""
        if not handle or lib is None or not self._matches(socket):
            return
        fired = False
        if self.bulk_sever_after_bytes:
            lib.brpc_tpu_fab_chaos(handle, CHAOS_SEVER_AFTER_OUT_BYTES,
                                   self.bulk_sever_after_bytes)
            fired = True
        if self.bulk_drop_frames:
            lib.brpc_tpu_fab_chaos(handle, CHAOS_DROP_FRAMES,
                                   self.bulk_drop_frames)
            fired = True
        if self.bulk_delay_park_ms:
            lib.brpc_tpu_fab_chaos(handle, CHAOS_DELAY_PARK_MS,
                                   self.bulk_delay_park_ms)
            fired = True
        if self.bulk_sever_now:
            lib.brpc_tpu_fab_chaos(handle, CHAOS_SEVER_NOW, 0)
            fired = True
        if fired:
            with self._lock:
                self.injected["bulk_chaos"] += 1

    def on_shm_attach(self, socket, lib, handle: int) -> None:
        """Applies the native shm chaos knobs to a just-attached ring."""
        if not handle or lib is None or not self._matches(socket):
            return
        fired = False
        if self.shm_sever_after_bytes:
            lib.brpc_tpu_shm_chaos(handle, CHAOS_SEVER_AFTER_OUT_BYTES,
                                   self.shm_sever_after_bytes)
            fired = True
        if self.shm_drop_frames:
            lib.brpc_tpu_shm_chaos(handle, CHAOS_DROP_FRAMES,
                                   self.shm_drop_frames)
            fired = True
        if self.shm_kill_now:
            lib.brpc_tpu_shm_chaos(handle, CHAOS_SEVER_NOW, 0)
            fired = True
        if fired:
            with self._lock:
                self.injected["shm_chaos"] += 1

    # -- handshake hooks -------------------------------------------------
    def on_shm_handshake(self, socket=None) -> bool:
        """True → refuse this shm segment attach (HELLO piggyback or
        re-establishment)."""
        if socket is not None and not self._matches(socket):
            return False
        with self._lock:
            if self._refuse_shm > 0:
                self._refuse_shm -= 1
                self.injected["refuse_shm"] += 1
                return True
        return False

    def on_bulk_handshake(self, socket=None) -> bool:
        """True → refuse this bulk (re)establishment handshake."""
        if socket is not None and not self._matches(socket):
            return False
        with self._lock:
            if self._refuse_bulk > 0:
                self._refuse_bulk -= 1
                self.injected["refuse_bulk"] += 1
                return True
        return False

    def on_collective_execute(self, devices=()) -> Optional[str]:
        """Refusal reason (the fan-out degrades in-call to per-member
        RPCs) or None.  Fires BETWEEN the screen and the program entry —
        the mid-fan-out window — like a participant dying after the
        client committed to the compiled route."""
        with self._lock:
            if self.collective_kill_device is not None \
                    and self.collective_kill_device in devices:
                self.injected["collective"] += 1
                return (f"member ici://{self.collective_kill_device} "
                        f"killed mid-fan-out")
            if self._fail_coll_execs > 0:
                self._fail_coll_execs -= 1
                self.injected["collective"] += 1
                return "injected collective execution failure"
        return None

    def on_device_post(self, socket=None) -> bool:
        """True → refuse this device-plane post_send (the WR fails before
        any descriptor exists, so the caller degrades in-frame)."""
        if socket is not None and not self._matches(socket):
            return False
        with self._lock:
            if self._fail_device_posts > 0:
                self._fail_device_posts -= 1
                self.injected["device_plane"] += 1
                return True
        return False

    def on_hello(self) -> bool:
        """True → the server refuses this control HELLO."""
        with self._lock:
            if self._refuse_hellos > 0:
                self._refuse_hellos -= 1
                self.injected["refuse_hello"] += 1
                return True
        return False

    # -- plane-scoped hooks (the kill-every-plane matrix) ----------------
    def on_xfer_stage(self, socket=None) -> bool:
        """True → refuse this transfer-server stage (the xfer route
        degrades in-frame to inline, before any descriptor exists)."""
        if socket is not None and not self._matches(socket):
            return False
        with self._lock:
            if self._refuse_xfer > 0:
                self._refuse_xfer -= 1
                self.injected["xfer"] += 1
                return True
        return False

    def on_plane_op(self, socket, plane: str) -> None:
        """SLOW injector: delay one operation on ``plane`` by
        ``plane_slow_ms[plane]`` — the "slow, not dead" fault.  Traffic
        completes late; a correct health machine must NOT degrade."""
        ms = self.plane_slow_ms.get(plane, 0)
        if not ms or (socket is not None and not self._matches(socket)):
            return
        with self._lock:
            self.injected["plane_slow"] += 1
        time.sleep(ms / 1000.0)

    def on_collective_announce(self) -> bool:
        """True → silently swallow this fan-out announce (black-hole):
        the member never sees it; the client times out with R_ANNOUNCE
        and degrades the collective route in-call."""
        with self._lock:
            if self._drop_announces > 0:
                self._drop_announces -= 1
                self.injected["coll_announce_drop"] += 1
                return True
        return False


# ---- plane-scoped chaos verbs (the kill-every-plane matrix) ------------

KILL = "kill"            # the plane dies NOW (sever / mark dead)
BLACKHOLE = "blackhole"  # bytes vanish silently (received frames drop)
SLOW = "slow"            # ops delayed, not dead — must NOT degrade


def chaos_plane(sock, plane: str, mode: str, value: int = 0) -> bool:
    """Apply one failure mode to a LIVE plane of one fabric socket,
    mid-traffic — the chaos matrix's verb.  bulk/shm reach through the
    native chaos entry points on the CURRENT handle (so the fault hits
    the attached plane, not a future one); returns True when armed.
    The shm ring has no native delay mode — SLOW there rides
    ``plane_slow_ms`` via a FabricFaultPlan instead, and so do the
    device/xfer/collective planes (their kill/black-hole shapes are
    plan knobs: post/stage refusal, announce drops)."""
    if plane not in ("bulk", "shm"):
        return False
    with sock._bulk_lock:
        h = sock._bulk if plane == "bulk" else sock._shm
        lib = sock._blib if plane == "bulk" else sock._shmlib
    if not h or lib is None:
        return False
    fn = (lib.brpc_tpu_fab_chaos if plane == "bulk"
          else lib.brpc_tpu_shm_chaos)
    if mode == KILL:
        fn(h, CHAOS_SEVER_NOW, 0)
    elif mode == BLACKHOLE:
        fn(h, CHAOS_DROP_FRAMES, value or 1_000_000)
    elif mode == SLOW:
        if plane == "shm":
            return False
        fn(h, CHAOS_DELAY_PARK_MS, value or 20)
    else:
        return False
    return True


_fabric_active: Optional[FabricFaultPlan] = None


def install_fabric(plan: Optional[FabricFaultPlan]) -> None:
    global _fabric_active
    _fabric_active = plan


def fabric_active() -> Optional[FabricFaultPlan]:
    return _fabric_active


class inject_fabric:
    """Context manager: install a fabric fault plan for the with-block,
    restore the previous plan after — no state leaks between tests."""

    def __init__(self, plan: FabricFaultPlan):
        self.plan = plan
        self._prev: Optional[FabricFaultPlan] = None

    def __enter__(self) -> FabricFaultPlan:
        self._prev = _fabric_active
        install_fabric(self.plan)
        return self.plan

    def __exit__(self, *exc) -> None:
        install_fabric(self._prev)
