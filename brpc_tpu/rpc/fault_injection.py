"""Fault injection — drop/delay/sever writes to exercise resilience.

The reference ships no built-in fault injection (SURVEY.md §5.3: tests
kill in-process servers); this module goes one step further so retry,
backup-request, health-check, and circuit-breaker machinery can be
exercised deterministically.  Faults act at the Socket.write boundary —
the same place a lossy or partitioned network would.

    from brpc_tpu.rpc import fault_injection as fi
    with fi.inject(fi.FaultInjector(drop_ratio=1.0,
                                    match=lambda s: s.remote_side == ep)):
        ...   # every write toward ep silently vanishes

Deterministic given a seed; thread-safe; uninstalls on context exit.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

PASS = "pass"
DROP = "drop"          # bytes vanish (lossy link / partition)
ERROR = "error"        # connection severed (peer reset)


class FaultInjector:
    def __init__(self, drop_ratio: float = 0.0, error_ratio: float = 0.0,
                 delay_ms: float = 0.0, seed: int = 0,
                 match: Optional[Callable] = None):
        self.drop_ratio = drop_ratio
        self.error_ratio = error_ratio
        self.delay_ms = delay_ms
        self.match = match
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected = {DROP: 0, ERROR: 0, "delayed": 0}

    def decide(self, socket) -> str:
        if self.match is not None and not self.match(socket):
            return PASS
        with self._lock:
            r = self._rng.random()
            if r < self.drop_ratio:
                self.injected[DROP] += 1
                return DROP
            if r < self.drop_ratio + self.error_ratio:
                self.injected[ERROR] += 1
                return ERROR
            if self.delay_ms > 0:
                self.injected["delayed"] += 1
        if self.delay_ms > 0:
            time.sleep(self.delay_ms / 1000.0)
        return PASS


_active: Optional[FaultInjector] = None


def install(injector: Optional[FaultInjector]) -> None:
    global _active
    _active = injector


def active() -> Optional[FaultInjector]:
    return _active


class inject:
    """Context manager: install for the with-block, restore after."""

    def __init__(self, injector: FaultInjector):
        self.injector = injector
        self._prev: Optional[FaultInjector] = None

    def __enter__(self) -> FaultInjector:
        self._prev = _active
        install(self.injector)
        return self.injector

    def __exit__(self, *exc) -> None:
        install(self._prev)
