"""Service/method declaration layer.

The reference services are protobuf-generated classes whose CallMethod is
invoked by protocols (baidu_rpc_protocol.cpp:448).  Here a service is a
Python class with protobuf request/response types declared per method:

    class EchoService(Service):
        @method(EchoRequest, EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = request.message
            done()

``done`` must be called exactly once (it sends the response); returning from
the handler without calling it keeps the RPC open (async server-side), same
contract as the reference's google::protobuf::Closure.
"""
from __future__ import annotations

import inspect
from typing import Callable, Dict, Optional, Type


def method(request_cls: Type, response_cls: Type):
    def deco(fn: Callable) -> Callable:
        fn._rpc_method = (request_cls, response_cls)
        return fn
    return deco


class MethodDescriptor:
    __slots__ = ("name", "full_name", "request_cls", "response_cls", "fn",
                 "service")

    def __init__(self, service: "Service", name: str, request_cls, response_cls,
                 fn: Callable):
        self.service = service
        self.name = name
        self.full_name = f"{service.service_name()}.{name}"
        self.request_cls = request_cls
        self.response_cls = response_cls
        self.fn = fn

    def invoke(self, cntl, request, response, done) -> None:
        """Run the handler with a done that recycles per-RPC server
        resources (session-local data, then the pooled Controller shim
        itself) once the response is sent — the protocol-agnostic
        completion point every wire protocol shares.  After ``done``
        returns the controller may be reset and reused by another
        request, so handlers must not touch it past their ``done()``
        call (the reference's Closure contract).

        The handler's synchronous body runs under the inbound request's
        cascading context (rpc/request_context.py): outbound calls it
        makes inherit priority/tenant and the decremented deadline
        budget by default."""
        def wrapped_done(*args, **kwargs):
            try:
                return done(*args, **kwargs)
            finally:
                cntl._release_session_data()
                cntl._maybe_recycle()
        from . import request_context as _reqctx
        with _reqctx.scope(cntl):
            self.fn(cntl, request, response, wrapped_done)


class Service:
    SERVICE_NAME: Optional[str] = None

    @classmethod
    def service_name(cls) -> str:
        return cls.SERVICE_NAME or cls.__name__

    def methods(self) -> Dict[str, MethodDescriptor]:
        out = {}
        for name, member in inspect.getmembers(self, predicate=callable):
            sig = getattr(member, "_rpc_method", None)
            if sig is not None:
                out[name] = MethodDescriptor(self, name, sig[0], sig[1], member)
        return out
