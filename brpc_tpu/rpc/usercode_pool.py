"""The free-threading handler pool (ROADMAP 4c / ISSUE 13): usercode
workers that can scale past the GIL.

The reference runs usercode on an M:N bthread scheduler precisely so one
slow handler never serializes the process (PAPER.md L2/L3 — bthread +
``usercode_in_pthread``).  Our ``ServerOptions.usercode_in_pthread`` seam
routes handlers to a backup THREAD pool — which protects the dispatch
loop, but every handler still funnels through the ONE GIL, so CPU-bound
handlers cannot scale.  This module puts an ISOLATION backend behind the
same seam:

* **probe once** (:func:`probe_isolation`): free-threading CPython
  (3.13t, GIL disabled) scales with plain threads; CPython ≥3.12 gives
  subinterpreters their own GIL; 3.8–3.11 subinterpreters are functional
  but SHARE the GIL (isolation without scaling — the capability record
  says so and the bench leg SKIPs, the striped-shm precedent); anything
  else falls back to the plain backup pool.
* **UsercodePool**: the backup ``ThreadPoolExecutor`` surface
  (``submit``/``shutdown``) stays byte-identical — regular handlers,
  queued-counter accounting, drain bounce, and admission ordering are
  untouched.  On top, *registered* isolated handlers
  (:meth:`register` + :meth:`call_isolated`) run inside per-worker
  subinterpreters under an explicit SHARE-NOTHING contract: handler
  source crosses as a string at registration, per-call arguments cross
  only as bytes (+ the opaque int attachment handle); anything else is
  refused with a clear TypeError.
* **worker-death resilience**: a worker that dies mid-task (chaos hook
  :attr:`chaos_kill_next`) requeues its in-hand task onto a replacement
  worker — zero caller-visible failures, counted in ``stats()``.

Server integration: ``Server.register_isolated`` +
``ServerBinding._run_isolated`` (ici/native_plane.py) route a registered
method's payload bytes to a worker and pass the parked attachment handle
through to the response (the zero-copy echo shape).
"""
from __future__ import annotations

import queue
import sys
import threading
import time
from collections import namedtuple
from typing import Dict, Optional

from ..butil import debug_sync as _dbg
from ..butil import logging as log

IsolationCaps = namedtuple(
    "IsolationCaps", ("mode", "functional", "scaling", "reason"))

_caps: Optional[IsolationCaps] = None
_caps_lock = threading.Lock()

# process-wide backend override for servers configured "auto" —
# tools/rpc_press --usercode-pool pins it for self-hosted targets
_default_kind = "auto"


def set_default_kind(kind: str) -> None:
    """Override the backend that "auto"-configured servers resolve to
    ("auto" restores capability-based resolution)."""
    global _default_kind
    if kind not in ("auto", "pthread", "subinterp"):
        raise ValueError(f"unknown usercode pool kind {kind!r}")
    _default_kind = kind


def default_kind() -> str:
    return _default_kind


def probe_isolation() -> IsolationCaps:
    """Probe the interpreter's isolation capability ONCE per process.

    ``mode``: "free-threading" | "subinterp" | "subinterp-shared-gil" |
    "none".  ``functional`` — isolated registration/dispatch works;
    ``scaling`` — isolated handlers can actually run CPU concurrently
    (the ≥2× bench acceptance needs this AND >1 core).  The record is
    surfaced verbatim in /status and bench extra so a SKIP always
    carries its reason."""
    global _caps
    if _caps is not None:
        return _caps
    with _caps_lock:
        if _caps is not None:
            return _caps
        gil_check = getattr(sys, "_is_gil_enabled", None)
        if gil_check is not None and not gil_check():
            caps = IsolationCaps("free-threading", True, True, "")
        elif _si_api() is not None:
            # the probe is FUNCTIONAL, not import-sniffing: _si_api()
            # only resolves after a real interpreter + channel round
            # trip succeeded, so an API drift between CPython versions
            # (the 3.12 channel split, the 3.13 module rename) degrades
            # to the pthread fallback instead of failing per call
            if sys.version_info >= (3, 12):
                caps = IsolationCaps("subinterp", True, True, "")
            else:
                caps = IsolationCaps(
                    "subinterp-shared-gil", True, False,
                    "CPython %d.%d subinterpreters share the GIL; "
                    "per-interpreter GIL needs 3.12+ (or a "
                    "free-threading build)" % sys.version_info[:2])
        else:
            caps = IsolationCaps(
                "none", False, False,
                "no working subinterpreter+channel support in this "
                "interpreter and the GIL is enabled — isolated "
                "handlers fall back to the backup thread pool")
        _caps = caps
        return caps


# Subinterpreter compat layer: (create, destroy, run_string,
# channel_create, channel_destroy, channel_send, channel_recv).
# CPython moved these around — 3.8-3.11 keep everything in
# _xxsubinterpreters; 3.12 split channels into _xxinterpchannels
# (send/recv without the channel_ prefix); 3.13 renamed the modules
# again.  Resolution is validated by a REAL round trip (create an
# interpreter, run a string that sends through a channel, receive it),
# so a layout this shim doesn't know reads as "none" instead of
# breaking every call.
_si_cache = ("unresolved",)


def _si_api():
    global _si_cache
    if _si_cache != ("unresolved",):
        return _si_cache[0]
    api = None
    try:
        import _xxsubinterpreters as si
        if hasattr(si, "channel_create"):          # <= 3.11 layout
            api = (si.create, si.destroy, si.run_string,
                   si.channel_create, si.channel_destroy,
                   si.channel_send, si.channel_recv)
        else:                                      # 3.12 split layout
            import _xxinterpchannels as ch
            api = (si.create, si.destroy, si.run_string,
                   ch.create, ch.destroy, ch.send, ch.recv)
    except ImportError:
        try:                                       # 3.13+ rename
            import _interpreters as si
            import _interpchannels as ch
            api = (si.create, si.destroy, si.run_string,
                   ch.create, ch.destroy, ch.send, ch.recv)
        except ImportError:
            api = None
    if api is not None:
        # validate end to end once; any surprise → no isolation
        try:
            create, destroy, run_string, c_create, c_destroy, \
                c_send, c_recv = api
            interp = create()
            cid = c_create()
            try:
                run_string(interp, _PROBE_SCRIPT, {"_cid": cid})
                if c_recv(cid) != b"probe-ok":
                    api = None
            finally:
                try:
                    c_destroy(cid)
                    destroy(interp)
                except Exception:
                    pass
        except Exception:
            api = None
    _si_cache = (api,)
    return api


# runs inside the probe interpreter: resolve whichever channel-send
# exists THERE and echo a marker back
_PROBE_SCRIPT = """\
try:
    import _xxsubinterpreters as _m
    _send = _m.channel_send
except (ImportError, AttributeError):
    try:
        import _xxinterpchannels as _m
    except ImportError:
        import _interpchannels as _m
    _send = _m.send
_send(_cid, b"probe-ok")
"""


class _WorkerKilled(BaseException):
    """Chaos injection: simulates a worker dying mid-handler (the thread
    unwinds without completing its task)."""


class _IsoTask:
    __slots__ = ("name", "payload", "event", "result", "error",
                 "requeued", "abandoned")

    def __init__(self, name: str, payload: bytes):
        self.name = name
        self.payload = payload
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.requeued = 0
        self.abandoned = False


# runs inside the worker's subinterpreter: dispatch one registered
# handler on the shared-in payload and send the tagged result back on
# the worker's channel (b"\x00" ok / b"\x01" handler error); the
# channel-send is resolved against whichever module layout exists in
# THAT interpreter (see _si_api)
_ISO_DISPATCH = """\
try:
    import _xxsubinterpreters as _m
    _send = _m.channel_send
except (ImportError, AttributeError):
    try:
        import _xxinterpchannels as _m
    except ImportError:
        import _interpchannels as _m
    _send = _m.send
try:
    _r = b"\\x00" + _handlers[_name](_in)
except BaseException as _e:
    _r = b"\\x01" + (type(_e).__name__ + ": " + str(_e)).encode()
_send(_cid, _r)
"""


class _IsoWorker:
    """One isolation worker: a thread hosting its own subinterpreter,
    draining the pool's shared isolated-task queue.  Handler sources
    exec lazily per worker (per-worker registration — nothing is shared
    between interpreters except the source string)."""

    def __init__(self, pool: "UsercodePool", wid: int):
        self.pool = pool
        self.wid = wid
        self._installed: Dict[str, int] = {}   # name -> version exec'd
        self._interp = None
        self._cid = None
        # fablint: thread-quiesced(daemon; shutdown() puts one None sentinel per worker and the loop returns after destroying its interpreter)
        self.thread = threading.Thread(
            target=self._run, name=f"usercode-iso-{wid}", daemon=True)
        self.thread.start()

    def _ensure_interp(self):
        api = _si_api()
        if self._interp is None:
            create = api[0]
            c_create = api[3]
            self._interp = create()
            self._cid = c_create()
            api[2](self._interp, "_handlers = {}", None)
        return api

    def _run(self) -> None:
        pool = self.pool
        q_ = pool._iso_queue
        while True:
            task = q_.get()
            if task is None:             # shutdown sentinel
                self._destroy_interp()
                return
            if task.abandoned:           # caller timed out: never burn
                continue                 # a worker on an unread result
            try:
                if pool.chaos_kill_next:
                    pool.chaos_kill_next = False
                    raise _WorkerKilled()
                self._exec(task)
            except _WorkerKilled:
                pool._on_worker_death(self, task)
                return                   # the thread IS dead
            except BaseException as e:   # never kill the worker loop
                task.error = f"{type(e).__name__}: {e}"
                task.event.set()

    def _destroy_interp(self) -> None:
        if self._interp is None:
            return
        try:
            api = _si_api()
            api[4](self._cid)            # channel destroy
            api[1](self._interp)         # interpreter destroy
        except Exception:
            pass                         # teardown best-effort
        self._interp = None

    def _exec(self, task: _IsoTask) -> None:
        api = self._ensure_interp()
        run_string = api[2]
        name = task.name
        pool = self.pool
        with pool._lock:
            src = pool._iso_handlers.get(name)
            ver = pool._iso_versions.get(name, 0)
        if self._installed.get(name) != ver:
            if src is None:
                task.error = f"no isolated handler {name!r}"
                task.event.set()
                return
            run_string(self._interp,
                       src + f"\n_handlers[{name!r}] = handle", None)
            self._installed[name] = ver
        run_string(self._interp, _ISO_DISPATCH,
                   {"_in": task.payload, "_name": name,
                    "_cid": self._cid})
        raw = api[6](self._cid)          # channel recv
        if raw[:1] == b"\x00":
            task.result = raw[1:]
        else:
            task.error = raw[1:].decode()
        task.event.set()


class UsercodePool:
    """The ``usercode_in_pthread`` backup pool, extended with the
    isolation backend.  The plain surface (``submit``/``shutdown``) is
    a passthrough to a ``ThreadPoolExecutor`` — byte-identical to the
    pre-pool behavior — so every existing dispatch/drain/admission
    semantics test covers it unchanged."""

    _GUARDED_BY = {"_iso_workers": "_lock", "_iso_handlers": "_lock",
                   "_shutdown_flag": "_lock", "isolated_calls": "_lock",
                   "contract_rejections": "_lock",
                   "worker_deaths": "_lock", "requeues": "_lock"}

    def __init__(self, kind: str = "auto", workers: int = 8):
        if kind not in ("auto", "pthread", "subinterp"):
            raise ValueError(f"unknown usercode pool kind {kind!r}")
        from concurrent.futures import ThreadPoolExecutor
        self.caps = probe_isolation()
        if kind == "auto":
            kind = _default_kind
        if kind == "auto":
            if self.caps.mode == "free-threading":
                # plain threads already scale past the (absent) GIL:
                # the backup pool IS the scaling backend — isolation
                # machinery would only add copies
                kind = "pthread"
            else:
                kind = "subinterp" if self.caps.functional else "pthread"
        elif kind == "subinterp" and (not self.caps.functional
                                      or _si_api() is None):
            # explicit request: validate against the REAL round-trip
            # probe, not the capability flag (a free-threading build
            # reads functional=True without ever touching _si_api)
            raise RuntimeError(
                f"usercode pool kind 'subinterp' unavailable: "
                f"{self.caps.reason or 'subinterpreter API round trip failed'}")
        self.kind = kind
        self.workers = max(int(workers), 1)
        self._tp = ThreadPoolExecutor(max_workers=self.workers,
                                      thread_name_prefix="usercode")
        self._lock = _dbg.make_lock("UsercodePool._lock")
        self._iso_queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._iso_workers: list = []
        self._iso_handlers: Dict[str, str] = {}
        self._iso_versions: Dict[str, int] = {}
        self._fallback_fns: Dict[str, object] = {}
        self._shutdown_flag = False
        self._next_wid = 0
        # stats — guarded by _lock like the worker table: += on a
        # plain int is NOT atomic on the free-threading builds this
        # module targets
        self.isolated_calls = 0
        self.contract_rejections = 0
        self.worker_deaths = 0
        self.requeues = 0
        self.chaos_kill_next = False     # test hook: next task's worker dies

    # ---- the byte-identical backup-pool surface -----------------------
    def submit(self, fn, *args):
        return self._tp.submit(fn, *args)

    def shutdown(self, wait: bool = False) -> None:
        with self._lock:
            self._shutdown_flag = True
            workers = list(self._iso_workers)
            self._iso_workers = []
        for _ in workers:
            self._iso_queue.put(None)
        # JOIN the isolation workers (bounded): each destroys its
        # subinterpreter on the way out, and a live subinterpreter at
        # process finalization is a hard abort ("PyInterpreterState_
        # Delete: remaining subinterpreters", SIGABRT) — the daemon
        # flag alone does not save us.  A worker wedged in a long
        # handler past the bound is left to its own exit (documented
        # residual risk, better than blocking stop() forever).
        deadline = time.monotonic() + 5.0
        for w in workers:
            w.thread.join(max(deadline - time.monotonic(), 0.1))
        # leftover sweep: a task that raced past the workers' exits
        # (queued behind the sentinels) fails NOW, not at its caller's
        # timeout — paired with call_isolated's locked check-and-put
        while True:
            try:
                t = self._iso_queue.get_nowait()
            except queue.Empty:
                break
            if t is not None:
                t.error = "usercode pool stopped"
                t.event.set()
        self._tp.shutdown(wait=wait)

    # ---- isolated handlers (share-nothing) ----------------------------
    @property
    def isolation_active(self) -> bool:
        """True when registered handlers actually run isolated (the
        subinterp backend); the pthread fallback runs them on backup
        threads instead — functional, GIL-bound."""
        return self.kind == "subinterp" and _si_api() is not None

    def register(self, name: str, src: str) -> None:
        """Register an isolated handler: ``src`` must be SOURCE (a
        string defining ``handle(payload: bytes) -> bytes``) — the
        share-nothing contract starts here: code crosses as text, never
        as an object."""
        if not isinstance(name, str) or not isinstance(src, str):
            with self._lock:
                self.contract_rejections += 1
            raise TypeError(
                "share-nothing contract: isolated handlers register as "
                "(name: str, src: str) — source crosses the isolation "
                f"boundary as text, got ({type(name).__name__}, "
                f"{type(src).__name__})")
        with self._lock:
            self._iso_handlers[name] = src
            # re-registration recompiles on EVERY backend: the fallback
            # cache drops its entry and the version bump makes each
            # subinterp worker reinstall past its own memoization
            self._iso_versions[name] = \
                self._iso_versions.get(name, 0) + 1
            self._fallback_fns.pop(name, None)
            spawn = self.isolation_active and not self._iso_workers \
                and not self._shutdown_flag
            if spawn:
                for _ in range(self.workers):
                    self._iso_workers.append(
                        _IsoWorker(self, self._next_wid))
                    self._next_wid += 1

    def call_isolated(self, name: str, payload,
                      timeout: Optional[float] = None) -> bytes:
        """Run a registered handler on an isolation worker; blocks the
        calling (backup) thread until the result crosses back.  Only
        bytes-like payloads cross; anything else is refused with a
        clear error — the share-nothing contract."""
        if isinstance(payload, (bytearray, memoryview)):
            payload = bytes(payload)
        elif not isinstance(payload, bytes):
            with self._lock:
                self.contract_rejections += 1
            raise TypeError(
                "share-nothing contract: isolated handler arguments "
                "cross as bytes (attachment handles as int) — got "
                f"{type(payload).__name__}; pass serialized bytes or "
                "run this handler unisolated")
        with self._lock:
            self.isolated_calls += 1
            if self._shutdown_flag:
                # stopped pool: refuse on EVERY backend — the pthread
                # fallback could still execute, but "works after
                # shutdown" is exactly the half-alive state callers
                # must not depend on
                raise RuntimeError("usercode pool stopped")
        if not self.isolation_active:
            # capability fallback: same handler SOURCE, executed on the
            # calling backup thread — functional parity, no scaling
            # (caps.reason says why).  The compiled namespace is cached
            # per name (invalidated by register), mirroring the
            # per-worker _installed memoization on the subinterp leg.
            fn = self._fallback_fns.get(name)
            if fn is None:
                with self._lock:
                    src = self._iso_handlers.get(name)
                if src is None:
                    raise KeyError(f"no isolated handler {name!r}")
                ns: dict = {}
                exec(src, ns)            # noqa: S102 — registered source
                fn = self._fallback_fns[name] = ns["handle"]
            return fn(payload)
        task = _IsoTask(name, payload)
        # check-and-enqueue under ONE lock: shutdown() flips the flag
        # under the same lock and then sweeps the queue after joining
        # the workers, so a task is either refused here or guaranteed
        # an answer (worker result, death requeue, or the sweep) —
        # never stranded behind the sentinels until the timeout
        with self._lock:
            if self._shutdown_flag:
                raise RuntimeError("usercode pool stopped")
            self._iso_queue.put(task)
        if not task.event.wait(timeout if timeout is not None else 60.0):
            # the caller stops waiting: mark the task so a worker that
            # dequeues it later drops it instead of computing a result
            # nobody reads
            task.abandoned = True
            raise TimeoutError(f"isolated handler {name!r} timed out")
        if task.error is not None:
            raise RuntimeError(task.error)
        return task.result

    def _on_worker_death(self, worker: "_IsoWorker", task: _IsoTask) -> None:
        """A worker died mid-task: requeue the in-hand task (another
        worker — or the replacement spawned here — picks it up) so the
        caller never sees the death.  A task that already died twice is
        failed rather than looped forever."""
        with self._lock:
            self.worker_deaths += 1
        log.warning("usercode isolation worker %d died mid-handler "
                    "(task %s); requeueing", worker.wid, task.name)
        with self._lock:
            try:
                self._iso_workers.remove(worker)
            except ValueError:
                pass
            replace = not self._shutdown_flag
            if replace:
                self._iso_workers.append(_IsoWorker(self, self._next_wid))
                self._next_wid += 1
        if not replace:
            # pool stopping: no worker will ever drain a requeue —
            # fail NOW instead of wedging the caller to its timeout
            task.error = "usercode pool stopped"
            task.event.set()
            return
        if task.requeued >= 2:
            task.error = "isolation worker died repeatedly"
            task.event.set()
            return
        task.requeued += 1
        with self._lock:
            self.requeues += 1
        self._iso_queue.put(task)

    # ---- observability -------------------------------------------------
    def describe(self) -> dict:
        caps = self.caps
        with self._lock:
            iso_workers = len(self._iso_workers)
            registered = sorted(self._iso_handlers)
            isolated_calls = self.isolated_calls
            contract_rejections = self.contract_rejections
            worker_deaths = self.worker_deaths
            requeues = self.requeues
        return {
            "kind": self.kind,
            "workers": self.workers,
            "isolation": {
                "mode": caps.mode,
                "functional": caps.functional,
                "scaling": caps.scaling,
                "reason": caps.reason,
            },
            "isolation_workers": iso_workers,
            "registered_isolated": registered,
            "isolated_calls": isolated_calls,
            "contract_rejections": contract_rejections,
            "worker_deaths": worker_deaths,
            "requeues": requeues,
        }
