"""RPC error space (reference: src/brpc/errno.proto + errno.cpp).

Negative codes are framework errors (same spelling as the reference so
operators can map runbooks); positive codes are OS errno passthrough.
"""
from __future__ import annotations

# framework errors (reference errno.proto values)
ENOSERVICE = 1001       # service not found
ENOMETHOD = 1002        # method not found
EREQUEST = 1003         # bad request
ERPCAUTH = 1004         # authentication failed
ETOOMANYFAILS = 1005    # too many sub-channel failures (ParallelChannel)
EPCHANFINISH = 1006     # ParallelChannel finished
EBACKUPREQUEST = 1007   # backup request triggered (internal)
ERPCTIMEDOUT = 1008     # RPC deadline exceeded
EFAILEDSOCKET = 1009    # the connection was broken during the RPC
EHTTP = 1010            # HTTP-level error
EOVERCROWDED = 1011     # too many buffering bytes on the socket
ERTMPPUBLISHABLE = 1012
ERTMPCREATESTREAM = 1013
EEOF = 1014             # stream reached EOF
EUNUSED = 1015
ESSL = 1016
EITP = 1017

# server errors
EINTERNAL = 2001        # uncaught server-side exception
ERESPONSE = 2002        # bad response
ELOGOFF = 2003          # server is stopping
ELIMIT = 2004           # concurrency limiter rejected the request
ECLOSE = 2005
EITIMEOUT = 2006

# os-ish
EINVAL = 22
EAGAIN = 11
ENODATA = 61
ECANCELED = 125
ENOMEM = 12
ECONNREFUSED = 111
ECONNRESET = 104
ENOENT = 2
EPERM = 1
ETIMEDOUT = 110

_DESCRIPTIONS = {
    ENOSERVICE: "Service not found",
    ENOMETHOD: "Method not found",
    EREQUEST: "Bad request",
    ERPCAUTH: "Unauthorized",
    ETOOMANYFAILS: "Too many failed sub-calls",
    EPCHANFINISH: "ParallelChannel finished",
    EBACKUPREQUEST: "Backup request triggered",
    ERPCTIMEDOUT: "RPC deadline exceeded",
    EFAILEDSOCKET: "Broken socket",
    EHTTP: "HTTP error",
    EOVERCROWDED: "Socket write buffer overcrowded",
    EEOF: "End of stream",
    EINTERNAL: "Internal server error",
    ERESPONSE: "Bad response",
    ELOGOFF: "Server is stopping",
    ELIMIT: "Rejected by concurrency limiter",
    EINVAL: "Invalid argument",
    ETIMEDOUT: "Timed out",
    ECONNREFUSED: "Connection refused",
    ECONNRESET: "Connection reset",
}


def berror(code: int) -> str:
    import os
    d = _DESCRIPTIONS.get(code)
    if d:
        return d
    try:
        return os.strerror(code)
    except Exception:
        return f"error {code}"


class RpcError(Exception):
    def __init__(self, code: int, text: str = ""):
        self.code = code
        self.text = text or berror(code)
        super().__init__(f"[E{code}] {self.text}")
