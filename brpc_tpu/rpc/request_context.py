"""Cascading request context: a handler's outbound calls inherit the
inbound request's admission metadata by default.

PR-9 propagated ``priority`` / ``tenant`` / ``deadline_left_ms`` on the
wire, but a SERVICE that fans out (the proxy/orchestrator shape —
router → prefill → decode) re-originated every outbound call with
channel defaults: a critical-band inbound request could spawn
default-band sub-calls that the downstream's admission controller sheds
first, and a nearly-spent deadline budget silently reset to the full
channel timeout at each hop (the runaway-work shape deadline
propagation exists to kill).

The fix is a thread-scoped inbound context installed around the
handler's synchronous body (``MethodDescriptor.invoke``) and consulted
by ``Channel.call_method``:

  * ``priority`` / ``tenant``: inherited unless the CALL overrides them
    (an explicit ``cntl.priority``/``cntl.tenant`` wins; the inherited
    value beats channel-wide ``ChannelOptions`` defaults — a static
    channel config must not demote a critical inbound request).
  * deadline: the outbound budget is capped at the inbound budget MINUS
    the time this handler already spent (monotonic, measured from
    handler entry) — the decrement-at-each-hop contract.  A spent
    budget fails the call immediately with ERPCTIMEDOUT instead of
    dispatching work the caller can no longer use.

Scope: the handler's synchronous body and everything it calls on the
same thread.  Work handed to other threads/tasklets re-originates (no
ambient context) — explicit propagation there is the caller's choice.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

_tls = threading.local()


class InboundContext:
    """Immutable snapshot of one inbound request's admission metadata,
    anchored at handler entry for deadline decrement."""

    __slots__ = ("priority", "tenant", "deadline_left_ms", "entry_mono")

    def __init__(self, priority: Optional[int], tenant: str,
                 deadline_left_ms: int):
        self.priority = priority
        self.tenant = tenant
        self.deadline_left_ms = deadline_left_ms
        self.entry_mono = time.monotonic()

    def residual_deadline_ms(self) -> Optional[float]:
        """Inbound budget minus handler time already spent; None when
        the inbound request carried no budget."""
        if not self.deadline_left_ms:
            return None
        spent_ms = (time.monotonic() - self.entry_mono) * 1000.0
        return self.deadline_left_ms - spent_ms


def current() -> Optional[InboundContext]:
    return getattr(_tls, "ctx", None)


class scope:
    """Install the inbound context for a handler invocation; restores
    the previous one on exit (nested inline dispatch — a loopback call
    inside a handler — sees ITS request's context, then the outer one
    again)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, cntl):
        pri = getattr(cntl, "priority", None)
        ten = getattr(cntl, "tenant", "") or ""
        ddl = getattr(cntl, "deadline_left_ms", 0) or 0
        self._ctx = (InboundContext(pri, ten, int(ddl))
                     if pri is not None or ten or ddl else None)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
