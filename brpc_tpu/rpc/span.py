"""rpcz spans: per-RPC timelines sampled through the bvar Collector.

Reference: src/brpc/span.{h,cpp} (Span at span.h:47-150, tls_parent :115,
SpanDB :206-223) + builtin/rpcz_service.cpp.  Client and server spans record
annotated timelines; sampling is speed-limited via CollectorSpeedLimit; kept
spans land in an in-memory ring (the LevelDB store's stand-in) rendered by
the /rpcz builtin service.  Propagation: trace/span/parent ids ride RpcMeta.

Pod-scope additions (docs/OBSERVABILITY.md):

  * every span records a **wall-clock anchor** (``wall_us``) alongside its
    monotonic timeline, so spans from DIFFERENT processes can be placed on
    one axis — refined by the fabric's per-pair clock-offset estimate
    (ici/clock.py, ±RTT/2 bound) when the pod stitcher merges them;
  * ``annotate_current`` consults the bthread-local *server* span AND the
    active *client* span (set around the channel write path), so
    client-side relocation/bulk/device-plane events are no longer lost;
  * deep subsystems that know their trace context (device-plane transfers
    carry trace/span ids on their descriptors) open **transfer spans** —
    first-class SpanDB entries parented under the RPC span that caused
    them, so a ``/rpcz?trace_id=`` query shows sequencer queue-wait,
    collective admit, CQ completion, and pin hold-time in the same tree.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Deque, List, Optional, Tuple

from ..butil.misc import fast_rand
from ..butil import flags as _flags
from .. import bvar
from ..bthread import scheduler

_rpcz_flag = _flags.define_flag("rpcz_enabled", False,
                                "collect per-RPC rpcz spans")
_flags.define_flag("rpcz_keep", 1000, "spans kept in memory",
                   _flags.positive_integer)

_speed_limit = bvar.CollectorSpeedLimit()
_store_lock = threading.Lock()
_store: Deque["Span"] = collections.deque(maxlen=10000)


class Span:
    __slots__ = ("trace_id", "span_id", "parent_span_id", "is_client",
                 "method", "start_us", "wall_us", "end_us", "annotations",
                 "error_code", "remote_side", "request_size",
                 "response_size", "kind")

    def __init__(self, method: str, is_client: bool, trace_id: int = 0,
                 parent_span_id: int = 0, kind: Optional[str] = None):
        self.trace_id = trace_id or fast_rand()
        self.span_id = fast_rand()
        self.parent_span_id = parent_span_id
        self.is_client = is_client
        self.method = method
        self.start_us = time.monotonic_ns() // 1000
        # wall-clock anchor: lets a remote process place this span on its
        # own axis (offset by the fabric clock estimate); annotations stay
        # monotonic offsets from start, so wall_us + offset reconstructs
        # their wall time without per-annotation wall reads
        self.wall_us = time.time_ns() // 1000
        self.end_us = 0
        self.annotations: List[Tuple[int, str]] = []
        self.error_code = 0
        self.remote_side = None
        self.request_size = 0
        self.response_size = 0
        self.kind = kind or ("client" if is_client else "server")

    def annotate(self, text: str) -> None:
        self.annotations.append((time.monotonic_ns() // 1000, text))

    def latency_us(self) -> int:
        return (self.end_us or time.monotonic_ns() // 1000) - self.start_us

    def describe(self) -> dict:
        return {
            "trace_id": f"{self.trace_id:016x}",
            "span_id": f"{self.span_id:016x}",
            "parent": f"{self.parent_span_id:016x}",
            "side": self.kind,
            "method": self.method,
            "start_real_us": self.wall_us,
            "latency_us": self.latency_us(),
            "error_code": self.error_code,
            "remote": str(self.remote_side),
            "annotations": [(t - self.start_us, a) for t, a in self.annotations],
        }


def rpcz_enabled() -> bool:
    # one attribute load, not a registry-dict lookup: this gate sits on
    # every call's client-span check
    return bool(_rpcz_flag.value)


def maybe_start_client_span(cntl, method: str) -> None:
    if not rpcz_enabled() or not _speed_limit.is_sampled():
        return
    # inherit trace from an enclosing server span (bthread-local parenting)
    parent: Optional[Span] = scheduler.local_get("rpcz_span")
    if parent is not None:
        span = Span(method, True, parent.trace_id, parent.span_id)
    else:
        span = Span(method, True)
    cntl.span = span
    cntl.trace_id = span.trace_id
    cntl.span_id = span.span_id
    cntl.parent_span_id = span.parent_span_id


def start_server_span(cntl, method: str, trace_id: int, parent_span_id: int) -> None:
    if not rpcz_enabled() or not _speed_limit.is_sampled():
        return
    span = Span(method, False, trace_id, parent_span_id)
    cntl.span = span
    scheduler.local_set("rpcz_span", span)


def current_span() -> Optional[Span]:
    """The span deep subsystems should annotate.  The ACTIVE client span
    wins when set — it is only published for the duration of a channel
    write, so inside that window it is the INNERMOST context (a client
    call issued from a server handler must stamp its relocation events
    on the client span, not the enclosing server span) — else the
    bthread-local server span.  Consulting the client span at all is the
    fix for client-side RPCs, whose relocation/bulk/device-plane events
    used to be lost because only the server span was read."""
    span: Optional[Span] = scheduler.local_get("rpcz_client_span")
    if span is not None:
        return span
    return scheduler.local_get("rpcz_span")


def current_trace_context() -> Tuple[int, int]:
    """(trace_id, span_id) of the span currently in scope, or (0, 0).
    Captured by the device plane at post time so transfer events can be
    parented into the RPC's trace — on BOTH processes, via the kind-4
    descriptor's trace fields."""
    span = current_span()
    if span is None:
        return 0, 0
    return span.trace_id, span.span_id


def set_client_span_local(span: Optional[Span]) -> None:
    """Publish ``span`` as the bthread-local active client span for the
    duration of the channel's encode/write (cleared with None after)."""
    scheduler.local_set("rpcz_client_span", span)


def annotate_current(text: str) -> None:
    """Annotate the span currently in scope (the ACTIVE client span
    during a channel write — the innermost context — else the
    bthread-local server span; see current_span), if sampling kept one.
    Deep subsystems (the device plane's posted→matched→complete
    lifecycle, bulk claims) use this to stamp their timeline onto
    whatever RPC is in progress without threading a Controller down the
    datapath."""
    if not rpcz_enabled():
        return
    span = current_span()
    if span is not None:
        span.annotate(text)


def start_transfer_span(method: str, trace_id: int,
                        parent_span_id: int) -> Span:
    """A data-plane event span (device-plane transfer, bulk claim):
    stored like any RPC span, parented under the RPC span that caused it,
    so the stitched trace shows the transfer's own timeline."""
    return Span(method, False, trace_id, parent_span_id, kind="transfer")


def end_span(span: Span, error_code: int = 0) -> None:
    """Close and store a span the caller owns (transfer spans)."""
    span.end_us = time.monotonic_ns() // 1000
    span.error_code = error_code
    store_span(span)


def store_span(span: Span) -> None:
    with _store_lock:
        _store.append(span)
        while len(_store) > _flags.get_flag("rpcz_keep"):
            _store.popleft()


def end_client_span(cntl) -> None:
    _finish(cntl)


def end_server_span(cntl) -> None:
    _finish(cntl)
    scheduler.local_set("rpcz_span", None)


def _finish(cntl) -> None:
    span = cntl.span
    if span is None:
        return
    span.end_us = time.monotonic_ns() // 1000
    span.error_code = cntl.error_code_
    span.remote_side = cntl.remote_side
    store_span(span)
    cntl.span = None


def recent_spans(limit: int = 100) -> List[Span]:
    with _store_lock:
        return list(_store)[-limit:]


def find_trace(trace_id: int) -> List[Span]:
    with _store_lock:
        return [s for s in _store if s.trace_id == trace_id]
