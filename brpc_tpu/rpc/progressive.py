"""Progressive attachment / progressive reader.

Reference: src/brpc/progressive_attachment.{h,cpp} + progressive_reader.h —
a server can keep appending body bytes after the response header went out
(large file download, incremental results); the client registers a reader
that consumes parts as they arrive.  The reference implements this with
chunked HTTP/raw socket writes; here it rides the stream machinery (same
wire as Streaming RPC), which gives flow control for free:

  client:  reader = ProgressiveReader(on_part, on_end)
           response_will_be_read_progressively(cntl, reader)   # before call
           ch.call_method(...)
  server:  pa = create_progressive_attachment(cntl)            # in handler
           done()                      # response goes out
           pa.append(b"...")           # as many times as needed
           pa.close()
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional

from ..butil.iobuf import IOBuf
from .stream import (Stream, StreamOptions, StreamInputHandler,
                     stream_create, stream_accept)


class ProgressiveReader:
    """Client-side part consumer (progressive_reader.h contract)."""

    def __init__(self,
                 on_part: Optional[Callable[[bytes], None]] = None,
                 on_end: Optional[Callable[[int], None]] = None):
        self._on_part = on_part
        self._on_end = on_end
        self.parts: List[bytes] = []
        self.ended = threading.Event()
        self.error_code = 0

    # overridable
    def on_read_one_part(self, data: bytes) -> None:
        self.parts.append(data)
        if self._on_part is not None:
            self._on_part(data)

    def on_end_of_message(self, error_code: int) -> None:
        self.error_code = error_code
        if self._on_end is not None:
            self._on_end(error_code)
        self.ended.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.ended.wait(timeout)

    def data(self) -> bytes:
        return b"".join(self.parts)


class _ReaderAdapter(StreamInputHandler):
    def __init__(self, reader: ProgressiveReader):
        self.reader = reader

    def on_received_messages(self, sid, msgs) -> None:
        for m in msgs:
            self.reader.on_read_one_part(m.to_bytes())

    def on_closed(self, sid) -> None:
        self.reader.on_end_of_message(0)


def response_will_be_read_progressively(cntl,
                                        reader: ProgressiveReader,
                                        max_buf_size: int = 2 << 20) -> None:
    """Client, before issuing the call (reference
    Controller::response_will_be_read_progressively)."""
    stream = stream_create(cntl, StreamOptions(
        handler=_ReaderAdapter(reader), max_buf_size=max_buf_size))
    cntl._progressive_stream = stream


class ProgressiveAttachment:
    """Server-side incremental body writer (progressive_attachment.h)."""

    def __init__(self, stream: Stream):
        self._stream = stream

    def append(self, data, timeout: Optional[float] = 10.0) -> int:
        """Blocking append honoring the stream window (0 ok)."""
        buf = data if isinstance(data, IOBuf) else IOBuf(data)
        return self._stream.write(buf, timeout=timeout)

    def close(self) -> None:
        self._stream.close()

    @property
    def closed(self) -> bool:
        return self._stream.closed


def create_progressive_attachment(cntl) -> Optional[ProgressiveAttachment]:
    """Server, inside the handler (before done()).  Returns None if the
    client didn't opt in."""
    stream = stream_accept(cntl, StreamOptions())
    return ProgressiveAttachment(stream)
