"""InputMessenger: read → cut messages → dispatch, one tasklet per message.

Reference: src/brpc/input_messenger.{h,cpp} (CutInputMessage at :64,
OnNewMessages at :317, QueueMessage at :169).  Reads the transport until
EAGAIN, tries registered protocols to cut complete messages (remembering the
first protocol that succeeds per socket), then dispatches every message in
its own tasklet — the request-isolation doctrine: a slow handler only slows
its own request.
"""
from __future__ import annotations

from typing import Any, List, Optional

from .. import bvar
from ..bthread import scheduler
from . import errors
from .protocol import ParseResultType, Protocol, list_protocols

_g_messages = bvar.Adder("rpc_input_messages")


class InputMessenger:
    def __init__(self, protocols: Optional[List[Protocol]] = None,
                 server=None):
        self._protocols = protocols          # None = all registered
        self.server = server                 # set for server-side messengers

    def protocols(self) -> List[Protocol]:
        return self._protocols if self._protocols is not None else list_protocols()

    # called from Socket._process_event (single reader per socket)
    def on_new_messages(self, socket):
        """Read until EAGAIN, cut messages, dispatch all but the last to
        their own tasklets, and RETURN the last one (already cut from the
        buffer).  The socket releases readership before processing it in
        place (input_messenger.cpp:205-311 + the socket.cpp:2046 single-
        reader discipline): a slow handler must block only itself, never
        the connection's later messages — the tail-latency-isolation
        doctrine of docs/en/io.md."""
        read_eof = False
        last = None
        # per-socket read granularity: TCP keeps 64KB (append_from_socket
        # allocates max_count per read, so big reads waste allocation on
        # small-message traffic); inbox-backed transports (ici/fabric)
        # advertise a large hint because their _do_read only CUTS already
        # -resident bytes — 8MB bulk frames used to take 128 read+parse
        # cycles each at 64KB
        read_max = getattr(socket, "read_chunk_hint", 1 << 16)
        while not read_eof and not socket.failed:
            nr = socket._do_read(socket._read_portal, read_max)
            if nr < 0:
                break                         # EAGAIN: wait for next event
            if nr == 0:
                read_eof = True               # remote closed: parse leftovers
            socket.stat.in_size += max(nr, 0)
            msgs = self._cut_messages(socket)
            if msgs is None:                  # corrupt stream
                socket.set_failed(errors.EREQUEST, "protocol parse error")
                return None
            if last is not None:              # previous batch's holdover
                self._queue_message(*last, socket)
                last = None
            for proto, msg in msgs[:-1]:
                self._queue_message(proto, msg, socket)
            if msgs:
                last = msgs[-1]
        if read_eof:
            if last is not None:
                self._queue_message(*last, socket)
                last = None
            # a peer that closed with an explicit code (lame-duck ELOGOFF
            # via the in-process transports) surfaces it here — AFTER the
            # queued responses above were drained, so an already-executed
            # call is completed, never retried elsewhere
            code = getattr(socket, "_eof_error_code", 0) or errors.EEOF
            socket.set_failed(code, "remote closed")
        return last

    def process_in_place(self, last, socket) -> None:
        proto, msg = last
        self._process_message(proto, msg, socket)

    def _cut_messages(self, socket) -> Optional[list]:
        out = []
        protocols = self.protocols()
        while len(socket._read_portal):
            result = None
            if socket._selected_protocol_index >= 0:
                proto = protocols[socket._selected_protocol_index]
                result = proto.parse(socket._read_portal, socket, False, self)
                if result.type == ParseResultType.TRY_OTHERS:
                    socket._selected_protocol_index = -1
                    result = None
            if result is None:
                for i, proto in enumerate(protocols):
                    result = proto.parse(socket._read_portal, socket, False, self)
                    if result.type in (ParseResultType.OK,
                                       ParseResultType.NOT_ENOUGH_DATA):
                        socket._selected_protocol_index = i
                        break
                else:
                    return None               # nobody recognizes the bytes
                proto = protocols[socket._selected_protocol_index]
            if result.type == ParseResultType.NOT_ENOUGH_DATA:
                break
            if result.type == ParseResultType.ERROR:
                return None
            socket.stat.in_num_messages += 1
            _g_messages << 1
            # order-sensitive messages (stream frames) are consumed here,
            # in cut order, before per-message tasklet dispatch can reorder
            if proto.process_inline is not None and proto.process_inline(
                    result.message, socket):
                continue
            out.append((proto, result.message))
        return out

    def _queue_message(self, proto: Protocol, msg: Any, socket) -> None:
        scheduler.start_background(self._process_message, proto, msg, socket,
                                   name="msg")

    def _process_message(self, proto: Protocol, msg: Any, socket) -> None:
        # usercode_in_pthread analogue: requests are handed to the
        # server's dedicated backup pool so a CPU-bound (GIL-holding)
        # handler can never occupy a scheduler worker — worker
        # compensation only fires on butex BLOCKING, which a compute
        # loop never does, so without this N spinning handlers starve
        # every other socket's reads (VERDICT Weak #6)
        pool = getattr(self.server, "usercode_pool", None) \
            if self.server is not None else None
        if pool is not None and proto.process_request is not None:
            # counted from submission: a request QUEUED behind a busy
            # pool has not reached on_request_in yet, and the lame-duck
            # drain gate must still wait for it
            self.server.on_usercode_queued()
            try:
                pool.submit(self._run_usercode, proto, msg, socket)
                return
            except RuntimeError:
                self.server.on_usercode_done()
                pass                 # pool shut down mid-stop: run here
        self._process_message_inline(proto, msg, socket)

    def _run_usercode(self, proto: Protocol, msg: Any, socket) -> None:
        try:
            self._process_message_inline(proto, msg, socket)
        finally:
            self.server.on_usercode_done()

    def _process_message_inline(self, proto: Protocol, msg: Any,
                                socket) -> None:
        try:
            if self.server is not None and proto.process_request is not None:
                # the admin port (ServerOptions.internal_port) serves ONLY
                # the http builtin pages: any other protocol on it would
                # bypass the service/admin separation — enforced HERE, the
                # one dispatch point every server protocol passes through
                if getattr(socket, "internal_only", False) and \
                        proto.name != "http":
                    socket.set_failed(
                        errors.EREQUEST,
                        f"protocol {proto.name!r} refused on the "
                        "internal admin port")
                    return
                proto.process_request(msg, socket, self.server)
            elif proto.process_response is not None:
                proto.process_response(msg, socket)
        except Exception as e:
            from ..butil import logging as log
            log.error("message processing raised: %s", e, exc_info=True)
