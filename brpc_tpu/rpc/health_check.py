"""Health checking: revive failed endpoints.

Reference: src/brpc/details/health_check.{h,cpp} (:42-237) — failed sockets
are probed periodically (reconnect, or an app-level RPC when
``health_check_path`` is set); on success the endpoint returns to service
and its circuit breaker is reset.  Probing runs on the shared TimerThread.
"""
from __future__ import annotations

import random
import threading
from typing import Any, Callable, Dict, Optional

from ..butil.endpoint import EndPoint, SCHEME_MEM, SCHEME_TCP, SCHEME_ICI
from ..butil import flags as _flags
from ..butil import debug_sync as _dbg
from ..butil import logging as log
from ..bthread.timer_thread import TimerThread
from .circuit_breaker import BreakerRegistry

_flags.define_flag("health_check_interval_s", 0.1,
                   "first probe delay for a failed endpoint (doubles per "
                   "failed probe up to health_check_max_interval_s)")
_flags.define_flag("health_check_max_interval_s", 2.0,
                   "cap on the exponential probe backoff")
_flags.define_flag("health_check_jitter", 0.2,
                   "fraction of the probe interval added as seeded "
                   "random jitter (de-synchronizes probers)")


def probe_endpoint(ep: EndPoint, timeout: float = 1.0) -> bool:
    """Transport-level reachability probe (the reference's periodic
    connect)."""
    try:
        if ep.scheme == SCHEME_TCP:
            import socket
            with socket.create_connection((ep.host, ep.port), timeout=timeout):
                return True
        if ep.scheme == SCHEME_MEM:
            from .mem_transport import _listeners, _listeners_lock
            with _listeners_lock:
                return ep.host in _listeners
        if ep.scheme == SCHEME_ICI:
            from ..ici.transport import _listeners as il, _listeners_lock as ill
            with ill:
                if ep.device_id in il:
                    return True
            # cross-process fabric endpoint: ask the owner process over
            # its control listener (a connectionless _F_PING — no fabric
            # socket is created by the probe)
            from ..ici.fabric import FabricNode
            node = FabricNode.instance()
            if node is not None and \
                    FabricNode.device_owner(ep.device_id) != node.process_id:
                return node.ping(ep.device_id, timeout=timeout)
            return False
    except OSError:
        return False
    return False


class HealthCheckTask:
    """Repeating probe for one endpoint until it revives.  Probe delays
    back off exponentially (base health_check_interval_s, doubling to
    health_check_max_interval_s) with seeded jitter so a fleet of
    checkers never stampedes a recovering peer."""

    # the registry lock guards per-task callback registration too:
    # start_health_check mutates _revive_cbs under it while the timer
    # thread's _probe snapshots it (fablint guarded-state contract)
    _GUARDED_BY = {"_revive_cbs": "_tasks_lock"}

    def __init__(self, ep: EndPoint,
                 on_revived: Optional[Callable[[EndPoint], None]] = None,
                 app_check: Optional[Callable[[EndPoint], bool]] = None,
                 max_probes: int = 0, seed: Optional[int] = None):
        self.ep = ep
        self.on_revived = on_revived
        # keyed revival callbacks (add_revive_callback): several parties
        # can care about one endpoint's revival (an LB lifting its
        # exclusion, the lame-duck registry clearing a peer-drain mark);
        # keying dedups re-registrations — a channel registers a fresh
        # lambda per breaker trip, and a long outage must not accumulate
        # one callback per trip
        self._revive_cbs: Dict[Any, Callable[[EndPoint], None]] = {}
        self.app_check = app_check          # app-level RPC probe
        self.probe_count = 0
        self.max_probes = max_probes        # 0 = unlimited
        self._rng = random.Random(
            seed if seed is not None else hash(ep) & 0xFFFFFFFF)
        self._cancelled = threading.Event()
        self._schedule()

    def next_delay_s(self) -> float:
        base = _flags.get_flag("health_check_interval_s")
        cap = _flags.get_flag("health_check_max_interval_s")
        d = min(base * (2 ** min(self.probe_count, 16)), cap)
        return d * (1.0 + _flags.get_flag("health_check_jitter")
                    * self._rng.random())

    def _schedule(self) -> None:
        TimerThread.instance().schedule_after(self._probe,
                                              self.next_delay_s())

    def _probe(self) -> None:
        if self._cancelled.is_set():
            return
        self.probe_count += 1
        ok = probe_endpoint(self.ep)
        if ok and self.app_check is not None:
            try:
                ok = self.app_check(self.ep)
            except Exception:
                ok = False
        if ok:
            BreakerRegistry.instance().breaker(self.ep).mark_recovered()
            _unregister(self.ep)
            # snapshot under the registry lock: start_health_check
            # inserts callbacks concurrently (channel breaker trips on
            # other threads), and iterating the live dict here raced
            # those inserts — a registration could be skipped or the
            # iteration could die mid-revival (fablint finding)
            with _tasks_lock:
                cbs = list(self._revive_cbs.values())
            if self.on_revived is not None:
                cbs.insert(0, self.on_revived)
            for cb in cbs:
                try:
                    cb(self.ep)
                except Exception:
                    pass
            log.info("endpoint %s revived after %d probes", self.ep,
                     self.probe_count)
            return
        if self.max_probes and self.probe_count >= self.max_probes:
            _unregister(self.ep)
            return
        self._schedule()

    def cancel(self) -> None:
        self._cancelled.set()
        _unregister(self.ep)


_tasks: Dict[EndPoint, HealthCheckTask] = {}
_tasks_lock = _dbg.make_lock("health_check._tasks_lock")

# fablint guarded-state contract for the module-level registry
_GUARDED_BY_GLOBALS = {"_tasks": "_tasks_lock"}


def start_health_check(ep: EndPoint,
                       on_revived: Optional[Callable] = None,
                       app_check: Optional[Callable] = None,
                       revive_key: Any = None) -> HealthCheckTask:
    """Ensure ``ep`` is under probing.  ``on_revived`` registers a
    revival callback; ``revive_key`` (default: the callback's code
    object, which dedups per-call-site lambdas) keys it so repeated
    registrations from one caller REPLACE rather than accumulate."""
    with _tasks_lock:
        t = _tasks.get(ep)
        if t is None:
            t = HealthCheckTask(ep, on_revived, app_check)
            _tasks[ep] = t
        elif on_revived is not None and t.on_revived is not on_revived:
            key = revive_key if revive_key is not None \
                else getattr(on_revived, "__code__", on_revived)
            t._revive_cbs[key] = on_revived
        return t


def _unregister(ep: EndPoint) -> None:
    with _tasks_lock:
        _tasks.pop(ep, None)


def checking(ep: EndPoint) -> bool:
    with _tasks_lock:
        return ep in _tasks
