"""Socket: revocable connection handles with serialized, batched writes.

Reference: src/brpc/socket.{h,cpp} — the heart of the runtime.  Kept
capabilities (SURVEY.md §2.4):

  * SocketId: versioned id from a global ResourcePool (socket_id.h:35).
    ``Socket.address(sid)`` fails after ``set_failed`` — handle revocation
    without locks.
  * Write path (socket.cpp:1584-1790): callers enqueue WriteRequests; the
    first uncontended writer drains in place, leftover work moves to a
    single "KeepWrite" tasklet that batches everyone else's requests.  One
    writer at a time, writers never block each other.
  * ``set_failed`` fails pending writes, notifies the health checker, and
    revokes the id (socket.cpp:863).
  * Input events are deduped by an atomic counter so exactly one reader
    tasklet runs per socket no matter how many readiness events fire
    (StartInputEvent, socket.cpp:2046-2090).

Transport specifics (fd IO, in-process loopback, device streams) live in
subclasses implementing ``_do_write``/``_do_read``.
"""
from __future__ import annotations

import time
from typing import Any, Callable, List, Optional

from ..butil.iobuf import IOBuf, IOPortal
from ..butil import flags as _flags
from ..butil.resource_pool import ResourcePool
from ..butil import debug_sync as _dbg
from ..butil.endpoint import EndPoint
from .. import bvar
from ..bthread import scheduler
from . import errors

_socket_pool: ResourcePool = ResourcePool()

_flags.define_flag("socket_max_unwritten_bytes", 64 * 1024 * 1024,
                   "reject writes with EOVERCROWDED beyond this backlog",
                   _flags.positive_integer)

_g_socket_count = bvar.Adder("rpc_socket_count")


class SocketStat:
    """Per-connection counters (reference SocketStat socket.h:123)."""

    __slots__ = ("in_size", "out_size", "in_num_messages", "out_num_messages")

    def __init__(self):
        self.in_size = 0
        self.out_size = 0
        self.in_num_messages = 0
        self.out_num_messages = 0


class WriteRequest:
    __slots__ = ("data", "notify_cid", "on_done", "completed")

    def __init__(self, data: IOBuf, notify_cid: int = 0,
                 on_done: Optional[Callable[[int], None]] = None):
        self.data = data
        self.notify_cid = notify_cid
        self.on_done = on_done      # on_done(error_code)
        self.completed = False


class Socket:
    """Base socket; see module docstring."""

    # fablint guarded-state contract (the write path's single-writer
    # discipline and the input-event dedup both live or die by these)
    _GUARDED_BY = {
        "_write_queue": "_write_lock",
        "_writing": "_write_lock",
        "_unwritten": "_write_lock",
        "_nevent": "_nevent_lock",
        "pipelined_contexts": "_pipeline_lock",
        "_inflight_cids": "_pipeline_lock",
        "_inflight_prune_at": "_pipeline_lock",
    }

    def __init__(self, remote_side: Optional[EndPoint] = None,
                 user: Any = None):
        self.id: int = _socket_pool.get_resource(self)
        self.remote_side = remote_side
        self.local_side: Optional[EndPoint] = None
        self.user = user                    # owner (Acceptor / SocketMap)
        self.failed = False
        self.failed_error = 0
        # logged-off (reference Socket::SetLogOff): the connection still
        # drains in-flight responses but accepts no NEW calls — SocketMap
        # replaces it on next use.  Set by h2 graceful GOAWAY.
        self.logoff = False
        self._write_queue: List[WriteRequest] = []
        self._unwritten = 0          # queued-but-unwritten bytes (EOVERCROWDED)
        self._write_lock = _dbg.make_lock("Socket._write_lock")
        self._writing = False
        self._nevent = 0                    # input-event dedup counter
        self._nevent_lock = _dbg.make_lock("Socket._nevent_lock")
        self.messenger = None               # InputMessenger set by owner
        self._read_portal = IOPortal()
        self._selected_protocol_index = -1  # protocol memory per socket
        self.stat = SocketStat()
        self.create_time = time.time()
        self.last_active = time.monotonic()   # idle-timeout reaping
        self.on_failed_callbacks: List[Callable[["Socket"], None]] = []
        self.pipelined_contexts: List[Any] = []   # redis/memcache pipelining
        self._pipeline_lock = _dbg.make_lock("Socket._pipeline_lock")
        # correlation ids written on this socket and possibly awaiting a
        # response: failed with the socket so a connection death completes
        # in-flight calls NOW instead of letting them burn their full
        # deadlines (the reference fails a Socket's waiters in SetFailed).
        # Completed cids linger until pruned — bthread_id's version guard
        # makes erroring a stale id a no-op.
        self._inflight_cids: set = set()
        self._inflight_prune_at = 256    # high-water mark (see write())
        self.health_check_interval_s = 0
        self.is_server_side = False
        # set by in-process transports when the peer closed with an
        # explicit code (lame-duck ELOGOFF): the EOF path fails the
        # socket with it instead of the generic EEOF
        self._eof_error_code = 0
        _g_socket_count << 1

    # ---- id management ----------------------------------------------
    @staticmethod
    def address(sid: int) -> Optional["Socket"]:
        s = _socket_pool.address(sid)
        return s if s is not None and not s.failed else None

    def set_failed(self, error_code: int = errors.EFAILEDSOCKET,
                   reason: str = "") -> bool:
        with self._write_lock:
            if self.failed:
                return False
            self.failed = True
            self.failed_error = error_code
            pending = self._write_queue
            self._write_queue = []
            self._unwritten = 0
        _socket_pool.return_resource(self.id)
        _g_socket_count << -1
        for req in pending:
            self._complete_write(req, error_code)
        for cb in list(self.on_failed_callbacks):
            try:
                cb(self)
            except Exception:
                pass
        # complete every call still awaiting a response on this socket:
        # its reply can never arrive now.  bthread_id's version guard
        # makes already-completed ids no-ops, and _retryable codes
        # (EFAILEDSOCKET/ELOGOFF/...) re-issue on a fresh connection.
        with self._pipeline_lock:
            inflight, self._inflight_cids = self._inflight_cids, set()
        if inflight:
            from ..bthread import id as bthread_id
            code = error_code or errors.EFAILEDSOCKET
            for cid in inflight:
                try:
                    bthread_id.error(cid, code)
                except Exception:
                    pass
        self._transport_close()
        return True

    # fablint: lock-held(_write_lock)
    def _unwritten_bytes(self) -> int:
        # running counter (maintained under _write_lock): the queue can hold
        # tens of thousands of requests under backlog, exactly when an
        # O(queue) scan per write would make the guard quadratic
        return self._unwritten

    # ---- write path ---------------------------------------------------
    def write(self, data: IOBuf, notify_cid: int = 0,
              on_done: Optional[Callable[[int], None]] = None) -> int:
        """Enqueue data; returns 0 or an error code immediately (completion
        is reported through on_done / correlation error)."""
        from . import fault_injection as _fi
        injector = _fi.active()
        if injector is not None:
            action = injector.decide(self)
            if action == _fi.DROP:
                return 0                 # bytes vanish: lossy link
            if action == _fi.ERROR:
                self.set_failed(errors.EFAILEDSOCKET, "injected fault")
                return errors.EFAILEDSOCKET
        req = WriteRequest(data, notify_cid, on_done)
        self.last_active = time.monotonic()
        if notify_cid:
            with self._pipeline_lock:
                self._inflight_cids.add(notify_cid)
                if len(self._inflight_cids) > self._inflight_prune_at:
                    # prune completed calls' ids, then move the
                    # high-water mark past the LIVE population so a
                    # steady state of many genuinely-concurrent calls
                    # doesn't rescan on every write (O(N) each time)
                    from ..bthread import id as bthread_id
                    self._inflight_cids = {
                        c for c in self._inflight_cids
                        if bthread_id.is_live(c)}
                    self._inflight_prune_at = max(
                        256, 2 * len(self._inflight_cids))
        with self._write_lock:
            if self.failed:
                err = self.failed_error or errors.EFAILEDSOCKET
                # complete outside the lock
            elif self._unwritten_bytes() > _flags.get_flag(
                    "socket_max_unwritten_bytes"):
                err = errors.EOVERCROWDED
            else:
                self._write_queue.append(req)
                self._unwritten += len(data)
                if self._writing:
                    return 0
                self._writing = True
                err = None
        if err is not None:
            self._complete_write(req, err)
            return err
        # we are the writer: drain once in place; leftover (transport not
        # writable) moves to a KeepWrite tasklet that batches later writers
        if not self._drain():
            scheduler.start_urgent(self._keep_write, name="keep_write")
        return 0

    def _drain(self) -> bool:
        """Write head requests until the queue empties (release writer,
        return True) or the transport stops accepting (stay writer, return
        False so the caller reschedules via KeepWrite)."""
        while True:
            with self._write_lock:
                if self.failed or not self._write_queue:
                    self._writing = False
                    return True
                req = self._write_queue[0]
            try:
                n = self._do_write(req.data)
            except Exception as e:
                self.set_failed(errors.EFAILEDSOCKET, str(e))
                return True
            if n < 0:           # transport not writable now
                return False
            self.stat.out_size += n
            if n > 0:
                with self._write_lock:
                    self._unwritten = max(0, self._unwritten - n)
            if len(req.data) == 0:
                with self._write_lock:
                    if self._write_queue and self._write_queue[0] is req:
                        self._write_queue.pop(0)
                self.stat.out_num_messages += 1
                self._complete_write(req, 0)

    def _keep_write(self) -> None:
        while True:
            if self._drain():
                return
            if not self._wait_writable():
                return

    def _complete_write(self, req: WriteRequest, error_code: int) -> None:
        with self._write_lock:
            if req.completed:
                return
            req.completed = True
        if req.on_done is not None:
            try:
                req.on_done(error_code)
            except Exception:
                pass
        if error_code != 0 and req.notify_cid:
            from ..bthread import id as bthread_id
            bthread_id.error(req.notify_cid, error_code)

    def _wait_writable(self, timeout: float = 30.0) -> bool:
        """Block until the transport can accept bytes again (EPOLLOUT
        analogue).  Default: brief yield for transports without readiness."""
        time.sleep(0.001)
        return not self.failed

    # ---- input path ---------------------------------------------------
    def start_input_event(self, inline: bool = False) -> None:
        self.last_active = time.monotonic()
        return self._start_input_event(inline)

    def _start_input_event(self, inline: bool = False) -> None:
        """Readiness notification; guarantees a single reader no matter how
        many events fire.  ``inline=True`` (loopback/device transports on
        the delivering thread) runs the reader directly instead of spawning
        a tasklet — the Python translation of the reference's
        bthread_start_urgent-for-cache-locality (socket.cpp:2084): zero
        scheduling hops on the latency path, while the released-readership
        discipline in _process_event keeps slow handlers from blocking the
        connection."""
        with self._nevent_lock:
            self._nevent += 1
            if self._nevent > 1:
                return
        if inline:
            self._process_event()
        else:
            scheduler.start_urgent(self._process_event, name="sock_reader")

    def _process_event(self) -> None:
        while True:
            last = None
            if self.messenger is not None:
                try:
                    last = self.messenger.on_new_messages(self)
                except Exception as e:
                    from ..butil import logging as log
                    log.error("input processing failed on %s: %s",
                              self.remote_side, e)
                    self.set_failed(errors.EFAILEDSOCKET, str(e))
            with self._nevent_lock:
                left = self._nevent - 1
                self._nevent = 1 if left > 0 else 0
                more = left > 0
            if not more:
                # readership released: the last message runs in this tasklet
                # for cache locality, but a slow handler now only blocks
                # itself — new readiness spawns a fresh reader.  Sockets
                # that parse INLINE on their delivering thread (fabric:
                # the control read loop) must never run a handler there —
                # a slow handler would stall CREDIT/PULLED processing for
                # the whole connection — so they queue it instead
                if last is not None and self.messenger is not None:
                    if getattr(self, "queue_last_message", False):
                        self.messenger._queue_message(*last, self)
                    else:
                        self.messenger.process_in_place(last, self)
                return
            # more events pending: keep readership, hand the holdover to its
            # own tasklet and loop back to read
            if last is not None and self.messenger is not None:
                self.messenger._queue_message(*last, self)

    # ---- pipelining (redis/memcache; socket.h:256-262) ----------------
    def push_pipelined_context(self, ctx: Any) -> None:
        with self._pipeline_lock:
            self.pipelined_contexts.append(ctx)

    def pop_pipelined_context(self) -> Optional[Any]:
        with self._pipeline_lock:
            return self.pipelined_contexts.pop(0) if self.pipelined_contexts else None

    # ---- transport hooks ----------------------------------------------
    def _do_write(self, data: IOBuf) -> int:
        raise NotImplementedError

    def _do_read(self, portal: IOPortal, max_count: int) -> int:
        """Read available bytes into portal; -1 on EAGAIN, 0 on EOF."""
        raise NotImplementedError

    def _transport_close(self) -> None:
        pass

    def description(self) -> str:
        return (f"Socket{{id={self.id} remote={self.remote_side} "
                f"failed={self.failed} in={self.stat.in_size}B "
                f"out={self.stat.out_size}B}}")


def list_sockets() -> List[Socket]:
    """Debug enumeration for the /sockets builtin service."""
    return [s for s in _socket_pool.live_payloads()
            if isinstance(s, Socket)]
