"""Loopback call plane: direct in-process dispatch for mem:// channels.

The deployed-common case the tentpole optimizes is a Python handler
behind an in-process transport.  For ici:// the native plane already
short-circuits the Python socket machinery (channel.py's fast path);
this module is the same idea for mem:// — the gRPC *in-process
transport* analogue: when the client channel and the server live in one
process, a unary tpu_std call skips the byte codec, socket pair, event
dispatch, and correlation-id machinery entirely and dispatches straight
into the server's method table.

What is NOT skipped — semantics are the wire path's, line for line:

* admission: lame-duck draining (retryable ELOGOFF), server
  max_concurrency (ELIMIT), per-method concurrency limiters (ELIMIT),
  ENOMETHOD/ENOSERVICE;
* accounting: ``Server.on_request_in/out``, MethodStatus
  on_requested/on_responded (the /status page and the lame-duck drain
  gate see loopback requests exactly like wire ones), and the
  ``usercode_in_pthread`` queued counter;
* isolation: the handler gets its OWN pooled server Controller and a
  request object parsed from the serialized bytes (a handler mutating
  its request never corrupts the caller's), and handlers run inline
  only on ``usercode_inline`` servers — otherwise they dispatch to a
  tasklet / the usercode backup pool, same as InputMessenger;
* failure surface: ERPCTIMEDOUT on deadline expiry and ECANCELED on
  Controller.cancel(), with the same late-completion guard the
  correlation id gives the wire path (a response landing after the
  claim is dropped, never written into a controller the caller may be
  reusing); and a lame-duck stop past its grace window fails in-flight
  loopback stragglers with ELOGOFF exactly like it fails wire
  connections (Server._stop_locked → fail_inflight).

Ineligible calls fall through to the wire path — the screens live in
channel.py: streaming, auth (channel- or server-side), compression,
backup-request hedging, fault injection, rpc_dump sampling, rpcz-sampled
requests (the wire path carries the server span + stage annotations),
and ``tpu_std_stage_metrics=on`` (the dedicated wire-pipeline
measurement mode).

Attachments cross by reference (zero-copy, the point of an in-process
plane): the server controller's request_attachment IS the caller's
IOBuf, and the response_attachment IOBuf moves back by reference.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ..butil import debug_sync as _dbg
from ..butil import flags as _flags
from ..butil import logging as log
from . import errors
from .controller import Controller, server_controller_pool

_loopback_flag = _flags.define_flag(
    "mem_loopback_fast", True,
    "direct in-process dispatch for unary tpu_std calls on mem:// "
    "channels (skips the byte codec and socket machinery; admission, "
    "limits, accounting, and drain semantics identical to the wire "
    "path).  Off forces every mem:// call through the wire plane.")

# fablint guarded-state: both registries only mutate under their lock.
_GUARDED_BY_GLOBALS = {"_servers": "_servers_lock",
                       "_inflight": "_inflight_lock"}
# mem name -> Server, maintained by Server.start/_teardown_listeners
_servers: Dict[str, Any] = {}
_servers_lock = _dbg.make_lock("loopback._servers_lock")
# id(server) -> set of in-flight _CallStates (the lame-duck straggler
# hook's view; entries deregister at completion)
_inflight: Dict[int, set] = {}
_inflight_lock = _dbg.make_lock("loopback._inflight_lock")


def register_server(name: str, server) -> None:
    with _servers_lock:
        _servers[name] = server


def unregister_server(name: str, server) -> None:
    with _servers_lock:
        if _servers.get(name) is server:
            del _servers[name]


def server_for(name: str):
    """The in-process Server listening on mem://<name>, or None."""
    with _servers_lock:
        return _servers.get(name)


def enabled() -> bool:
    return bool(_loopback_flag.value)


class _CallState:
    """First-of(completion, timeout, cancel, lame-duck-fail) arbitration
    — the loopback translation of the correlation id's version guard:
    exactly one side writes the client-visible result."""

    __slots__ = ("lock", "finished", "event", "server_key", "cntl",
                 "done", "t0")

    def __init__(self, server_key: int, cntl, done, t0: int):
        self.lock = threading.Lock()
        self.finished = False
        self.event: Optional[threading.Event] = None
        self.server_key = server_key
        self.cntl = cntl
        self.done = done
        self.t0 = t0

    def try_finish(self) -> bool:
        with self.lock:
            if self.finished:
                return False
            self.finished = True
            ev = self.event
        _inflight_remove(self)
        if ev is not None:
            ev.set()
        return True

    def wait_begin(self) -> Optional[threading.Event]:
        """Arm (or reuse) the park event; None when already finished.
        The sync caller and any join()ers share one event."""
        with self.lock:
            if self.finished:
                return None
            if self.event is None:
                self.event = threading.Event()
            return self.event


def _inflight_add(state: _CallState) -> None:
    with _inflight_lock:
        _inflight.setdefault(state.server_key, set()).add(state)


def _inflight_remove(state: _CallState) -> None:
    with _inflight_lock:
        bucket = _inflight.get(state.server_key)
        if bucket is not None:
            bucket.discard(state)
            if not bucket:
                del _inflight[state.server_key]


def fail_inflight(server, code: int, text: str) -> int:
    """Lame-duck straggler handling (Server._stop_locked, past-grace
    phase): claim every in-flight loopback call on ``server`` with the
    given error — the loopback analogue of failing the server's wire
    connections.  The still-running handlers finish on their own; their
    late done() is dropped by the claim.  Returns the number failed."""
    with _inflight_lock:
        states = list(_inflight.get(id(server), ()))
    n = 0
    for st in states:
        if st.try_finish():
            st.cntl.set_failed(code, text)
            st.cntl.latency_us = (time.monotonic_ns() - st.t0) // 1000
            _finish_client(st.cntl, st.done)
            n += 1
    return n


def cancel(cntl: Controller) -> bool:
    """Controller.cancel() hook: claim an in-flight loopback call with
    ECANCELED (the late server completion is dropped)."""
    state = cntl.__dict__.get("_loopback_state")
    if state is None or not state.try_finish():
        return False
    cntl.set_failed(errors.ECANCELED, "canceled by caller")
    cntl.latency_us = (time.monotonic_ns() - state.t0) // 1000
    _finish_client(cntl, state.done)
    return True


def call(server, method_full_name: str, cntl: Controller, request: Any,
         response_cls: Any, done=None):
    """One loopback RPC.  Sync (done is None): returns the response or
    None on failure, cntl filled either way.  Async: schedules done(cntl)
    after completion and returns None."""
    t0 = time.monotonic_ns()
    state = _CallState(id(server), cntl, done, t0)
    cntl._loopback_state = state
    _inflight_add(state)
    inline = bool(getattr(server.options, "usercode_inline", False))
    try:
        req_bytes = request.SerializeToString()
    except AttributeError:
        req_bytes = bytes(request) if request is not None else b""

    if inline:
        _serve(server, method_full_name, cntl, req_bytes, response_cls,
               state)
    else:
        # mirror InputMessenger._process_message: the usercode backup
        # pool when configured (queued-counter accounting for the drain
        # gate), else a scheduler tasklet
        pool = getattr(server, "usercode_pool", None)
        dispatched = False
        if pool is not None:
            server.on_usercode_queued()
            try:
                pool.submit(_serve_pooled, server, method_full_name, cntl,
                            req_bytes, response_cls, state)
                dispatched = True
            except RuntimeError:
                server.on_usercode_done()
        if not dispatched:
            from ..bthread import scheduler
            scheduler.start_background(
                _serve, server, method_full_name, cntl, req_bytes,
                response_cls, state,
                name=f"loopback:{method_full_name}")

    tms = cntl.timeout_ms
    if done is not None:
        if not state.finished and tms and tms > 0:
            from ..bthread.timer_thread import TimerThread
            TimerThread.instance().schedule_after(
                lambda: _timeout(cntl, state), tms / 1000.0)
        return None
    if not state.finished:
        ev = state.wait_begin()
        if ev is not None:
            from ..bthread import scheduler
            scheduler.note_worker_blocked()
            try:
                # the deadline is the CLIENT's: claim ERPCTIMEDOUT the
                # moment it expires (wire parity — its timer would fire
                # now), while the server side keeps running and its late
                # completion is dropped by the claim
                ev.wait(tms / 1000.0 if tms and tms > 0 else None)
            finally:
                scheduler.note_worker_unblocked()
            _timeout(cntl, state)
    return cntl.response if not cntl.failed() else None


def _timeout(cntl: Controller, state: _CallState) -> None:
    """Deadline expiry: claim the completion if the server hasn't."""
    if not state.try_finish():
        return
    cntl.latency_us = (time.monotonic_ns() - state.t0) // 1000
    cntl.set_failed(errors.ERPCTIMEDOUT,
                    f"reached timeout={cntl.timeout_ms}ms")
    _finish_client(cntl, state.done)


def _finish_client(cntl: Controller, done) -> None:
    if cntl.span is not None:
        from .span import end_client_span
        end_client_span(cntl)
    if done is not None:
        from ..bthread import scheduler
        scheduler.start_background(done, cntl, name="rpc_done")


def _serve_pooled(server, full_name, cntl, req_bytes, response_cls,
                  state) -> None:
    try:
        _serve(server, full_name, cntl, req_bytes, response_cls, state)
    finally:
        server.on_usercode_done()


def _serve(server, full_name: str, client_cntl: Controller,
           req_bytes: bytes, response_cls, state: _CallState) -> None:
    """Server half: admission → parse → invoke → completion copy-back.
    Runs inline on the caller (usercode_inline) or on a tasklet/pool
    thread; semantically the loopback ProcessRpcRequest."""
    t0 = state.t0
    done = state.done
    cntl = server_controller_pool.acquire()  # fablint: custody-moved(request-lifecycle) the shim rides the request; _maybe_recycle releases it back to the pool when the response (or failure path) completes
    cntl.server = server
    if client_cntl.log_id:
        cntl.log_id = client_cntl.log_id
    ep = server.listen_endpoint
    cntl.remote_side = ep
    cntl.local_side = ep
    tms = client_cntl.timeout_ms
    if tms and tms > 0:
        cntl.method_deadline = time.monotonic() + tms / 1000.0
    # admission-metadata propagation is in-process: the caller's
    # controller IS the carrier (no wire decode).  Copied for EVERY
    # call, not just under an admission controller — handlers read
    # cntl.priority/tenant/deadline_left_ms on all planes, and the
    # cascading request context (rpc/request_context.py) inherits from
    # these fields
    cntl.priority = client_cntl.priority
    cntl.tenant = client_cntl.tenant
    if tms and tms > 0:
        cntl.deadline_left_ms = int(tms)

    def bail(code: int, text: str, status=None, counted=False,
             retry_after: int = 0) -> None:
        if status is not None:
            status.on_responded(code, 0)
        if counted:
            server.on_request_out()
        cntl._maybe_recycle()
        if not state.try_finish():
            return
        client_cntl.set_failed(code, text)
        if retry_after:
            client_cntl.retry_after_ms = retry_after
        client_cntl.latency_us = (time.monotonic_ns() - t0) // 1000
        _finish_client(client_cntl, done)

    if server.is_draining():
        bail(errors.ELOGOFF, "server is draining (lame duck)")
        return
    md = server.find_method(full_name)
    adm = server.admission
    if adm is not None:
        # admission-control path: identical decision to the wire plane
        # (shed-before-queue, WFQ, deadline shed) — loopback calls are
        # not a back door around overload protection
        if md is None:
            service = full_name.rpartition(".")[0]
            bail(errors.ENOMETHOD if service in server.services()
                 else errors.ENOSERVICE, f"no method {full_name}")
            return
        status = server.method_status(full_name)
        from . import admission as admission_mod
        adm.submit(
            priority=client_cntl.priority, tenant=client_cntl.tenant,
            deadline_left_ms=int(tms) if tms and tms > 0 else None,
            recv_us=t0 // 1000,
            try_enter=admission_mod.server_method_gate(server, status),
            run=lambda queued_us: _execute(server, full_name, cntl,
                                           client_cntl, req_bytes,
                                           response_cls, state, md,
                                           status),
            shed=lambda code, text, ra: bail(code, text, retry_after=ra))
        return
    if not server.on_request_in():
        bail(errors.ELIMIT, "server max_concurrency reached")
        return
    if md is None:
        service = full_name.rpartition(".")[0]
        bail(errors.ENOMETHOD if service in server.services()
             else errors.ENOSERVICE, f"no method {full_name}",
             counted=True)
        return
    status = server.method_status(full_name)
    if status is not None and not status.on_requested():
        bail(errors.ELIMIT, f"method {full_name} max_concurrency reached",
             counted=True)
        return
    _execute(server, full_name, cntl, client_cntl, req_bytes,
             response_cls, state, md, status)


def _execute(server, full_name: str, cntl: Controller,
             client_cntl: Controller, req_bytes: bytes, response_cls,
             state: _CallState, md, status) -> None:
    """Gates held: parse → invoke → completion copy-back (the post-
    admission half of the loopback ProcessRpcRequest)."""
    t0 = state.t0
    done = state.done

    def bail(code: int, text: str, status=None, counted=False) -> None:
        if status is not None:
            status.on_responded(code, 0)
        if counted:
            server.on_request_out()
        cntl._maybe_recycle()
        if not state.try_finish():
            return
        client_cntl.set_failed(code, text)
        client_cntl.latency_us = (time.monotonic_ns() - t0) // 1000
        _finish_client(client_cntl, done)

    start_us = time.monotonic_ns() // 1000
    try:
        request = md.request_cls()
        request.ParseFromString(req_bytes)
    except Exception as e:
        bail(errors.EREQUEST, f"fail to parse request: {e}",
             status=status, counted=True)
        return
    # zero-copy attachment pass: the handler sees the CALLER's request
    # attachment IOBuf (in-process plane; mutating cuts consume it).
    # Session-local data stays LAZY (Controller.session_local_data).
    req_att = client_cntl._peek_request_attachment()
    if req_att is not None:
        cntl.request_attachment = req_att
    response = md.response_cls()
    done_called = [False]

    def s_done() -> None:
        if done_called[0]:
            return
        done_called[0] = True
        err = cntl.error_code_
        if status is not None:
            status.on_responded(err,
                                time.monotonic_ns() // 1000 - start_us)
        server.on_request_out()
        if not state.try_finish():
            return       # caller timed out / canceled / lame-duck-failed:
        #                  dropped like a stale correlation version
        if err:
            client_cntl.set_failed(err, cntl.error_text_)
            # a handler-set shed hint rides back exactly like the wire
            # plane's ResponseMeta (tpu_std.py packs cntl.retry_after_ms
            # for the same shape — loopback is not a hint black hole)
            if cntl.retry_after_ms:
                client_cntl.retry_after_ms = cntl.retry_after_ms
        else:
            resp_att = cntl._peek_response_attachment()
            if resp_att is not None and len(resp_att):
                client_cntl.response_attachment = resp_att
                # detach so the pooled shim's reset can't recycle the
                # buffer now owned by the caller
                cntl.__dict__.pop("response_attachment", None)
            if response_cls is None:
                client_cntl.response = response.SerializeToString()
            elif md.response_cls is response_cls:
                client_cntl.response = response
            else:
                out = response_cls()
                out.ParseFromString(response.SerializeToString())
                client_cntl.response = out
            client_cntl.error_code_ = 0
        client_cntl.latency_us = (time.monotonic_ns() - t0) // 1000
        _finish_client(client_cntl, done)

    cntl.set_server_done(s_done)
    try:
        md.invoke(cntl, request, response, s_done)
    except Exception as e:   # uncaught user exception → EINTERNAL
        log.error("method %s raised: %s", full_name, e, exc_info=True)
        if not done_called[0]:
            cntl.set_failed(errors.EINTERNAL, f"{type(e).__name__}: {e}")
            s_done()
            cntl._release_session_data()
            cntl._maybe_recycle()
