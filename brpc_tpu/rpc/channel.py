"""Channel: the client stub.

Reference: src/brpc/channel.{h,cpp} (Init :236-393, CallMethod :407-592) and
Controller::IssueRPC (controller.cpp:985-1144).  A channel targets a single
endpoint or a naming service + load balancer; per-call state lives in the
Controller; connection selection honors single/pooled/short types.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..butil.endpoint import EndPoint, parse_endpoint
from . import errors
from .controller import Controller
from .input_messenger import InputMessenger
from . import loopback as _loopback
from .protocol import find_protocol
from . import request_context as _reqctx
from .socket_map import SocketMap
from .span import end_client_span, maybe_start_client_span


@dataclass
class ChannelOptions:
    protocol: str = "tpu_std"
    # "" = adaptive: single when the protocol supports it, else pooled
    # (reference adaptive_connection_type.h); explicit values are enforced
    connection_type: str = ""           # "" | single | pooled | short
    timeout_ms: int = 1000
    max_retry: int = 3
    backup_request_ms: int = 0          # 0 = disabled
    # Opt-in: split timeout_ms evenly over max_retry+1 tries and hedge a
    # fresh try when a try's share elapses silently (recovers requests a
    # lossy fabric *dropped*).  Off by default because a hedged try can
    # duplicate a non-idempotent request — same caveat as backup_request_ms
    # (docs/cn/backup_request.md); the reference treats ERPCTIMEDOUT as
    # final.  Ignored when backup_request_ms is set (that is already the
    # user's explicit hedging schedule).
    retry_on_timeout: bool = False
    # Base delay before a retry after a connection-class failure
    # (EFAILEDSOCKET/ECONNREFUSED/...), doubling per retry with ±25%
    # seeded jitter.  0 (default) retries immediately — the historical
    # behavior.  Spaced retries are what let one generously-budgeted
    # call issued DURING an endpoint outage survive until health-check
    # revival brings the peer back (docs/PARITY.md failure semantics).
    retry_backoff_ms: int = 0
    connect_timeout_ms: int = 1000
    auth: object = None                 # Authenticator
    ssl_context: object = None          # ssl.SSLContext for TLS channels
    ns_filter: object = None            # NamingServiceFilter: fn(ServerEntry)->bool
    # The mesh device this channel's caller "lives on" for ici://
    # targets: response device refs relocate TOWARD it.  None keeps the
    # historical default (the target's neighbor, (remote+1) % mesh.size
    # — every response pays one relocation hop); a caller colocated with
    # the server passes the server's own device id for the pure ref-pass
    # round trip.
    ici_local_device: object = None     # Optional[int]
    # Admission-control defaults stamped on every call that didn't set
    # its own (Controller.priority/tenant): priority band 0=critical ..
    # 3=sheddable (None = let the server apply its default band) and the
    # fair-queueing tenant this channel's traffic belongs to.
    priority: Optional[int] = None
    tenant: str = ""


# loopback-screen module handles, resolved once at first call (lazy only
# to dodge the policy<->rpc import cycle at load time)
_loopback_screen = None


def _loopback_screen_modules():
    global _loopback_screen
    if _loopback_screen is None:
        from . import fault_injection as _fi
        from . import rpc_dump as _dump
        from ..policy.tpu_std import _stage_flag
        _loopback_screen = (_fi, _dump, _stage_flag)
    return _loopback_screen


class Channel:
    def __init__(self):
        self.options = ChannelOptions()
        self._endpoint: Optional[EndPoint] = None
        self._lb = None                 # LoadBalancer
        self._ns_thread = None          # NamingServiceThread
        self._protocol = None
        self.messenger = InputMessenger(server=None)
        self._native_ici = None
        self._native_ici_lock = threading.Lock()

    # ---- init ---------------------------------------------------------
    def init(self, target: Any, lb_name: str = "",
             options: Optional[ChannelOptions] = None) -> int:
        if options is not None:
            self.options = options
        self._protocol = find_protocol(self.options.protocol)
        if self._protocol is None:
            raise ValueError(f"unknown protocol {self.options.protocol!r}")
        from .protocol import (CONNECTION_TYPE_SINGLE, CONNECTION_TYPE_POOLED,
                               CONNECTION_TYPE_SHORT)
        _ctype_bits = {"single": CONNECTION_TYPE_SINGLE,
                       "pooled": CONNECTION_TYPE_POOLED,
                       "short": CONNECTION_TYPE_SHORT}
        if self.options.connection_type not in ("",) and \
                self.options.connection_type not in _ctype_bits:
            raise ValueError(
                f"unknown connection_type {self.options.connection_type!r}")
        want = _ctype_bits.get(self.options.connection_type)
        if want is not None and not (
                self._protocol.supported_connection_type & want):
            # the reference fails Channel::Init on an unsupported explicit
            # connection type rather than silently changing it
            raise ValueError(
                f"protocol {self._protocol.name!r} does not support "
                f"connection_type={self.options.connection_type!r}")
        if isinstance(target, EndPoint):
            self._endpoint = target
            return 0
        from ..policy.naming import is_naming_url
        if isinstance(target, str) and is_naming_url(target):
            # naming-service url (file://, list://, http://, mesh://, …)
            from ..policy.naming import get_naming_service_thread
            from ..policy.load_balancers import create_load_balancer
            self._lb = create_load_balancer(lb_name or "rr")
            self._ns_thread = get_naming_service_thread(target)
            watcher = self._lb
            if self.options.ns_filter is not None:
                watcher = _FilteredWatcher(self._lb, self.options.ns_filter)
            # remembered so close() can detach THIS object — removing
            # the raw LB would miss the filter wrapper (review finding)
            self._ns_watcher = watcher
            self._ns_thread.add_watcher(watcher)
            return 0
        self._endpoint = parse_endpoint(target) if isinstance(target, str) else target
        # loopback fast-plane eligibility (channel-level screens; the
        # per-call ones live in call_method): unary tpu_std against an
        # in-process mem:// server, no auth, no hedging
        from ..butil.endpoint import SCHEME_MEM as _MEM
        if (self._endpoint is not None
                and getattr(self._endpoint, "scheme", None) == _MEM
                and self.options.protocol == "tpu_std"
                and self.options.auth is None
                and self.options.backup_request_ms <= 0):
            self._loopback_name = self._endpoint.host
            # the breaker gate from _select_socket, honored on the fast
            # plane too: an isolated endpoint fails fast even in-process
            # (loopback traffic itself never trips or resets breakers —
            # there is no connection to be unhealthy)
            from .circuit_breaker import BreakerRegistry
            self._loopback_breaker = \
                BreakerRegistry.instance().breaker(self._endpoint)
        return 0

    # ---- calls ----------------------------------------------------------
    def call_method(self, method_full_name: str, cntl: Controller,
                    request: Any, response_cls: Any = None,
                    done: Optional[Callable[[Controller], None]] = None):
        """Sync when done is None (returns the response); async otherwise."""
        # fused native fast path (ISSUE 13): a cached in-process ici
        # binding bound with ici_fused_dispatch serves sync calls
        # through ONE flat code object (context inherit, screens, issue,
        # response, error tails all inside call_fused).  Anything it
        # can't serve — oversize frames, hedging, a dead conn's one-shot
        # re-route — returns the FALLTHROUGH sentinel and the unfused
        # body below handles it exactly as before.
        nch0 = self._native_ici
        if (nch0 is not None and done is None and nch0._fused
                and cntl.stream_creator is None):
            result = nch0.call_fused(method_full_name, cntl, request,
                                     response_cls, self)
            if result is not nch0.FUSED_FALLTHROUGH:
                return result
            skip_native = True     # the fused leg already decided the
        else:                      # re-route; don't re-enter the native
            skip_native = False    # block below
        # cascading inbound context (rpc/request_context.py): a call made
        # inside a handler's scope inherits the inbound priority/tenant
        # unless THIS call overrides them, and its timeout is capped at
        # the inbound deadline budget minus the handler time already
        # spent.  Inherited values beat channel-wide defaults (a static
        # channel config must not demote a critical inbound request).
        _ctx = _reqctx.current()
        if _ctx is not None:
            if cntl.priority is None and _ctx.priority is not None:
                cntl.priority = _ctx.priority
            if not cntl.tenant and _ctx.tenant:
                cntl.tenant = _ctx.tenant
            residual = _ctx.residual_deadline_ms()
            if residual is not None:
                if residual <= 0:
                    cntl.set_failed(
                        errors.ERPCTIMEDOUT,
                        "inherited deadline budget spent before call")
                    if cntl.span is not None:
                        end_client_span(cntl)
                    if done is not None:
                        done(cntl)
                        return None
                    return None
                base = cntl.timeout_ms if cntl.timeout_ms is not None \
                    else self.options.timeout_ms
                if base is None or base <= 0 or base > residual:
                    cntl.timeout_ms = max(int(residual), 1)
        # channel-level admission defaults (per-call Controller wins)
        if cntl.priority is None and self.options.priority is not None:
            cntl.priority = self.options.priority
        if not cntl.tenant and self.options.tenant:
            cntl.tenant = self.options.tenant
        # ici:// fast path: when the target device has a native listener in
        # this process, the whole unary hot path (frame/window/dispatch/
        # correlation) runs in native/rpc.cpp — no Python between
        # serialize and parse except device-ref relocation (VERDICT r3 #1).
        # Streaming, auth, non-tpu_std protocols, backup-request hedging,
        # and frames too large for the native send window ride the Python
        # plane (which drains big payloads chunkwise through its credit
        # window).
        nch = None if skip_native else self._native_ici
        if nch is None:
            if not skip_native:
                nch = self._native_ici_binding(cntl)
        elif cntl.stream_creator is not None:
            # the cached-binding fast path must re-screen the ONE
            # eligibility input that varies per call; the channel-level
            # ones (protocol, auth, endpoint) were screened at cache time
            nch = None
        if nch is not None and not self._fast_call_fits(nch, cntl, request):
            nch = None
        if nch is not None:
            if cntl.timeout_ms is None:
                cntl.timeout_ms = self.options.timeout_ms
            if done is None:
                result = self._native_ici_call(nch, method_full_name, cntl,
                                               request, response_cls)
                result = self._native_shed_retry(nch, method_full_name,
                                                 cntl, request,
                                                 response_cls, result)
                if not self._native_ici_fallback(cntl):
                    if cntl.span is not None:
                        end_client_span(cntl)
                    return result
            else:
                from ..bthread import scheduler

                def _run():
                    try:
                        self._native_ici_call(nch, method_full_name, cntl,
                                              request, response_cls)
                    except Exception as e:   # done() must ALWAYS fire
                        if not cntl.failed():
                            cntl.set_failed(errors.EINTERNAL,
                                            f"{type(e).__name__}: {e}")
                        done(cntl)
                        return
                    if self._native_ici_fallback(cntl):
                        # dead native conn (server restarted) or oversize
                        # fast-fail: re-route through the Python plane
                        self.call_method(method_full_name, cntl, request,
                                         response_cls, done=done)
                    else:
                        if cntl.span is not None:
                            end_client_span(cntl)
                        done(cntl)

                scheduler.start_background(
                    _run, name=f"ici-call:{method_full_name}")
                return None
        # mem:// loopback fast plane (loopback.py): in-process direct
        # dispatch, no byte codec / socket machinery.  Per-call screens:
        # anything the wire plane implements that loopback doesn't
        # (streaming handshakes, compression, fault injection, rpc_dump
        # sampling) falls through.
        lb_name = getattr(self, "_loopback_name", None)
        if (lb_name is not None and cntl.stream_creator is None
                and cntl.compress_type == 0 and not cntl.auth_token
                and _loopback.enabled()):
            hot = _loopback_screen_modules()
            _fi, _dump, _stage_flag = hot
            if cntl.span is None:
                maybe_start_client_span(cntl, method_full_name)
            srv = _loopback.server_for(lb_name)
            # rpcz-sampled requests and the stage-metrics measurement
            # mode ride the wire plane: they exist to observe it (server
            # span, five-stage decomposition); auth verification needs
            # the wire socket context
            if (srv is not None and cntl.span is None
                    and srv.options.auth is None
                    and not self._loopback_breaker.is_isolated()
                    and _stage_flag.value != "on"
                    and _fi.active() is None
                    and not _dump.dump_enabled()):
                if cntl.timeout_ms is None:
                    cntl.timeout_ms = self.options.timeout_ms
                # loopback completes the client span itself (the span
                # ends with the response, also on async completions)
                return _loopback.call(srv, method_full_name, cntl,
                                      request, response_cls, done)
        if self.options.auth is not None and not cntl.auth_token:
            cntl.auth_token = self.options.auth.generate_credential(cntl)
        payload = self._protocol.serialize_request(request, cntl)
        if cntl.span is None:
            maybe_start_client_span(cntl, method_full_name)
        cntl._start_call(self, method_full_name, payload, response_cls, done)
        if done is None:
            timeout = ((cntl.timeout_ms or 0) / 1000.0 + 35.0)
            cntl.join(timeout)
            return cntl.response
        return None

    def _fast_call_fits(self, nch, cntl: Controller, request) -> bool:
        """Per-call screen for the native fast plane: the frame (payload
        + attachment + headroom) must fit the native send window, and
        backup-request hedging rides the Python plane."""
        try:                            # non-proto requests have no size
            req_sz = request.ByteSize()
        except Exception:
            req_sz = 0
        return (len(cntl.request_attachment) + req_sz + 65536
                <= nch.window_bytes
                and self.options.backup_request_ms <= 0)

    def inline_fast_call_ok(self, cntl: Controller, request,
                            method_full_name: str) -> bool:
        """True when THIS call would take the native in-process fast
        path AND the listener answers it inline on the caller's thread —
        i.e. issuing it synchronously from a fan-out loop costs nothing
        over a tasklet (the handler runs in the caller's stack either
        way).  Used by ParallelChannel's inline-issue optimization; must
        mirror call_method's routing screens exactly, or a fan-out
        commits to inline issue and then serializes on the Python plane
        (review finding r5)."""
        nch = self._native_ici
        if nch is None or cntl.stream_creator is not None:
            return False
        if not self._fast_call_fits(nch, cntl, request):
            return False
        from ..ici import native_plane
        return native_plane.listener_dispatch_inline(
            nch.remote_dev, method_full_name) is True

    def _native_ici_call(self, nch, method_full_name: str,
                         cntl: Controller, request, response_cls):
        """One fast-path RPC with the Python plane's client tracing
        (rpcz span).  No retry loop: the only retryable error an
        in-process transport can produce is EFAILEDSOCKET (our conn died
        with the server), which _native_ici_fallback re-routes; every
        other failure here is deterministic (ENOMETHOD, ELIMIT, parse,
        timeout) and would fail identically on a retry."""
        if cntl.span is None:
            maybe_start_client_span(cntl, method_full_name)
        return nch.call(method_full_name, cntl, request, response_cls)

    def _native_shed_retry(self, nch, method_full_name: str,
                           cntl: Controller, request, response_cls,
                           result):
        """Honor an admission shed's retry_after_ms on the native fast
        plane (sync calls): the server said how long its backlog needs —
        sleep the hint (plus jitter ABOVE it, never below: synchronized
        re-arrival is the storm the shed exists to prevent) and reissue,
        bounded by the retry budget and the overall deadline.  The wire
        plane gets the same behavior through the Controller retry
        machinery (handle_response)."""
        import time as _time

        from .admission import shed_backoff_s
        max_retry = cntl.max_retry if cntl.max_retry is not None \
            else self.options.max_retry
        attempt = 0
        orig_tms = cntl.timeout_ms
        # the budget started when the FIRST attempt was issued: count its
        # already-recorded duration against the deadline, so the whole
        # loop — attempts AND backoffs — is bounded by ONE timeout_ms
        # (the wire plane's single-deadline-timer semantics)
        t0 = _time.monotonic() - (cntl.latency_us / 1e6)
        try:
            while (cntl.error_code_ == errors.ELIMIT
                   and cntl.retry_after_ms > 0 and attempt < max_retry):
                attempt += 1
                delay_s = shed_backoff_s(cntl.retry_after_ms)
                if orig_tms and orig_tms > 0:
                    remaining = orig_tms / 1000.0 \
                        - (_time.monotonic() - t0)
                    if delay_s >= remaining:
                        # the backoff cannot fit the budget: the overall
                        # deadline wins, like the wire plane's timer
                        cntl.set_failed(
                            errors.ERPCTIMEDOUT,
                            f"reached timeout={orig_tms}ms backing "
                            "off from admission shed")
                        return None
                from ..bthread import scheduler as _sched
                _sched.note_worker_blocked()
                try:
                    _time.sleep(delay_s)
                finally:
                    _sched.note_worker_unblocked()
                cntl.error_code_ = 0
                cntl.error_text_ = ""
                cntl.retry_after_ms = 0
                cntl.retried_count += 1
                if orig_tms and orig_tms > 0:
                    # the reissue gets only what's LEFT of the budget
                    left_ms = int((orig_tms / 1000.0
                                   - (_time.monotonic() - t0)) * 1000)
                    if left_ms <= 0:
                        cntl.set_failed(errors.ERPCTIMEDOUT,
                                        f"reached timeout={orig_tms}ms")
                        return None
                    cntl.timeout_ms = left_ms
                result = self._native_ici_call(nch, method_full_name,
                                               cntl, request,
                                               response_cls)
        finally:
            cntl.timeout_ms = orig_tms
        return result

    def _native_ici_fallback(self, cntl: Controller) -> bool:
        """After a fast-path failure, decide whether to re-route the call
        through the Python plane (once per call).  Two cases:
        * EFAILEDSOCKET — OUR cached conn died (server restarted): drop
          the cache; the Python plane reconnects per call.
        * EOVERCROWDED oversize fast-fail — the frame can never fit the
          native window; the Python plane drains it chunkwise."""
        code = cntl.error_code_
        if code == errors.EFAILEDSOCKET:
            drop_cache = True
        elif code == errors.EOVERCROWDED and \
                cntl.error_text_.startswith("frame larger"):
            drop_cache = False
        else:
            return False
        if getattr(cntl, "_ici_rerouted", False):
            return False               # one re-route per call: no flapping
        cntl._ici_rerouted = True
        if drop_cache:
            with self._native_ici_lock:
                stale, self._native_ici = self._native_ici, None
            if stale is not None:
                stale.close()
        # reset the controller so the fallback attempt starts clean
        cntl.error_code_ = 0
        cntl.error_text_ = ""
        return True

    def _native_ici_binding(self, cntl: Controller):
        """The native in-process ici connection, or None (→ Python plane:
        other-controller targets, streaming calls, auth, non-tpu_std)."""
        ep = self._endpoint
        if (ep is None or getattr(ep, "scheme", None) != "ici"
                or self.options.protocol != "tpu_std"
                or self.options.auth is not None
                or getattr(cntl, "stream_creator", None) is not None):
            return None
        cached = getattr(self, "_native_ici", None)
        if cached is not None:
            return cached
        try:
            from ..ici import native_plane
            if not (native_plane.available()
                    and native_plane.has_listener(ep.device_id)):
                return None
            with self._native_ici_lock:
                if getattr(self, "_native_ici", None) is None:
                    self._native_ici = native_plane.ChannelBinding(
                        ep.device_id,
                        local_dev=self.options.ici_local_device)
                return self._native_ici
        except Exception:
            return None

    # IssueRPC: runs once per try -----------------------------------------
    def _issue_rpc(self, cntl: Controller) -> None:
        sock = self._select_socket(cntl)
        cntl.remote_side = sock.remote_side
        cntl._pack_socket = sock       # connection-stateful protocols (h2)
        cid = cntl.current_cid()
        packet = self._protocol.pack_request(
            cntl._request_buf, cid, cntl, cntl._method_full_name)
        if cntl.span is not None:
            cntl.span.annotate("issue try=%d to %s" % (cntl.current_try,
                                                       sock.remote_side))
        if self._protocol.pipelined:
            maker = getattr(self._protocol, "make_pipeline_ctx", None)
            ctx = maker(cid, cntl) if maker is not None else cid
            cntl._pipeline_ctx = ctx
            sock.push_pipelined_context(ctx)
        # publish the client span for the write path: relocation / bulk
        # / device-plane events raised while THIS thread encodes the
        # frame annotate the CLIENT span — previously only the
        # bthread-local server span was consulted, so caller-side
        # relocation annotations were silently lost.  SAVE/RESTORE, not
        # clear: a usercode_inline handler dispatched inside this very
        # write can issue its own call, and clearing would strip the
        # OUTER window for the rest of the outer frame's encode.
        from ..bthread import scheduler as _sched
        from .span import set_client_span_local
        # `published` is decided BEFORE the write: an inline-completed
        # call (usercode_inline handler + response inside this very
        # sock.write) runs _end_rpc, which clears cntl.span — re-reading
        # it in the finally would skip the restore and leak the finished
        # span into the thread-local forever
        published = cntl.span is not None
        prev_span = None
        if published:
            prev_span = _sched.local_get("rpcz_client_span")
            set_client_span_local(cntl.span)
        try:
            rc = sock.write(packet, notify_cid=cid)
        finally:
            if published:
                set_client_span_local(prev_span)
        if rc != 0:
            raise ConnectionError(f"write failed: {rc}")
        cntl._last_socket = sock

    def _select_socket(self, cntl: Controller):
        ctype = self.options.connection_type
        # adaptive connection type (reference adaptive_connection_type.h):
        # when unset, protocols without an on-wire correlation id can't
        # share a single connection across concurrent calls → pooled
        from .protocol import CONNECTION_TYPE_SINGLE
        if ctype == "" and not (self._protocol.supported_connection_type
                                & CONNECTION_TYPE_SINGLE):
            ctype = "pooled"
        smap = SocketMap.instance()
        # reference semantics: < 0 waits indefinitely; 0 takes the
        # default (1s); > 0 is the timeout
        cto_ms = self.options.connect_timeout_ms
        cto = None if cto_ms < 0 else (cto_ms or 1000) / 1000.0
        if self._lb is not None:
            ep = self._lb.select_server(cntl)
            if ep is None:
                raise ConnectionError("no available server")
        else:
            ep = self._endpoint
            # circuit breaker gating for single-endpoint channels: while
            # the endpoint is isolated (tripped by consecutive failures),
            # fail fast instead of stampeding reconnects at a recovering
            # peer — the health checker alone probes it, and its revival
            # (mark_recovered) lifts the isolation (cluster_recover
            # ramp-up discipline applied to one endpoint)
            from .circuit_breaker import BreakerRegistry
            if BreakerRegistry.instance().breaker(ep).is_isolated():
                raise ConnectionError(
                    f"{ep} isolated by circuit breaker")
        cntl._selected_endpoint = ep
        group = self._channel_signature()
        ssl_ctx = self.options.ssl_context
        if ctype == "pooled":
            sock = smap.get_pooled_socket(ep, self.messenger, group=group,
                                          ssl_context=ssl_ctx,
                                          connect_timeout=cto)
            cntl._pooled_from = ep
        elif ctype == "short":
            sock = smap.get_short_socket(ep, self.messenger,
                                         ssl_context=ssl_ctx,
                                         connect_timeout=cto)
            cntl._short_socket = sock
        else:
            sock = smap.get_socket(ep, self.messenger,
                                   ssl_context=ssl_ctx, group=group,
                                   connect_timeout=cto)
        return sock

    def close(self) -> None:
        """Tear down this channel's connections: every socket the map
        holds for its endpoint is failed with ECLOSE (a deliberate
        local close — no health-check revival) and the native ici
        binding is released.  Idempotent; a later call on the channel
        simply reconnects.  Without this, a dropped client channel
        leaves its connection pair live in the socket pool until
        process exit (the resource-census leak class)."""
        if self._protocol is None:
            return          # init() never completed: nothing to close
        with self._native_ici_lock:
            nb, self._native_ici = getattr(self, "_native_ici", None), None
        if nb is not None:
            try:
                nb.close()
            except Exception:
                pass
        sig = self._channel_signature()
        smap = SocketMap.instance()
        if self._endpoint is not None:
            smap.close_endpoint(self._endpoint, sig)
        lb = self._lb
        if lb is not None:
            # load-balanced channel: detach from the (shared) naming
            # watcher and close every member's connections under this
            # channel's signature — a single-endpoint-only close would
            # silently leak the whole pool (review finding)
            ns = self._ns_thread
            if ns is not None:
                try:
                    ns.remove_watcher(getattr(self, "_ns_watcher", lb))
                except Exception:
                    pass
            dbd = getattr(lb, "_dbd", None)
            if dbd is not None:
                with dbd.read() as lst:
                    eps = [e.endpoint for e in lst]
                for ep in eps:
                    smap.close_endpoint(ep, sig)

    def _channel_signature(self) -> tuple:
        """Connection-compatibility key (reference channel.cpp
        ComputeChannelSignature): channels may share a connection only
        when the peer would parse it identically — protocol, TLS, and
        auth identity all partition the space.  The auth object itself is
        part of the key (the map then pins it, so identity can never be
        recycled while its connections live)."""
        return (self._protocol.name,
                self.options.ssl_context is not None,
                self.options.auth)

    def _on_call_end(self, cntl: Controller) -> None:
        # pooled sockets go back to the pool; short ones close
        sock = getattr(cntl, "_last_socket", None)
        ep = getattr(cntl, "_pooled_from", None)
        own_ctx = getattr(cntl, "_pipeline_ctx", None)
        exclusive = ep is not None or \
            getattr(cntl, "_short_socket", None) is not None
        if cntl.failed() and sock is not None and own_ctx is not None \
                and exclusive:
            # THIS call's context is still queued on an exclusive
            # (pooled/short) connection: the response never arrived, and
            # reusing the connection would mis-correlate the next call's
            # response (the reference closes cid-less connections on
            # error).  Shared single connections are left alone — their
            # other calls' contexts are legitimately outstanding and a
            # late response pops the stale context harmlessly.
            with sock._pipeline_lock:
                dangling = own_ctx in sock.pipelined_contexts
            if dangling:
                sock.set_failed(errors.ECLOSE,
                                "own pipelined context still outstanding")
        if ep is not None and sock is not None:
            SocketMap.instance().return_pooled_socket(
                ep, sock, group=self._channel_signature())
        short = getattr(cntl, "_short_socket", None)
        if short is not None:
            short.set_failed(errors.ECLOSE, "short connection done")
        sel = getattr(cntl, "_selected_endpoint", None)
        # an admission shed (retryable ELIMIT + retry_after_ms) is an
        # OVERLOADED-BUT-HEALTHY endpoint saying "not now" — it must not
        # count as an endpoint failure for the circuit breaker, or a 10x
        # overload isolates the very server still serving critical-band
        # traffic (the client-side twin of the limiter-floor poisoning
        # fixed in MethodStatus).  LB feedback still sees the error:
        # steering weight away from an overloaded member is correct.
        breaker_code = 0 if (cntl.error_code_ == errors.ELIMIT
                             and cntl.retry_after_ms > 0) \
            else cntl.error_code_
        if self._lb is not None:
            if sel is not None:
                self._lb.feedback(sel, cntl.error_code_, cntl.latency_us)
                # circuit breaker + health-check revival (SURVEY.md §5.3)
                from .circuit_breaker import BreakerRegistry
                breaker = BreakerRegistry.instance().breaker(sel)
                if not breaker.on_call_end(breaker_code):
                    from .health_check import start_health_check
                    lb = self._lb
                    lb.exclude(sel, breaker.isolated_until())
                    # revive_key=the LB: repeated trips re-register the
                    # same (replaced) callback instead of accumulating
                    # one per trip, while distinct LBs watching the same
                    # endpoint each keep theirs
                    start_health_check(
                        sel, on_revived=lambda ep: lb.exclude(ep, 0.0),
                        revive_key=id(lb))
        elif sel is not None:
            # single-endpoint channels feed the same breaker: repeated
            # failures trip isolation (gating reconnect stampedes in
            # _select_socket) and hand the endpoint to the health
            # checker, whose successful probe resets the breaker
            from .circuit_breaker import BreakerRegistry
            if not BreakerRegistry.instance().breaker(sel).on_call_end(
                    breaker_code):
                from .health_check import start_health_check
                start_health_check(sel)


class _FilteredWatcher:
    """Per-channel membership filter (reference naming_service_filter.h)."""

    def __init__(self, lb, filter_fn):
        self._lb = lb
        self._filter = filter_fn

    def reset_servers(self, entries):
        self._lb.reset_servers([e for e in entries if self._filter(e)])
