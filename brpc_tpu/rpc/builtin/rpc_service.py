"""Builtin admin RPC services — the pages as REAL RPC methods.

The builtin pages have always been reachable over HTTP on any transport;
this module makes the services.py docstring literally true: every page is
ALSO an RPC method, dogfooded over the fabric itself.  Two services are
mounted on every server with builtin services enabled:

  * ``brpc_tpu.Trace`` — the pod-scope rpcz query surface:
    ``FindTrace``/``ListRecent`` answer from the LOCAL SpanDB (rpc/span.py)
    with the responder's process id and wall clock attached, so a peer can
    stitch the spans into its own timeline (builtin/pod_scope.py).
  * ``brpc_tpu.Builtin`` — ``Call(page, query)`` dispatches any builtin
    page through the server's BuiltinDispatcher; the pod-scope ``/vars``
    and ``/brpc_metrics`` aggregation pulls every member's variables
    through it.

Messages are :class:`JsonMsg` — a self-describing JSON-bytes message that
speaks the protobuf surface the protocols require (SerializeToString /
ParseFromString) without a compiled schema, so the services ride tpu_std
over mem://, tcp://, and ici:// (the fabric) unchanged.

Admin-surface discipline: when ``ServerOptions.internal_port`` moved the
admin pages off the public port, ``Builtin.Call`` refuses on the public
RPC surface too (the same reason /flags must not leak onto the VIP).
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict

from ..service import Service, method
from .. import errors


class JsonMsg:
    """A JSON-carried message with the protobuf wire surface.  Fields
    live in ``.fields``; construct with keyword args."""

    def __init__(self, **fields: Any):
        self.fields: Dict[str, Any] = dict(fields)

    def SerializeToString(self) -> bytes:
        return json.dumps(self.fields).encode()

    def ParseFromString(self, data: bytes) -> None:
        self.fields = json.loads(data.decode()) if data else {}

    def get(self, key: str, default: Any = None) -> Any:
        return self.fields.get(key, default)

    def __getitem__(self, key: str) -> Any:
        return self.fields[key]

    def __repr__(self) -> str:
        return f"JsonMsg({self.fields!r})"


def local_pid() -> int:
    """This process's pod/fabric process id; -1 single-process."""
    try:
        from ...ici.fabric import FabricNode
        node = FabricNode.instance()
        return node.process_id if node is not None else -1
    except Exception:
        return -1


def _refuse_off_internal_port(cntl) -> bool:
    """When ServerOptions.internal_port moved the admin pages off the
    public port, the admin RPC surface must refuse there too — the
    SpanDB (method names, endpoints, timelines) is exactly the data the
    option exists to keep off the VIP.  True = refused (cntl failed)."""
    server = cntl.server
    if server is not None and server.options.internal_port >= 0:
        cntl.set_failed(errors.EPERM, "admin services are only served "
                                      "on the internal port")
        return True
    return False


class TraceService(Service):
    """find_trace / list-recent over the local SpanDB — the RPC the
    pod-scope /rpcz stitcher fans out (builtin/rpcz_service.cpp's query
    surface, reachable over the fabric)."""

    SERVICE_NAME = "brpc_tpu.Trace"

    @method(JsonMsg, JsonMsg)
    def FindTrace(self, cntl, request, response, done):
        from ..span import find_trace
        if _refuse_off_internal_port(cntl):
            done()
            return
        try:
            tid = int(str(request.get("trace_id", "0")), 16)
        except ValueError:
            cntl.set_failed(errors.EREQUEST, "trace_id must be hex")
            done()
            return
        response.fields = {
            "pid": local_pid(),
            "wall_us": time.time_ns() // 1000,
            "spans": [s.describe() for s in find_trace(tid)],
        }
        done()

    @method(JsonMsg, JsonMsg)
    def ListRecent(self, cntl, request, response, done):
        from ..span import recent_spans
        if _refuse_off_internal_port(cntl):
            done()
            return
        limit = int(request.get("limit", 100))
        response.fields = {
            "pid": local_pid(),
            "wall_us": time.time_ns() // 1000,
            "spans": [s.describe() for s in recent_spans(limit)],
        }
        done()


class BuiltinRpcService(Service):
    """Any builtin page as an RPC: Call({page, query}) → {status,
    content_type, body, pid}.  The pod-scope /vars and /brpc_metrics
    aggregation rides this."""

    SERVICE_NAME = "brpc_tpu.Builtin"

    @method(JsonMsg, JsonMsg)
    def Call(self, cntl, request, response, done):
        server = cntl.server
        builtin = getattr(server, "_builtin", None) \
            if server is not None else None
        if builtin is None:
            cntl.set_failed(errors.ENOSERVICE, "no builtin dispatcher")
            done()
            return
        if _refuse_off_internal_port(cntl):
            done()
            return
        page = str(request.get("page", ""))
        query = request.get("query") or {}
        hit = builtin.dispatch(page, {str(k): str(v)
                                      for k, v in query.items()})
        if hit is None:
            response.fields = {"status": 404, "content_type": "text/plain",
                               "body": f"no builtin page {page!r}",
                               "pid": local_pid()}
            done()
            return
        status, (ctype, body) = (200, hit) if len(hit) == 2 \
            else (hit[0], hit[1:])
        response.fields = {"status": status, "content_type": ctype,
                           "body": body, "pid": local_pid()}
        done()
