"""Pod-scope observability: fan out builtin queries over pod membership.

A router→prefill→decode request crosses three processes; each one's
SpanDB, /vars, and /brpc_metrics see only their own slice.  This module
turns ANY pod member into a whole-pod query point:

  * ``rpcz_pod`` — ``/rpcz?trace_id=``: query every up member's
    ``brpc_tpu.Trace.FindTrace`` (dogfooded over the fabric: the channel
    to each member is an ordinary ``ici://`` channel through
    ``connect_any``), map every remote span's wall anchor onto the local
    clock with the fabric's per-pair offset estimate (ici/clock.py,
    ±RTT/2 bound), and merge the spans into ONE causally-ordered tree —
    parent links from span ids, sibling order from aligned timestamps.
  * ``vars_pod`` / ``metrics_pod`` — ``?scope=pod``: pull every member's
    exposed variables over ``brpc_tpu.Builtin.Call`` and emit them
    grouped per process (/vars) or as process-labelled Prometheus
    exposition (/brpc_metrics: ``name{process="2"} value``).

Members are addressed by their first serving, non-draining device; the
local member answers locally (no self-RPC).  A member that cannot be
reached contributes an error entry, never a hang — the fan-out uses
short per-member timeouts and no retries (an rpcz query must not retry
its way into a draining member)."""
from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Tuple

# one cached channel per member endpoint (the TraceService cache the
# fablint guarded-state contract below covers): fan-outs are repeated —
# dashboards poll — and a fresh fabric handshake per query would be the
# expensive path
_channels_lock = threading.Lock()
_channels: Dict[str, object] = {}

# fablint guarded-state contract
_GUARDED_BY_GLOBALS = {
    "_channels": "_channels_lock",
}

_FANOUT_TIMEOUT_MS = 4000


def _member_targets() -> Tuple[Optional[object], List[Tuple[int, Optional[str]]]]:
    """(pod, [(pid, endpoint-or-None)]) for every UP member; None
    endpoint = the local member (answered locally) or a member with no
    serving device (reported as unreachable)."""
    try:
        from ...ici.pod import Pod, UP
    except Exception:
        return None, []
    pod = Pod.current()
    if pod is None:
        return None, []
    out: List[Tuple[int, Optional[str]]] = []
    for pid, m in sorted(pod.members(refresh=True).items()):
        if m.state != UP:
            continue
        if pid == pod.pid:
            out.append((pid, None))
            continue
        dev = next((d for d in m.serving if d not in m.draining), None)
        out.append((pid, f"ici://{dev}" if dev is not None else None))
    return pod, out


def _channel_to(target: str):
    with _channels_lock:
        ch = _channels.get(target)
    if ch is not None:
        return ch
    from ..channel import Channel, ChannelOptions
    ch = Channel()
    ch.init(target, options=ChannelOptions(
        timeout_ms=_FANOUT_TIMEOUT_MS, max_retry=0))
    with _channels_lock:
        kept = _channels.setdefault(target, ch)
    if kept is not ch:
        try:
            ch.close()
        except Exception:
            pass
    return kept


def _evict_channel(target: str) -> None:
    with _channels_lock:
        ch = _channels.pop(target, None)
    if ch is not None:
        try:
            ch.close()
        except Exception:
            pass


def _prune_channels(valid: set) -> None:
    """Drop cached channels for endpoints no longer in the member table
    (departed/restarted members must not pin sockets forever)."""
    with _channels_lock:
        stale = [t for t in _channels if t not in valid]
    for t in stale:
        _evict_channel(t)


def _call_member(target: str, method: str, fields: dict) -> dict:
    from ..controller import Controller
    from .rpc_service import JsonMsg
    ch = _channel_to(target)
    cntl = Controller()
    resp = ch.call_method(method, cntl, JsonMsg(**fields), JsonMsg)
    if cntl.failed():
        # a dead member must not be re-dialed from the cache on every
        # dashboard poll: evict, so the next fan-out starts fresh
        _evict_channel(target)
        raise ConnectionError(
            f"{method} at {target}: {cntl.error_code_} {cntl.error_text_}")
    return resp.fields


def _fanout_members(jobs):
    """Run {pid: thunk} CONCURRENTLY (one thread per remote member) and
    return {pid: ("ok", result) | ("err", text)}.  Pod membership keeps
    a crashed member's record UP by design (liveness is the health
    checker's concern), so per-member timeouts must overlap — a serial
    fan-out would stall a trace query behind each dead member in turn."""
    results: Dict[int, tuple] = {}
    rlock = threading.Lock()

    def run(pid, thunk):
        try:
            r = ("ok", thunk())
        except Exception as e:
            r = ("err", f"{type(e).__name__}: {e}")
        with rlock:
            results[pid] = r

    threads = [threading.Thread(target=run, args=(pid, thunk),
                                name=f"pod_fanout:{pid}", daemon=True)
               for pid, thunk in jobs.items()]
    for t in threads:
        t.start()
    import time as _time
    end = _time.monotonic() + _FANOUT_TIMEOUT_MS / 1000.0 + 2.0
    for t in threads:
        t.join(max(0.0, end - _time.monotonic()))
    with rlock:
        for pid in jobs:
            results.setdefault(pid, ("err", "fan-out timed out"))
        return dict(results)


# ---- /rpcz?trace_id= pod stitching -------------------------------------

def rpcz_pod(server, q: dict):
    """The pod-scope /rpcz handler body: one trace stitched across every
    member, or every member's recent spans when no trace_id was given."""
    from ..span import rpcz_enabled
    from ...ici import clock as _clock
    pod, targets = _member_targets()
    if pod is None:
        return "application/json", json.dumps(
            {"error": "scope=pod requires a joined pod (ici/pod.py)"},
            indent=1)
    tid_q = q.get("trace_id")
    # the local member IS pod.pid (the key _member_targets used) —
    # re-deriving it through FabricNode would mislabel the local slice
    # if the node is mid-teardown while the pod singleton survives
    my_pid = pod.pid
    processes: Dict[str, dict] = {}
    spans: List[dict] = []
    _prune_channels({t for _, t in targets if t is not None})
    fields = ({"trace_id": tid_q} if tid_q
              else {"limit": int(q.get("limit", "100"))})
    method = ("brpc_tpu.Trace.FindTrace" if tid_q
              else "brpc_tpu.Trace.ListRecent")
    jobs = {}
    for pid, target in targets:
        if target is None and pid != my_pid:
            processes[str(pid)] = {"error": "no serving endpoint"}
            continue
        if pid == my_pid:
            continue                     # answered locally below
        jobs[pid] = (lambda t=target:
                     _call_member(t, method, fields)["spans"])
    results = _fanout_members(jobs)
    from ..span import find_trace, recent_spans
    if tid_q:
        local = [s.describe() for s in find_trace(int(tid_q, 16))]
    else:
        local = [s.describe()
                 for s in recent_spans(int(q.get("limit", "100")))]
    results[my_pid] = ("ok", local)
    for pid in sorted(results):
        status, got = results[pid]
        if status != "ok":
            processes[str(pid)] = {"error": got}
            continue
        # clock alignment: map the member's wall anchors onto OUR wall
        # axis; bound -1 = no fabric sample for that peer (raw wall
        # clocks, skew unbounded — the stitcher never hides that)
        for s in got:
            if pid == my_pid:
                aligned, bound = float(s["start_real_us"]), 0.0
            else:
                aligned, bound = _clock.to_local_wall_us(
                    pid, s["start_real_us"])
            s["process"] = pid
            s["aligned_start_us"] = int(aligned)
            s["clock_bound_us"] = bound
        processes[str(pid)] = {"spans": len(got)}
        spans.extend(got)
    out = {
        "enabled": rpcz_enabled(),
        "scope": "pod",
        "pod": pod.name,
        "queried_from": my_pid,
        "processes": processes,
        "clock": _clock.describe(),
        "span_count": len(spans),
    }
    if tid_q:
        out["trace_id"] = tid_q
        out["tree"] = stitch_tree(spans)
    else:
        out["spans"] = sorted(spans,
                              key=lambda s: s["aligned_start_us"])
    return "application/json", json.dumps(out, indent=1)


def stitch_tree(spans: List[dict]) -> List[dict]:
    """Merge span dicts (with aligned_start_us already set) into a
    causally-ordered forest: children under their parent span, siblings
    and roots ordered by aligned start.  Causality is explicit — parent
    links come from span ids (propagated in RpcMeta / kind-4
    descriptors), only SIBLING order relies on the clock alignment, and
    every node carries the bound that order is valid under."""
    by_id: Dict[str, dict] = {}
    for s in spans:
        node = dict(s)
        node["children"] = []
        by_id[node["span_id"]] = node
    roots: List[dict] = []
    for node in by_id.values():
        parent = by_id.get(node["parent"])
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    def order(nodes: List[dict]) -> List[dict]:
        nodes.sort(key=lambda n: n["aligned_start_us"])
        for n in nodes:
            order(n["children"])
        return nodes
    return order(roots)


# ---- /vars and /brpc_metrics pod aggregation ---------------------------

def _fanout_page(server, page: str, query: dict) -> Dict[int, dict]:
    """{pid: {"body": str} | {"error": str}} for one builtin page pulled
    from every up member over brpc_tpu.Builtin.Call; the LOCAL member
    answers through ``server``'s own dispatcher (the same one the RPC
    would hit), no self-RPC."""
    pod, targets = _member_targets()
    if pod is None:
        return {}
    my_pid = pod.pid
    out: Dict[int, dict] = {}
    _prune_channels({t for _, t in targets if t is not None})

    def remote(target):
        got = _call_member(target, "brpc_tpu.Builtin.Call",
                           {"page": page, "query": query})
        if got.get("status", 200) != 200:
            raise RuntimeError(
                f"status {got.get('status')}: {got.get('body')}")
        return got["body"]

    jobs = {}
    for pid, target in targets:
        if pid == my_pid:
            continue                     # answered locally below
        if target is None:
            out[pid] = {"error": "no serving endpoint"}
            continue
        jobs[pid] = (lambda t=target: remote(t))
    results = _fanout_members(jobs)
    try:
        if getattr(server, "_builtin", None) is None:
            raise RuntimeError("no local server with builtins")
        hit = server._builtin.dispatch(page, query)
        results[my_pid] = ("ok", hit[-1])
    except Exception as e:
        results[my_pid] = ("err", f"{type(e).__name__}: {e}")
    for pid, (status, body) in results.items():
        out[pid] = {"body": body} if status == "ok" else {"error": body}
    return out


def vars_pod(server, q: dict):
    query = {"filter": q["filter"]} if q.get("filter") else {}
    results = _fanout_page(server, "vars", query)
    if not results:
        return "text/plain", "scope=pod requires a joined pod\n"
    lines: List[str] = []
    for pid in sorted(results):
        r = results[pid]
        lines.append(f"== process {pid} ==")
        if "error" in r:
            lines.append(f"<unreachable: {r['error']}>")
        else:
            lines.append(r["body"].rstrip("\n"))
    return "text/plain", "\n".join(lines) + "\n"


def metrics_pod(server, q: dict):
    """Process-labelled Prometheus exposition: every member's gauges
    with a ``process`` label (the MultiDimension labelling convention),
    TYPE lines deduplicated across members."""
    results = _fanout_page(server, "brpc_metrics", {})
    if not results:
        return "text/plain; version=0.0.4", \
            "# scope=pod requires a joined pod\n"
    lines: List[str] = []
    typed = set()
    errors: List[str] = []
    for pid in sorted(results):
        r = results[pid]
        if "error" in r:
            errors.append(f"# process {pid} unreachable: {r['error']}")
            continue
        for line in r["body"].splitlines():
            if not line:
                continue
            if line.startswith("#"):
                if line not in typed:
                    typed.add(line)
                    lines.append(line)
                continue
            name, _, value = line.rpartition(" ")
            if not name:
                continue
            lines.append(f'{name}{{process="{pid}"}} {value}')
    return ("text/plain; version=0.0.4",
            "\n".join(errors + lines) + "\n")


def close_channels_for_test() -> None:
    """Drop the fan-out channel cache (resource-census hygiene)."""
    with _channels_lock:
        chans = list(_channels.values())
        _channels.clear()
    for ch in chans:
        try:
            ch.close()
        except Exception:
            pass
