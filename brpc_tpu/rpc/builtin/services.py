"""Builtin admin service set.

Reference: src/brpc/builtin/*.{h,cpp} (30+ services: /status /vars /flags
/connections /health /rpcz /protobufs /brpc_metrics …).  TPU-native twist:
every page is served both as an RPC method (BuiltinService.Call) reachable
over any transport — including ici:// so an admin can query a chip's runtime
through the mesh — and as HTTP via the admin protocol (http_admin.py).

Pages return JSON (machine-readable first; the reference's HTML pages were
for 2015 browsers — the /vars and /brpc_metrics text formats are kept
Prometheus-compatible).
"""
from __future__ import annotations

import json
import time
from typing import Callable, Dict, Optional

from ... import bvar
from ...butil import flags as _flags


class BuiltinDispatcher:
    """path → handler(server, query: dict) -> (content_type, body_str),
    or (http_status, content_type, body_str) for pages whose HTTP status
    must carry signal (/health while draining → 503: status-code-keyed
    checkers pull the endpoint too, not just body-readers)."""

    def __init__(self, server):
        self.server = server
        self.handlers: Dict[str, Callable] = {}
        self._register_defaults()

    def add(self, path: str, fn: Callable) -> None:
        self.handlers[path.strip("/")] = fn

    def dispatch(self, path: str, query: Optional[dict] = None):
        fn = self.handlers.get(path.strip("/"))
        if fn is None:
            return None
        try:
            return fn(self.server, query or {})
        except Exception as e:
            # a handler exception must become a response, never a hung
            # client (bad query args, unreadable paths, ...)
            return "text/plain", f"error: {type(e).__name__}: {e}\n"

    def paths(self):
        return sorted(self.handlers)

    # ---- default pages ------------------------------------------------
    def _register_defaults(self) -> None:
        self.add("health", _health)
        self.add("status", _status)
        self.add("vars", _vars)
        self.add("flags", _flags_page)
        self.add("connections", _connections)
        self.add("rpcz", _rpcz)
        self.add("brpc_metrics", _metrics)
        self.add("protobufs", _protobufs)
        self.add("sockets", _sockets)
        self.add("bthreads", _bthreads)
        self.add("ids", _ids)
        self.add("index", _index)
        self.add("version", _version)
        self.add("hotspots", _hotspots)
        self.add("contention", _contention)
        self.add("threads", _threads)
        self.add("list_services", _list_services)
        self.add("ici", _ici)
        self.add("vlog", _vlog)
        self.add("dir", _dir)
        self.add("pprof/cmdline", _pprof_cmdline)
        self.add("pprof/profile", _pprof_profile)
        self.add("pprof/symbol", _pprof_symbol)


def _health(server, q):
    # lame-duck: a draining server stops reporting healthy BEFORE its
    # hard stop, so HTTP health checkers and naming watchers pull the
    # endpoint while in-flight work is still completing.  503 + body:
    # checkers keyed on the status CODE (k8s readiness, LB HTTP checks)
    # must see the drain too, not only body-readers.
    if getattr(server, "is_draining", lambda: False)():
        return 503, "text/plain", "draining"
    return "text/plain", "OK"


def _lifecycle(server) -> str:
    if getattr(server, "is_draining", lambda: False)():
        return "draining"
    return "running" if server.is_running() else "stopped"


def _version(server, q):
    from ... import __version__
    return "text/plain", server.version or f"brpc_tpu/{__version__}"


def _status(server, q):
    bvar.expose_default_variables()
    out = {
        "server": str(server.listen_endpoint),
        "name": server.options.server_info_name or "",
        "state": _lifecycle(server),
        "inflight_requests": server.inflight_requests()
        if hasattr(server, "inflight_requests") else 0,
        "uptime_s": round(time.time() - _start_time, 1),
        "services": sorted(server.services()),
        "methods": [ms.describe() for ms in server.method_statuses()],
        "connections": len(server.connections()),
    }
    adm = getattr(server, "admission", None)
    if adm is not None:
        # the overload-survival block: queue depth, shed-by-reason per
        # (tenant, band), observed service rate, current retry hint
        out["admission"] = adm.describe()
    pool = getattr(server, "usercode_pool", None)
    if pool is not None and hasattr(pool, "describe"):
        # the usercode pool block (ROADMAP 4c): isolation capability
        # (probed once — mode/functional/scaling + the reason when a
        # host can't scale), worker counts, and the share-nothing
        # contract/death counters
        out["usercode_pool"] = pool.describe()
    serving = {}
    for name, svc in server.services().items():
        # the serving block (ROADMAP item 3): any hosted service
        # exposing describe_serving() — decode workers report step
        # rate / batch occupancy / paged-pool pages / evictions by
        # reason+tenant, routers report LALB divided weights + picks
        fn = getattr(svc, "describe_serving", None)
        if callable(fn):
            try:
                serving[name] = fn()
            except Exception:
                pass
    if serving:
        out["serving"] = serving
    return "application/json", json.dumps(out, indent=1)


def _vars(server, q):
    if q.get("scope") == "pod":
        # pod aggregation: every member's exposed variables over the
        # brpc_tpu.Builtin.Call RPC, grouped per process
        from .pod_scope import vars_pod
        return vars_pod(server, q)
    bvar.expose_default_variables()
    wildcard = q.get("filter", "")
    lines = [f"{name} : {value}" for name, value in bvar.dump_exposed(wildcard)]
    return "text/plain", "\n".join(lines) + "\n"


def _flags_page(server, q):
    setname = q.get("setvalue")
    if setname:
        try:
            _flags.set_flag(setname, q.get("to", ""))
            return "text/plain", f"set {setname} ok"
        except Exception as e:
            return "text/plain", f"error: {e}"
    lines = [f"{f.name}={f.get()}  (default={f.default})  {f.help}"
             for f in _flags.list_flags()]
    return "text/plain", "\n".join(lines) + "\n"


def _connections(server, q):
    rows = []
    for s in server.connections():
        rows.append({
            "remote": str(s.remote_side),
            "in_bytes": s.stat.in_size, "out_bytes": s.stat.out_size,
            "in_messages": s.stat.in_num_messages,
            "out_messages": s.stat.out_num_messages,
            "age_s": round(time.time() - s.create_time, 1),
        })
    return "application/json", json.dumps(rows, indent=1)


def _rpcz(server, q):
    from ..span import recent_spans, find_trace, rpcz_enabled
    tid = q.get("trace_id")
    scope = q.get("scope")
    if scope != "local" and (scope == "pod" or tid):
        # pod-scope stitching: a trace_id query on ANY member fans out
        # over pod membership and answers with the MERGED causally-
        # ordered tree — explicit ?scope=local keeps the single-process
        # view, and a process with no pod falls through to it
        try:
            from ...ici.pod import Pod
            joined = Pod.current() is not None
        except Exception:
            joined = False
        if joined or scope == "pod":
            from .pod_scope import rpcz_pod
            return rpcz_pod(server, q)
    if tid:
        spans = find_trace(int(tid, 16))
    else:
        spans = recent_spans(int(q.get("limit", "100")))
    return "application/json", json.dumps({
        "enabled": rpcz_enabled(),
        "spans": [s.describe() for s in spans],
    }, indent=1)


def _metrics(server, q):
    """Prometheus exposition (prometheus_metrics_service.cpp)."""
    if q.get("scope") == "pod":
        # process-labelled exposition pulled from every pod member
        from .pod_scope import metrics_pod
        return metrics_pod(server, q)
    bvar.expose_default_variables()
    lines = []
    for name, value in bvar.dump_exposed():
        try:
            float(value)
        except (TypeError, ValueError):
            continue
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")
    return "text/plain; version=0.0.4", "\n".join(lines) + "\n"


def _protobufs(server, q):
    out = {}
    for full_name, md in server._methods.items():
        out[full_name] = {
            "request": md.request_cls.DESCRIPTOR.full_name
            if hasattr(md.request_cls, "DESCRIPTOR") else str(md.request_cls),
            "response": md.response_cls.DESCRIPTOR.full_name
            if hasattr(md.response_cls, "DESCRIPTOR") else str(md.response_cls),
        }
    return "application/json", json.dumps(out, indent=1)


def _sockets(server, q):
    from ..socket import list_sockets
    return "text/plain", "\n".join(s.description() for s in list_sockets())


def _bthreads(server, q):
    from ...bthread.scheduler import TaskControl
    ctl = TaskControl.instance()
    return "application/json", json.dumps({
        "workers": ctl.worker_count(),
        "tasklets": ctl.tasklet_count,
        "queue_depths": [len(g.deque) for g in ctl.groups],
        "steals": [g.steal_count for g in ctl.groups],
    })


def _ids(server, q):
    from ...bthread.id import _pool
    return "text/plain", f"live correlation ids: {_pool.size()}"


def _hotspots(server, q):
    """CPU profile (the gperftools/pprof stand-in: hotspots_service.cpp)."""
    from ..profiler import profile_for
    seconds = min(float(q.get("seconds", "1")), 30.0)
    return "text/plain", profile_for(seconds, top=int(q.get("top", "40")))


def _contention(server, q):
    """Lock contention profile (bthread/mutex.cpp contention profiler)."""
    from ..profiler import (contention_profile, enable_contention_profiler,
                            _contention_enabled)
    if q.get("enable") == "1":
        enable_contention_profiler(True)
        return "text/plain", "contention profiler enabled"
    if q.get("enable") == "0":
        enable_contention_profiler(False)
        return "text/plain", "contention profiler disabled"
    rows = contention_profile()
    lines = [f"enabled: {_contention_enabled}",
             f"{'total_wait_s':>12}  {'samples':>8}  site"]
    for site, n, total in rows[:50]:
        lines.append(f"{total:12.4f}  {n:8d}  {site}")
    return "text/plain", "\n".join(lines) + "\n"


def _threads(server, q):
    """Stack dump of every live thread (builtin/threads_service.cpp does
    this for pthreads via SIGQUIT; here: sys._current_frames)."""
    import sys
    import threading as _threading
    import traceback
    names = {t.ident: t.name for t in _threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} (tid={tid}) ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return "text/plain", "\n".join(out) + "\n"


def _list_services(server, q):
    """Service/method registry (builtin/list_service.cpp ListService)."""
    out = {}
    for name, svc in server.services().items():
        out[name] = [
            {"method": m, "request": md.request_cls.__name__
             if md.request_cls else "",
             "response": md.response_cls.__name__
             if md.response_cls else ""}
            for m, md in svc.methods().items()]
    return "application/json", json.dumps(out, indent=1)


def _ici(server, q):
    """The ici:// fabric's data planes: transport byte totals and the
    device plane (compiled-program transfers — program cache, counters,
    and the recent posted→matched→complete timelines)."""
    out = {}
    try:
        from ...ici.transport import ici_transport_stats
        moved, device_moved = ici_transport_stats()
        out["transport"] = {"bytes_moved": moved,
                            "device_bytes_moved": device_moved}
    except Exception:
        out["transport"] = {}
    try:
        from ...ici.device_plane import DevicePlane
        plane = DevicePlane.instance()
        out["device_plane"] = plane.stats()
        out["device_plane_recent"] = plane.recent_transfers()
    except Exception:
        out["device_plane"] = {}
    try:
        # pod membership + the per-pair native planes (N-member fabric)
        from ...ici.pod import Pod
        pod = Pod.current()
        if pod is not None:
            out["pod"] = pod.describe()
        from ...ici.fabric import pair_plane_stats, FabricSocket
        pairs = pair_plane_stats()
        if pairs:
            out["pair_planes"] = {str(pid): st
                                  for pid, st in pairs.items()}
        from ..socket import list_sockets
        seqs = {}
        shms = {}
        for s in list_sockets():
            if isinstance(s, FabricSocket):
                d = s.describe_dplane_sequencer()
                if d is not None:
                    seqs[str(s.remote_side)] = d
                sh = s.describe_shm()
                if sh is not None:
                    shms[str(s.remote_side)] = sh
        if seqs:
            out["dplane_sequencers"] = seqs
        if shms:
            # per-pair shm ring tier: byte totals, epoch, live ring
            # occupancy and doorbell waits
            out["shm_planes"] = shms
    except Exception:
        pass
    try:
        # per-route byte-mover counters (ici/route.py): which plane
        # carried how many frames/bytes — shm / uds / tcp / xfer /
        # dplane / inline
        from ...ici.route import route_stats, collective_stats
        rs = route_stats()
        if rs:
            out["routes"] = rs
        cs = collective_stats()
        if cs:
            out["collective_route_events"] = cs
    except Exception:
        pass
    try:
        # unified plane health (ici/plane_health.py): per-socket
        # state/reason/down_epoch/reprobe_in for bulk/shm/device/xfer,
        # the collective plane's record, and the engine's event
        # counters (rpc_fabric_plane_<name>_{down,reprobe,revived,ramp})
        planes = {}
        from ...ici.fabric import FabricSocket as _FS
        from ..socket import list_sockets as _ls
        socks = {}
        for s in _ls():
            if isinstance(s, _FS):
                socks[str(s.remote_side)] = s.describe_planes()
        if socks:
            planes["sockets"] = socks
        from ...channels import collective_fanout as _cfp
        inst = _cfp.CollectiveFanoutPlane._instance
        if inst is not None:
            planes["collective"] = inst._health.snapshot()
        from ...ici.route import plane_stats
        ev = plane_stats()
        if ev:
            planes["events"] = ev
        if planes:
            out["planes"] = planes
    except Exception:
        pass
    try:
        # compiled fan-out plane: health, entry order cursor, compile
        # cache, registered device-handler methods
        from ...channels import collective_fanout as _cf
        if _cf.registry().method_names() \
                or _cf.CollectiveFanoutPlane._instance is not None:
            out["collective_fanout"] = _cf.describe()
    except Exception:
        pass
    try:
        # per-peer clock alignment (span stitching's offset source)
        from ...ici import clock as _clock
        peers = _clock.describe()
        if peers:
            out["clock_offsets"] = peers
    except Exception:
        pass
    return "application/json", json.dumps(out, indent=1)


def _vlog(server, q):
    """Verbose-logging control (builtin/vlog_service.cpp); maps to the
    logging module's min level here."""
    import logging as _pylog

    from ...butil import logging as log
    if "level" in q:
        level = _pylog.getLevelNamesMapping().get(q["level"].upper())
        if level is None:
            return "text/plain", f"unknown level {q['level']!r}\n"
        log.set_min_log_level(level)
        return "text/plain", f"min level set to {q['level']}\n"
    return "text/plain", (
        f"min level: {_pylog.getLevelName(log._logger.level)}\n")


def _dir(server, q):
    """Filesystem browser (builtin/dir_service.cpp), restricted to the
    server's working directory subtree."""
    import os
    root = os.path.realpath(os.getcwd())
    rel = q.get("path", ".")
    path = os.path.realpath(os.path.join(root, rel))
    # commonpath, not startswith: /data/app must not admit /data/app-x
    if os.path.commonpath([root, path]) != root:
        return "text/plain", "path escapes working directory\n"
    try:
        if os.path.isdir(path):
            entries = sorted(os.listdir(path))
            return "application/json", json.dumps(
                {"dir": os.path.relpath(path, root), "entries": entries})
        with open(path, "rb") as f:
            data = f.read(1 << 20)
        return "text/plain", data.decode("utf-8", "replace")
    except OSError as e:
        return "text/plain", f"cannot read: {e}\n"


def _pprof_cmdline(server, q):
    """pprof remote protocol: the profiled binary's command line
    (builtin/pprof_service.cpp)."""
    import sys
    return "text/plain", "\x00".join([sys.executable] + sys.argv)


def _pprof_profile(server, q):
    """pprof remote protocol: CPU profile for ?seconds=N — same engine as
    /hotspots (pprof_service.cpp shares ProfilerStart with hotspots)."""
    return _hotspots(server, {"seconds": q.get("seconds", "2"),
                              "top": q.get("top", "60")})


def _pprof_symbol(server, q):
    """pprof symbol endpoint: Python frames are already symbolic; report
    the symbol count convention (pprof probes with a GET first)."""
    return "text/plain", "num_symbols: 1\n"


def _index(server, q):
    return "application/json", json.dumps({
        "paths": server._builtin.paths() if hasattr(server, "_builtin") else [],
    })


_start_time = time.time()


def register_builtin_services(server) -> None:
    server._builtin = BuiltinDispatcher(server)
    # the pages as REAL RPC services too (rpc_service.py): the pod-scope
    # fan-outs query peers through these over the fabric itself
    from .rpc_service import BuiltinRpcService, TraceService
    if "brpc_tpu.Trace" not in server.services():
        server.add_service(TraceService())
    if "brpc_tpu.Builtin" not in server.services():
        server.add_service(BuiltinRpcService())
