"""Builtin admin services (reference: src/brpc/builtin/, SURVEY.md §2.4)."""
from .services import register_builtin_services, BuiltinDispatcher
