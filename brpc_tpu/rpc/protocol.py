"""Pluggable wire-protocol contract + registry.

Reference: src/brpc/protocol.{h,cpp} (struct Protocol at protocol.h:77-196,
RegisterProtocol at :186).  A Protocol supplies parse (message cutting),
request/response serialization+packing, and server/client process callbacks.
InputMessenger tries registered protocols in order and remembers the first
that succeeds for a socket (protocol detection).
"""
from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..butil.iobuf import IOBuf


class ParseResultType(enum.Enum):
    OK = 0
    NOT_ENOUGH_DATA = 1     # keep buffering
    TRY_OTHERS = 2          # not this protocol
    ERROR = 3               # corrupt stream: kill the connection


@dataclass
class ParseResult:
    type: ParseResultType
    message: Any = None     # protocol-specific InputMessage when OK
    error: str = ""

    @staticmethod
    def ok(message: Any) -> "ParseResult":
        return ParseResult(ParseResultType.OK, message)

    @staticmethod
    def not_enough_data() -> "ParseResult":
        return ParseResult(ParseResultType.NOT_ENOUGH_DATA)

    @staticmethod
    def try_others() -> "ParseResult":
        return ParseResult(ParseResultType.TRY_OTHERS)

    @staticmethod
    def parse_error(msg: str = "") -> "ParseResult":
        return ParseResult(ParseResultType.ERROR, error=msg)


# Connection-type support bitmask (adaptive_connection_type.h)
CONNECTION_TYPE_SINGLE = 1
CONNECTION_TYPE_POOLED = 2
CONNECTION_TYPE_SHORT = 4
CONNECTION_TYPE_ALL = 7


@dataclass
class Protocol:
    name: str
    # parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult
    parse: Callable[[IOBuf, Any, bool, Any], ParseResult]
    # server side: process_request(msg, socket, server) — runs in a tasklet
    process_request: Optional[Callable[..., None]] = None
    # client side: process_response(msg, socket) — runs in a tasklet
    process_response: Optional[Callable[..., None]] = None
    # serialize_request(request_obj, controller) -> IOBuf (payload only)
    serialize_request: Optional[Callable[..., IOBuf]] = None
    # pack_request(payload: IOBuf, cid, controller) -> IOBuf (wire packet)
    pack_request: Optional[Callable[..., IOBuf]] = None
    # verify(msg) -> bool: authentication hook on first message
    verify: Optional[Callable[[Any], bool]] = None
    supported_connection_type: int = CONNECTION_TYPE_ALL
    support_client: bool = True
    support_server: bool = True
    # responses correlate by arrival order on the connection instead of an
    # embedded correlation id (HTTP/1.1, redis, memcache pipelining)
    pipelined: bool = False
    # optional: build the per-call pipeline context (default: the raw cid)
    make_pipeline_ctx: Optional[Callable[[int, Any], Any]] = None
    # optional: consume order-sensitive messages in the reader, in cut order
    # (stream frames: cheap enqueue/credit ops).  Returns True if consumed.
    process_inline: Optional[Callable[[Any, Any], bool]] = None


_protocols: List[Protocol] = []
_by_name: Dict[str, Protocol] = {}
_lock = threading.Lock()


def register_protocol(proto: Protocol) -> None:
    with _lock:
        if proto.name in _by_name:
            raise ValueError(f"protocol {proto.name!r} already registered")
        _protocols.append(proto)
        _by_name[proto.name] = proto


def list_protocols() -> List[Protocol]:
    with _lock:
        return list(_protocols)


def find_protocol(name: str) -> Optional[Protocol]:
    with _lock:
        return _by_name.get(name)
