"""JSON ↔ protobuf transcoding (reference: src/json2pb/ json_to_pb.h,
pb_to_json.h).  Drives HTTP/JSON access to pb services.  Built on
google.protobuf.json_format with the reference's option surface
(bytes_to_base64, enum_as_number, jsonify_empty_array &c. map onto
json_format's flags)."""
from __future__ import annotations

from typing import Any, Optional, Tuple, Type

from google.protobuf import json_format


class Pb2JsonOptions:
    def __init__(self, bytes_to_base64: bool = True,
                 jsonify_empty_array: bool = True,
                 always_print_primitive_fields: bool = False,
                 enum_option_as_number: bool = False):
        self.bytes_to_base64 = bytes_to_base64
        self.jsonify_empty_array = jsonify_empty_array
        self.always_print_primitive_fields = always_print_primitive_fields
        self.enum_option_as_number = enum_option_as_number


def pb_to_json(message: Any,
               options: Optional[Pb2JsonOptions] = None) -> Tuple[bool, str]:
    options = options or Pb2JsonOptions()
    try:
        out = json_format.MessageToJson(
            message,
            preserving_proto_field_name=True,
            always_print_fields_with_no_presence=options.always_print_primitive_fields,
            use_integers_for_enums=options.enum_option_as_number,
            indent=None)
        return True, out
    except Exception as e:
        return False, str(e)


def json_to_pb(json_str: str, message_cls: Type) -> Tuple[bool, Any, str]:
    """Returns (ok, message, error)."""
    msg = message_cls()
    try:
        json_format.Parse(json_str, msg, ignore_unknown_fields=True)
        return True, msg, ""
    except Exception as e:
        return False, None, str(e)


def pb_to_dict(message: Any) -> dict:
    return json_format.MessageToDict(message, preserving_proto_field_name=True)


def dict_to_pb(d: dict, message_cls: Type) -> Any:
    msg = message_cls()
    json_format.ParseDict(d, msg, ignore_unknown_fields=True)
    return msg
