"""Codec side-libraries (reference: src/json2pb/ + src/mcpack2pb/, SURVEY.md §2.7)."""
from . import json2pb
from . import mcpack
