"""mcpack v2 binary codec + protobuf bridge (the mcpack2pb equivalent).

Reference behavior: src/mcpack2pb/ — field heads (field_type.h:30-76,
parser.cpp:25-80): FieldFixedHead(u8 type, u8 name_size) for primitives
whose size is the type's low nibble; FieldShortHead(+u8 value_size) for
strings ≤254 and binary ≤255 with FIELD_SHORT_MASK set on the type;
FieldLongHead(+u32le value_size) otherwise.  Names are NUL-terminated and
name_size counts the NUL (0 = unnamed, e.g. array items and the top-level
object).  OBJECT/ARRAY values start with ItemsHead(u32le item_count);
ISOARRAY values start with IsoItemsHead(u8 item type) and then raw
unheaded items.  Strings carry a trailing NUL in their value.

The reference generates per-message C++ codecs (generator.cpp); here the
bridge walks protobuf descriptors at runtime — same wire, no codegen.
Python values map: dict→OBJECT, list→ARRAY, str→STRING, bytes→BINARY,
bool→BOOL, int→smallest signed/unsigned fit, float→DOUBLE, None→NULL.
compack (the reference's FORMAT_COMPACK, selectable via
SerializationFormat) shares these field heads; its only wire difference
is that homogeneous primitive arrays are serialized as ISOARRAYs
(mcpack2pb/serializer.cpp:716-740) — pass compack=True to mcpack_encode/
pb_to_mcpack for that variant (used by the ubrpc_compack protocol).
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

FIELD_OBJECT = 0x10
FIELD_ARRAY = 0x20
FIELD_ISOARRAY = 0x30
FIELD_OBJECTISOARRAY = 0x40
FIELD_STRING = 0x50
FIELD_BINARY = 0x60
FIELD_INT8 = 0x11
FIELD_INT16 = 0x12
FIELD_INT32 = 0x14
FIELD_INT64 = 0x18
FIELD_UINT8 = 0x21
FIELD_UINT16 = 0x22
FIELD_UINT32 = 0x24
FIELD_UINT64 = 0x28
FIELD_BOOL = 0x31
FIELD_FLOAT = 0x44
FIELD_DOUBLE = 0x48
FIELD_NULL = 0x61

FIELD_SHORT_MASK = 0x80
FIELD_FIXED_MASK = 0x0F

_INT_PACK = {
    FIELD_INT8: "<b", FIELD_INT16: "<h", FIELD_INT32: "<i",
    FIELD_INT64: "<q", FIELD_UINT8: "<B", FIELD_UINT16: "<H",
    FIELD_UINT32: "<I", FIELD_UINT64: "<Q",
}


class McpackError(ValueError):
    pass


# ---- encoding ---------------------------------------------------------

def _name_bytes(name: str) -> bytes:
    if not name:
        return b""
    nb = name.encode() + b"\x00"
    if len(nb) > 255:
        raise McpackError(f"field name too long: {name[:32]}...")
    return nb


def _fixed(out: bytearray, ftype: int, name: str, value: bytes) -> None:
    nb = _name_bytes(name)
    out += struct.pack("<BB", ftype, len(nb))
    out += nb
    out += value


def _short_or_long(out: bytearray, ftype: int, name: str,
                   value: bytes) -> None:
    nb = _name_bytes(name)
    if len(value) <= 255:
        out += struct.pack("<BBB", ftype | FIELD_SHORT_MASK, len(nb),
                           len(value))
    else:
        out += struct.pack("<BBI", ftype, len(nb), len(value))
    out += nb
    out += value


def _pick_int_type(v: int) -> int:
    if v < 0:
        if v >= -(1 << 7):
            return FIELD_INT8
        if v >= -(1 << 15):
            return FIELD_INT16
        if v >= -(1 << 31):
            return FIELD_INT32
        if v >= -(1 << 63):
            return FIELD_INT64
        raise McpackError(f"int out of range: {v}")
    if v < (1 << 7):
        return FIELD_INT8
    if v < (1 << 15):
        return FIELD_INT16
    if v < (1 << 31):
        return FIELD_INT32
    if v < (1 << 63):
        return FIELD_INT64
    if v < (1 << 64):
        return FIELD_UINT64
    raise McpackError(f"int out of range: {v}")


def _iso_item_type(value: List[Any]) -> Tuple[int, str, int]:
    """Uniform primitive item (type, pack fmt, size) for a compack
    isoarray, or (0, "", 0) when the list is not isoarray-eligible."""
    if not value:
        return 0, "", 0
    if all(isinstance(v, bool) for v in value):
        return FIELD_BOOL, "", 1
    if all(isinstance(v, int) and not isinstance(v, bool) for v in value):
        lo, hi = _pick_int_type(min(value)), _pick_int_type(max(value))
        if FIELD_UINT64 in (lo, hi):
            if min(value) < 0:
                return 0, "", 0           # mixed sign beyond int64: bail
            t = FIELD_UINT64
        else:
            t = lo if (lo & FIELD_FIXED_MASK) >= (hi & FIELD_FIXED_MASK) \
                else hi
        return t, _INT_PACK[t], t & FIELD_FIXED_MASK
    if all(isinstance(v, float) for v in value):
        return FIELD_DOUBLE, "<d", 8
    return 0, "", 0


def _encode_field(out: bytearray, name: str, value: Any,
                  compack: bool = False) -> None:
    if value is None:
        _fixed(out, FIELD_NULL, name, b"\x00")
    elif isinstance(value, bool):
        _fixed(out, FIELD_BOOL, name, b"\x01" if value else b"\x00")
    elif isinstance(value, int):
        t = _pick_int_type(value)
        _fixed(out, t, name, struct.pack(_INT_PACK[t], value))
    elif isinstance(value, float):
        _fixed(out, FIELD_DOUBLE, name, struct.pack("<d", value))
    elif isinstance(value, str):
        _short_or_long(out, FIELD_STRING, name, value.encode() + b"\x00")
    elif isinstance(value, (bytes, bytearray, memoryview)):
        _short_or_long(out, FIELD_BINARY, name, bytes(value))
    elif isinstance(value, dict):
        _encode_group(out, FIELD_OBJECT, name,
                      [(k, v) for k, v in value.items()], compack)
    elif isinstance(value, (list, tuple)):
        # FORMAT_COMPACK (mcpack2pb/serializer.cpp:716-740): primitive
        # arrays carry one item-type byte + raw values, no per-item heads
        if compack:
            t, fmt, isize = _iso_item_type(list(value))
            if t:
                body = bytearray([t])
                for v in value:
                    body += (b"\x01" if v else b"\x00") if t == FIELD_BOOL \
                        else struct.pack(fmt, v)
                _short_or_long(out, FIELD_ISOARRAY, name, bytes(body))
                return
        _encode_group(out, FIELD_ARRAY, name, [("", v) for v in value],
                      compack)
    else:
        raise McpackError(f"cannot mcpack-encode {type(value).__name__}")


def _encode_group(out: bytearray, ftype: int, name: str,
                  items: List[Tuple[str, Any]],
                  compack: bool = False) -> None:
    body = bytearray(struct.pack("<I", len(items)))
    for n, v in items:
        _encode_field(body, n, v, compack)
    nb = _name_bytes(name)
    out += struct.pack("<BBI", ftype, len(nb), len(body))
    out += nb
    out += body


def mcpack_encode(obj: Dict[str, Any], compack: bool = False) -> bytes:
    """Serialize a dict as a top-level (unnamed) object.  With
    compack=True, emit the reference's FORMAT_COMPACK variant
    (mcpack2pb.h:41): identical field heads, but homogeneous primitive
    arrays become ISOARRAYs."""
    if not isinstance(obj, dict):
        raise McpackError("top-level mcpack value must be a dict")
    out = bytearray()
    _encode_group(out, FIELD_OBJECT, "", list(obj.items()), compack)
    return bytes(out)


# ---- decoding ---------------------------------------------------------

class _Reader:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise McpackError("truncated mcpack data")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def u8(self) -> int:
        return self.take(1)[0]


def _decode_field(r: _Reader) -> Tuple[str, Any]:
    ftype = r.u8()
    name_size = r.u8()
    if ftype & FIELD_SHORT_MASK:
        base = ftype & ~FIELD_SHORT_MASK
        value_size = r.u8()
    elif ftype & FIELD_FIXED_MASK:
        base = ftype
        value_size = ftype & FIELD_FIXED_MASK
    else:
        base = ftype
        value_size = struct.unpack("<I", r.take(4))[0]
    name = r.take(name_size)[:-1].decode() if name_size else ""
    if base == FIELD_NULL:
        r.take(1)
        return name, None
    if base == FIELD_BOOL:
        return name, r.take(1) != b"\x00"
    if base in _INT_PACK:
        return name, struct.unpack(_INT_PACK[base], r.take(value_size))[0]
    if base == FIELD_FLOAT:
        return name, struct.unpack("<f", r.take(4))[0]
    if base == FIELD_DOUBLE:
        return name, struct.unpack("<d", r.take(8))[0]
    if base == FIELD_STRING:
        raw = r.take(value_size)
        return name, raw[:-1].decode() if raw else ""
    if base == FIELD_BINARY:
        return name, r.take(value_size)
    if base in (FIELD_OBJECT, FIELD_ARRAY):
        end = r.pos + value_size
        count = struct.unpack("<I", r.take(4))[0]
        if base == FIELD_OBJECT:
            obj: Dict[str, Any] = {}
            for _ in range(count):
                k, v = _decode_field(r)
                obj[k] = v
            val: Any = obj
        else:
            val = [_decode_field(r)[1] for _ in range(count)]
        if r.pos != end:
            raise McpackError(f"group size mismatch: at {r.pos}, want {end}")
        return name, val
    if base == FIELD_ISOARRAY:
        end = r.pos + value_size
        item_type = r.u8()
        fmt = _INT_PACK.get(item_type)
        if item_type == FIELD_DOUBLE:
            fmt, isize = "<d", 8
        elif item_type == FIELD_FLOAT:
            fmt, isize = "<f", 4
        elif item_type == FIELD_BOOL:
            fmt, isize = None, 1
        elif fmt is not None:
            isize = item_type & FIELD_FIXED_MASK
        else:
            raise McpackError(f"bad isoarray item type {item_type:#x}")
        nbytes = end - r.pos
        if nbytes % isize:
            raise McpackError("isoarray size not a multiple of item size")
        items: List[Any] = []
        for _ in range(nbytes // isize):
            raw = r.take(isize)
            items.append(raw != b"\x00" if fmt is None
                         else struct.unpack(fmt, raw)[0])
        return name, items
    raise McpackError(f"unknown mcpack field type {ftype:#x}")


def mcpack_decode(data: bytes) -> Dict[str, Any]:
    """Parse a top-level mcpack_v2 object into a dict."""
    r = _Reader(data)
    name, value = _decode_field(r)
    if not isinstance(value, dict):
        raise McpackError("top-level mcpack value is not an object")
    return value


def mcpack_decode_prefix(data: bytes) -> Tuple[Dict[str, Any], int]:
    """Parse one top-level object, returning (object, bytes consumed)."""
    r = _Reader(data)
    _, value = _decode_field(r)
    if not isinstance(value, dict):
        raise McpackError("top-level mcpack value is not an object")
    return value, r.pos


# ---- protobuf bridge (mcpack2pb) --------------------------------------

def _is_repeated(fd) -> bool:
    rep = getattr(fd, "is_repeated", None)
    if isinstance(rep, bool):
        return rep
    from google.protobuf.descriptor import FieldDescriptor as FD
    return fd.label == FD.LABEL_REPEATED


def _is_map(fd) -> bool:
    mt = getattr(fd, "message_type", None)
    return mt is not None and mt.GetOptions().map_entry


def pb_to_dict(msg: Any) -> Dict[str, Any]:
    """Walk the descriptor: the mcpack field names are the pb field names
    (what the reference's generated code emits)."""
    from google.protobuf.descriptor import FieldDescriptor as FD
    out: Dict[str, Any] = {}
    for fd, value in msg.ListFields():
        if _is_map(fd):
            vfd = fd.message_type.fields_by_name["value"]
            if vfd.type == FD.TYPE_MESSAGE:
                out[fd.name] = {str(k): pb_to_dict(v)
                                for k, v in value.items()}
            else:
                out[fd.name] = {str(k): v for k, v in value.items()}
        elif _is_repeated(fd):
            if fd.type == FD.TYPE_MESSAGE:
                out[fd.name] = [pb_to_dict(m) for m in value]
            else:
                out[fd.name] = list(value)
        elif fd.type == FD.TYPE_MESSAGE:
            out[fd.name] = pb_to_dict(value)
        else:
            out[fd.name] = value
    return out


def dict_to_pb(d: Dict[str, Any], msg: Any) -> Any:
    from google.protobuf.descriptor import FieldDescriptor as FD
    for fd in msg.DESCRIPTOR.fields:
        if fd.name not in d:
            continue
        value = d[fd.name]
        if _is_map(fd):
            target = getattr(msg, fd.name)
            vfd = fd.message_type.fields_by_name["value"]
            kfd = fd.message_type.fields_by_name["key"]
            for k, v in value.items():
                key = int(k) if kfd.type != FD.TYPE_STRING and \
                    isinstance(k, str) else k
                if vfd.type == FD.TYPE_MESSAGE:
                    dict_to_pb(v, target[key])
                else:
                    target[key] = v
        elif _is_repeated(fd):
            target = getattr(msg, fd.name)
            for item in value:
                if fd.type == FD.TYPE_MESSAGE:
                    dict_to_pb(item, target.add())
                else:
                    target.append(item)
        elif fd.type == FD.TYPE_MESSAGE:
            dict_to_pb(value, getattr(msg, fd.name))
        elif fd.type == FD.TYPE_BYTES:
            setattr(msg, fd.name, bytes(value))
        else:
            setattr(msg, fd.name, value)
    return msg


def pb_to_mcpack(msg: Any, compack: bool = False) -> bytes:
    return mcpack_encode(pb_to_dict(msg), compack=compack)


def mcpack_to_pb(data: bytes, msg: Any) -> Any:
    return dict_to_pb(mcpack_decode(data), msg)
