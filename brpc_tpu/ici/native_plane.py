"""Native ici:// plane — Python control plane over native/rpc.cpp's ici
datapath.

This is the fusion VERDICT r2/r3 task #1 demanded: the full unary hot path
(window reservation → TRPC frame encode → queue hop → dispatch →
correlation wake) runs in C++; Python appears on the datapath ONLY for
device-ref relocation (``jax.device_put``, the HBM→HBM ICI transfer), and
only when a ref is not already resident on the target chip.  Reference
anchors: the wait-free write discipline src/brpc/socket.cpp:1584-1596 and
the RDMA endpoint's zero-copy post + completion custody
src/brpc/rdma/rdma_endpoint.cpp:771,926.

Three pieces:

* **device-ref registry** — keeps jax arrays alive while their keys are in
  native custody.  Custody rules (must mirror native/rpc.cpp exactly):
  a key given to native exits custody either INTO Python (``take`` at an
  upcall or response boundary) or via the release upcall on drop paths.
* **ServerBinding** — attaches an ``rpc.Server``'s method table to a
  native listener; per-request upcall parses + dispatches user code
  (inline or on a tasklet, mirroring InputMessenger's dispatch).
* **ChannelBinding** — the client side used by ``rpc.Channel`` when the
  target device has a native listener in this process.
"""
from __future__ import annotations

import ctypes
import itertools
import threading
import time as _time
from typing import Any, Dict, List, Optional, Tuple

from ..butil import debug_sync as _dbg
from ..butil import flags as _flags
from ..butil import logging as log
from ..butil import native
from ..butil.iobuf import IOBuf, DEVICE
from ..butil.native import IciCallOut, IciRespC, IciSegC, _ICI_BATCH_FN, \
    _ICI_RELEASE_FN, _ICI_RELOCATE_FN
from ..rpc import errors
from ..rpc import request_context as _reqctx

_U8P = ctypes.POINTER(ctypes.c_uint8)

# the fused paths read the request-context slot without the
# current()/scope() call frames — same thread-local the module owns
_reqctx_tls = _reqctx._tls

# call_fused returns this when the call must re-route to the Python
# plane (frame too large / hedging configured / dead-conn fallback):
# distinct from None, which is a legitimate failed-call result
FUSED_FALLTHROUGH = object()

# the raw C string_at (stable since 2.5): the public wrapper is a
# Python frame per read, and the fused paths read 2-3 borrowed buffers
# per RPC
_string_at = ctypes._string_at

_fused_ffi = None


def _fused_call_binding(att_custody: bool):
    """Fused-path FFI bindings for call3/call4 whose payload/att-host
    argtypes are ``c_char_p`` — bytes objects pass straight through
    (ABI-identical pointer) instead of paying two ``ctypes.cast``
    frames per call.  Bound on a SEPARATE CDLL handle so the legacy
    ``call`` keeps its POINTER(c_uint8) binding byte-for-byte."""
    global _fused_ffi
    if _fused_ffi is None:
        lib = native.load()
        lib2 = ctypes.CDLL(lib._name)
        segp = ctypes.POINTER(IciSegC)
        argt = [ctypes.c_uint64, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
                segp, ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(IciCallOut)]
        f3 = lib2.brpc_tpu_ici_call3
        f3.restype = ctypes.c_uint64
        f3.argtypes = argt
        f4 = lib2.brpc_tpu_ici_call4
        f4.restype = ctypes.c_uint64
        f4.argtypes = argt
        _fused_ffi = (f3, f4)
    return _fused_ffi[1] if att_custody else _fused_ffi[0]

# Batched one-struct upcall tuning (native/rpc.cpp enqueue_batch): the
# drainer takes up to max_batch requests per GIL crossing; an arrival
# whose queue head has aged past batch_age_us steals the queue and
# delivers concurrently, so p99 never pays more than the age bound for
# batching.  Applied to every new ServerBinding.
_flags.define_flag("ici_upcall_max_batch", 64,
                   "max Python-handler requests delivered per batched "
                   "upcall (one GIL crossing) on the native ici plane")
_flags.define_flag("ici_upcall_batch_age_us", 50,
                   "age bound (us) before a queued ici request is "
                   "stolen from a busy drainer and delivered "
                   "concurrently — bounds the p99 cost of batching")

# Native attachment custody (ISSUE 12): device-seg lists park in a
# NATIVE att table and move as one opaque handle — the handler tier
# receives a ready zero-copy IOBuf view (NativeAttachment) instead of
# walking seg descriptors through the registry twice per RPC.  Off =
# the PR-8 take-during-upcall walk, byte-for-byte (the A/B leg).
_flags.define_flag("ici_native_att_custody", True,
                   "resolve ici attachment seg tokens native-side: "
                   "handlers receive a lazily-materialized zero-copy "
                   "view backed by native custody instead of a "
                   "per-seg registry walk")

# Fused dispatch (ISSUE 13): the per-RPC interpreter-frame chain on the
# native-ici hot path collapses into single flat code objects —
# _process/_execute/done fuse into ServerBinding._process_fused +
# _FusedDone on the server, Channel.call_method's native preamble +
# screens + ChannelBinding.call fuse into ChannelBinding.call_fused on
# the client, with per-method dispatch resolved ONCE per (listener,
# method) instead of per call.  Off = the PR-12 frame chain
# byte-for-byte (the A/B leg).  Snapshot at bind/connect time, like
# ici_native_att_custody.
_flags.define_flag("ici_fused_dispatch", True,
                   "collapse the native-ici per-RPC dispatch chain "
                   "into fused code objects (server process/execute/"
                   "done and the client call path); off restores the "
                   "unfused PR-12 frame chain for A/B")

# hot-path module handles, resolved once at first call: the per-call
# `from x import y` dance measured ~1 us/call on the fast plane (the
# lazy-at-call-time form exists only to dodge import cycles at load)
_hot = None


def _hot_modules():
    global _hot
    if _hot is None:
        from ..bthread import scheduler
        from ..rpc import fault_injection
        from . import transport
        _hot = (fault_injection, scheduler, transport)
    return _hot


# tpu_std's stage-decomposition hooks (tpu_std_server_* recorders); the
# ici handler tier feeds the SAME recorders so the per-stage p50s
# decompose the deployed-common path (lazy: policy<->ici import cycle)
_stage_hot = None


def _stage_modules():
    global _stage_hot
    if _stage_hot is None:
        from ..policy.tpu_std import _record_stage, _stage_flag
        _stage_hot = (_stage_flag, _record_stage)
    return _stage_hot


_cntl_pool = None


def _controller_pool():
    global _cntl_pool
    if _cntl_pool is None:
        from ..rpc.controller import server_controller_pool
        _cntl_pool = server_controller_pool
    return _cntl_pool


# ---------------------------------------------------------------------
# device-ref registry
# ---------------------------------------------------------------------

class _DevRegistry:
    """key → jax.Array, alive while the key is in native custody.

    Lock-free by construction: keys come from itertools.count (atomic in
    CPython) and every table op is a single GIL-atomic dict operation —
    put/take pairs on the RPC hot path used to cost four lock
    acquisitions per attachment round trip.  A key is written exactly
    once and removed exactly once (the exactly-one-exit custody
    invariant), so there is no read-modify-write to race."""

    # fablint custody contract (ISSUE 20): every registered device ref
    # leaves through take (Python assumes custody) or release (drop);
    # keys parked in wire segments / IOBuf handles carry custody-moved
    # markers at the put site naming the structure that owes the exit.
    _CUSTODY = {"put": ("take", "release")}

    def __init__(self):
        self._m: Dict[int, Any] = {}
        self._next = itertools.count(1).__next__

    def put(self, arr) -> int:
        key = self._next()
        self._m[key] = arr
        return key

    def peek(self, key: int):
        return self._m.get(key)

    def take(self, key: int):
        """Remove and return — the Python side assumes custody."""
        return self._m.pop(key, None)

    def release(self, key: int) -> None:
        self._m.pop(key, None)

    def live(self) -> int:
        return len(self._m)


_registry = _DevRegistry()


def registry() -> _DevRegistry:
    return _registry


# ---------------------------------------------------------------------
# hooks (relocation = the only Python on the datapath)
# ---------------------------------------------------------------------

def _relocate(key: int, target_dev: int) -> int:
    """Move the array behind ``key`` to mesh device ``target_dev``; returns
    a NEW key for the moved array (native releases the old one) or the same
    key when already resident.  0 = failure (native fails the RPC).

    Payloads at/above ``ici_device_plane_threshold`` cross through the
    device plane's compiled transfer program (post_send + rendezvous —
    the no-host datapath); smaller or refused ones keep device_put."""
    try:
        import jax
        from .mesh import IciMesh
        arr = _registry.peek(key)
        if arr is None:
            return 0
        mesh = IciMesh.default()
        target = mesh.device(target_dev)
        if not hasattr(arr, "devices"):
            # host-delivered fabric bulk payload (a ctypes-backed numpy
            # view over the native receive buffer) being forwarded into
            # an in-process call: detach into an owned copy first —
            # device_put zero-copy ALIASES such views WITHOUT retaining
            # them, and the native pool recycles the buffer under the
            # alias (same discipline as transport.py _relocate)
            import numpy as np
            arr = np.array(arr, copy=True)
        else:
            try:
                if target in arr.devices():
                    return key                   # resident: pure ref pass
            except Exception:
                pass
            from . import device_plane as _dp
            nbytes = int(arr.shape[0]) if arr.ndim == 1 else 0
            if nbytes and _dp.eligible(nbytes):
                src_idx = _dp.mesh_index_of(arr, mesh)
                if src_idx >= 0 and src_idx != target_dev:
                    try:
                        t = _dp.plane().transfer_local(arr, src_idx,
                                                       target_dev)
                        return _registry.put(t.out)
                    except _dp.DevicePlaneError:
                        pass       # counted by the plane; device_put path
        moved = jax.device_put(arr, target)      # HBM→HBM over ICI
        return _registry.put(moved)
    except Exception as e:                       # never raise across ctypes
        log.error("ici relocate(key=%d, dev=%d) failed: %s", key,
                  target_dev, e)
        return 0


def _release(key: int) -> None:
    _registry.release(key)


_hooks_installed = False
_hooks_lock = threading.Lock()
_relocate_cb = None
_release_cb = None


def ensure_hooks() -> bool:
    """Install the relocate/release upcalls once per process."""
    global _hooks_installed, _relocate_cb, _release_cb, _att_fns
    lib = native.load()
    if lib is None:
        return False
    with _hooks_lock:
        if not _hooks_installed:
            _relocate_cb = _ICI_RELOCATE_FN(_relocate)
            _release_cb = _ICI_RELEASE_FN(_release)
            lib.brpc_tpu_ici_set_hooks(_relocate_cb, _release_cb)
            # att-custody handle ops, bound once (the view's custody
            # exits must not pay native.load()'s lock)
            _att_fns = (lib.brpc_tpu_ici_att_take,
                        lib.brpc_tpu_ici_att_dispose)
            _hooks_installed = True
    return True


def available() -> bool:
    return native.available()


def has_listener(device_id: int) -> bool:
    lib = native.load()
    return lib is not None and \
        lib.brpc_tpu_ici_has_listener(device_id) == 1


# Python-side view of live ServerBindings, for properties native cannot
# answer (dispatch mode).  device_id -> ServerBinding.
_server_bindings: Dict[int, "ServerBinding"] = {}
_server_bindings_lock = threading.Lock()


def listener_dispatch_inline(device_id: int,
                             method: Optional[str] = None) -> Optional[bool]:
    """True when the in-process listener at ``device_id`` answers
    ``method`` INLINE on the caller's thread — usercode_inline servers
    (every method), or the compiled echo tier (that method is served
    fully in C regardless of the server's dispatch mode).  False when
    the handler parks on a tasklet, None when unknown.  Fan-out issuers
    use this: against an inline answer a sub-call-per-tasklet buys no
    concurrency (the work runs in the caller's stack either way) and
    costs a scheduling hop."""
    with _server_bindings_lock:
        b = _server_bindings.get(device_id)
    if b is None:
        return None
    if method is not None and method in b._echo_methods:
        return True
    return bool(getattr(b._server.options, "usercode_inline", False))


# ---------------------------------------------------------------------
# IOBuf ⇄ (att_host, segs) marshalling
# ---------------------------------------------------------------------

def split_attachment(buf: IOBuf) -> Tuple[bytes, list]:
    """Decompose an attachment IOBuf into the host byte-stream plus the
    ordered segment descriptor list — PLAIN TUPLES (key, nbytes, dev,
    is_dev), not ctypes structs: a ctypes Structure construction per seg
    measured ~0.8 µs, and the FFI boundary fills its arrays from the
    tuples with plain field stores.  Device blocks are registered (native
    custody begins); host runs merge into one descriptor each."""
    if buf.backing_block_num() == 1:
        # the dominant fast-plane shape: one whole device block
        r = buf.backing_block(0)
        if (r.block.kind == DEVICE and not r.offset
                and r.length == r.block.size):
            arr = r.block.data
            return b"", [(_registry.put(arr), r.length,
                          _device_index(arr), 1)]
    host_parts: List[bytes] = []
    segs: list = []
    run = 0
    for i in range(buf.backing_block_num()):
        r = buf.backing_block(i)
        if r.block.kind == DEVICE:
            if run:
                segs.append((0, run, 0, 0))
                run = 0
            arr = r.block.data
            if r.offset or r.length != len(arr):
                arr = arr[r.offset:r.offset + r.length]
            dev = _device_index(arr)
            segs.append((_registry.put(arr), r.length, dev, 1))
        else:
            host_parts.append(bytes(r.block.host_view(r.offset, r.length)))
            run += r.length
    if run:
        segs.append((0, run, 0, 0))
    return b"".join(host_parts), segs


def fill_seg_array(segs) -> "ctypes.Array":
    """(IciSegC * n) array from split_attachment's tuple descriptors
    (tolerates IciSegC instances for callers that build their own)."""
    arr = (IciSegC * len(segs))()
    for j, sg in enumerate(segs):
        if type(sg) is tuple:
            e = arr[j]
            e.key, e.nbytes, e.dev, e.is_dev = sg
        else:
            arr[j] = sg
    return arr


def build_attachment_from_c(att_host: bytes, segs_p, nsegs: int) -> IOBuf:
    """build_attachment reading the ctypes seg array DIRECTLY — skips the
    per-seg IciSegC copy the list-based form needs (one ctypes Structure
    construction per seg measured ~0.8 µs on the handler tier).

    EXCEPTION-SAFE (ISSUE 12 satellite): the upcall contract says the
    walk TAKES every device key — native clears its seg list when the
    upcall returns, so a mid-walk failure used to strand every
    not-yet-walked key in the registry forever (already-taken keys ride
    the dropped buf; the REMAINING ones had no owner left).  On any
    failure the un-walked device keys are released before re-raising."""
    buf = IOBuf()
    off = 0
    take = _registry.take
    i = 0
    try:
        while i < nsegs:
            s = segs_p[i]
            n = s.nbytes
            if s.is_dev:
                arr = take(s.key)
                if arr is None:
                    raise KeyError(f"ici device ref {s.key} missing")
                buf.append_device_array_unchecked(arr, n)
            else:
                buf.append(att_host[off:off + n])
                off += n
            i += 1
    except BaseException:
        release = _registry.release
        for j in range(i + 1, nsegs):
            s = segs_p[j]
            if s.is_dev:
                release(s.key)
        raise
    return buf


# native att-custody handle ops, bound once at ensure_hooks (the hot
# path must not pay native.load()'s lock per call): (take, dispose)
_att_fns = None


class NativeAttachment(IOBuf):
    """Zero-copy attachment view backed by NATIVE custody (ISSUE 12).

    The device-seg list this buffer represents is PARKED in the native
    att table under ``_h``; the keys stay in the device-ref registry
    (arrays alive, custody native).  Construction costs one small
    object — no registry ops, no Block/BlockRef builds, no seg walk.
    The handle exits custody EXACTLY ONCE, by whichever happens first:

      * pass-through — ``cntl.response_attachment = view`` hands the
        handle back to native in the respond struct (the echo shape:
        zero Python walks end to end);
      * materialization — any structural touch (``backing_block_num``,
        ``to_bytes``, appending it into another IOBuf, ...) inflates
        real DEVICE blocks: the registry keys are taken into Python
        custody and the native entry is dropped without release;
      * dispose — Controller pool-recycle (server side), ``__del__``
        (client side / safety net): native releases every parked key.

    ``len()``/``size()``/``empty()`` answer from the descriptor total
    WITHOUT materializing — presence checks stay free.  Like IOBuf
    itself, instances are not thread-safe."""

    __slots__ = ("_h", "_total", "_seg_meta", "_mat")

    def __init__(self, handle: int, total: int, seg_meta: tuple):
        # deliberately NOT calling IOBuf.__init__: _refs/_size stay
        # unset until materialization — __getattr__ inflates on the
        # first structural touch
        self._h = handle
        self._total = total
        self._seg_meta = seg_meta      # ((key, nbytes, dev), ...)
        self._mat = False

    # ---- lazy inflation ----------------------------------------------
    def __getattr__(self, name):
        if name in ("_refs", "_size"):
            self._materialize()
            return object.__getattribute__(self, name)
        raise AttributeError(name)

    def _materialize(self) -> None:
        IOBuf.__init__(self)           # sets _refs/_size
        self._mat = True
        h = self._h
        if not h:
            return                     # surrendered/disposed: empty
        self._h = 0
        for arr, nbytes in self._take_parked(h):
            self.append_device_array_unchecked(arr, nbytes)

    def _take_parked(self, h: int) -> list:
        """Consume the parked native entry for ``h`` plus its registry
        keys, returning ``[(array, nbytes), ...]`` — the ONE custody
        walk behind both exits-into-Python (``_materialize`` and
        ``take_segments``).  On any failure every not-yet-taken key is
        released before the raise (the view can no longer exit, so a
        stranded key would pin its array forever); releasing keys a
        native dispose already dropped is a no-op, never a
        double-free."""
        fns = _att_fns
        metas = self._seg_meta
        if fns is None or fns[0](h) < 0:    # att_take consumes the entry
            release = _registry.release
            for key, _n, _d in metas:
                release(key)
            raise KeyError(f"ici native att handle {h} missing")
        take = _registry.take
        out = []
        for i, (key, nbytes, _dev) in enumerate(metas):
            arr = take(key)
            if arr is None:
                release = _registry.release
                for k2, _n2, _d2 in metas[i + 1:]:
                    release(k2)
                raise KeyError(f"ici device ref {key} missing")
            out.append((arr, nbytes))
        return out

    # ---- cheap overrides (no materialization) ------------------------
    def __len__(self) -> int:
        return self._total if not self._mat else self._size

    def size(self) -> int:
        return self.__len__()

    def empty(self) -> bool:
        return self.__len__() == 0

    def __repr__(self) -> str:
        if self._mat:
            return IOBuf.__repr__(self)
        return (f"NativeAttachment(size={self._total}, "
                f"handle={self._h:#x}, lazy)")

    @property
    def parked(self) -> bool:
        """True while the seg list is still in NATIVE custody (never
        materialized, handle not yet exited) — the predicate outside
        callers (the serving KV loader) route on instead of reaching
        into the view's slots."""
        return not self._mat and bool(self._h)

    # ---- custody exits -----------------------------------------------
    def take_segments(self) -> list:
        """Fourth custody exit (ISSUE 15): take the parked segs into
        Python as raw ``(array, nbytes)`` pairs WITHOUT building IOBuf
        blocks — the serving KV scatter-loader's surface (the bytes go
        straight into pool blocks, so Block/BlockRef construction would
        be pure overhead).  Consumes the handle and the registry keys
        (exactly-one-exit holds: afterwards the view reads as an EMPTY
        IOBuf and pool-recycle/GC disposes are no-ops).  On a custody
        bug mid-walk the remaining keys are released before the raise,
        same as materialization."""
        if self._mat or not self._h:
            raise ValueError(
                "take_segments: view already materialized or exited")
        IOBuf.__init__(self)           # _refs/_size: the view is now an
        self._mat = True               # inert empty buffer
        h = self._h
        self._h = 0
        return self._take_parked(h)

    def _surrender_native(self) -> int:
        """Hand the parked entry back to native (the response pass-
        through): returns the handle and forgets it — the respond
        struct now owns the exit.  0 when there is nothing to pass."""
        if self._mat:
            return 0
        h = self._h
        self._h = 0
        return h

    def _dispose_native(self) -> None:
        """Drop path (pool recycle / reject): native releases every
        parked key.  Idempotent — a surrendered or materialized view
        holds no handle."""
        h = self._h
        if h:
            self._h = 0
            fns = _att_fns
            if fns is not None:
                fns[1](h)

    def __del__(self):                 # noqa: D105 — safety net: a view
        try:                           # GC'd unexited must not strand
            self._dispose_native()     # keys in the registry forever
        except Exception:
            pass


class ResponseAttachment(NativeAttachment):
    """The server/client response-attachment default (installed as
    ``Controller.response_attachment``'s lazy factory once this module
    loads): a plain IOBuf until a WHOLE, untouched ``NativeAttachment``
    view is appended while this buffer is still empty — the PR-8 echo
    idiom ``cntl.response_attachment.append(cntl.request_attachment)``
    — which ADOPTS the parked handle instead of materializing it
    (ISSUE 13 satellite): the respond path then passes the handle back
    with zero Python seg walks, byte-identical to the assignment
    idiom.  Any structural touch after adoption inflates through the
    inherited lazy discipline; exactly-one-exit holds (pass-through at
    respond, or dispose at pool recycle / GC)."""

    __slots__ = ()

    def __init__(self):
        IOBuf.__init__(self)
        self._h = 0
        self._total = 0
        self._seg_meta = ()
        self._mat = True               # a real (empty) buffer until adopted

    def append(self, data) -> None:
        if (self._mat and isinstance(data, NativeAttachment)
                and not data._mat and data._h and not self._refs):
            # adopt: the handle moves here and THIS buffer becomes the
            # lazy view — the donor is left surrendered (same aliasing
            # the assignment idiom has).  The real refs/size slots are
            # deleted so the first structural touch re-inflates through
            # NativeAttachment.__getattr__.
            self._h = data._h
            data._h = 0
            self._total = data._total
            self._seg_meta = data._seg_meta
            self._mat = False
            del self._refs, self._size
            return
        IOBuf.append(self, data)


def _install_response_attachment_factory() -> None:
    """Swap Controller's lazy response-attachment factory to
    ResponseAttachment — process-wide, on every call plane (the wire
    and loopback planes see a plain IOBuf in all but the adoption
    shape, which only the native custody tier can produce)."""
    from ..rpc.controller import Controller
    vars(Controller)["response_attachment"].factory = ResponseAttachment


_install_response_attachment_factory()


def _seg_meta_from_req(r, nsegs: int):
    """((key, nbytes, dev), ...) + total bytes for a handle-carrying
    request struct: the dominant 1-seg shape reads the inline seg0
    mirror (plain struct fields); longer lists walk the parked segs."""
    if nsegs == 1:
        n = r.seg0_nbytes
        return ((r.seg0_key, n, r.seg0_dev),), n
    segs_p = r.segs
    total = 0
    meta = []
    for i in range(nsegs):
        s = segs_p[i]
        meta.append((s.key, s.nbytes, s.dev))
        total += s.nbytes
    return tuple(meta), total


def att_table_live() -> int:
    """Parked native att entries (census surface); 0 when the native
    core is unavailable."""
    lib = native.load()
    if lib is None or not hasattr(lib, "brpc_tpu_ici_att_count"):
        return 0
    return int(lib.brpc_tpu_ici_att_count())


# id(arr) -> (mesh generation, mesh index), evicted by a finalizer when
# the array dies (the id is unique until then).  A steady workload
# re-posts the same payload arrays, and arr.device + the mesh lookup
# measured ~2-3 us/call on the axon backend.  An array cannot change
# residence in place, but the MESH can be swapped (IciMesh.set_default)
# — entries are keyed on the mesh generation so a swap invalidates them
# instead of silently stamping a wrong logical id (review finding r5).
# idx == -1 ("not in the mesh") is never cached: it usually means the
# mesh isn't configured yet, and pinning it would force a relocate
# upcall on every later send of that array.
_devidx_cache: Dict[int, Tuple[int, int]] = {}


_IciMesh = None


def _mesh_cls():
    global _IciMesh
    if _IciMesh is None:
        from .mesh import IciMesh
        _IciMesh = IciMesh
    return _IciMesh


def _device_index(arr) -> int:
    """Logical mesh id of the array's residence, or -1 when the device is
    not in the mesh.  -1 never equals a target id, so native relocation
    always upcalls for such refs — the relocate hook then does the real
    residency check/device_put, preserving Python-plane semantics instead
    of silently skipping relocation (review finding: a 0 default would
    alias device 0)."""
    IciMesh = _IciMesh
    if IciMesh is None:
        IciMesh = _mesh_cls()
    gen = IciMesh.generation
    key = id(arr)
    hit = _devidx_cache.get(key)
    if hit is not None and hit[0] == gen:
        return hit[1]
    mesh = IciMesh.default()
    idx = -1
    try:
        idx = mesh.device_index(arr.device)      # single-device fast path
    except Exception:
        pass
    if idx < 0:
        try:
            for d in arr.devices():
                i = mesh.device_index(d)
                if i >= 0:
                    idx = i
                    break
        except Exception:
            pass
    if idx >= 0:
        try:
            import weakref
            if hit is None:
                weakref.finalize(arr, _devidx_cache.pop, key, None)
            _devidx_cache[key] = (gen, idx)
        except TypeError:
            pass                 # not weakref-able: skip caching
    return idx


# ---------------------------------------------------------------------
# server binding
# ---------------------------------------------------------------------

class _RespondCollector:
    """Per-upcall response accumulator — the symmetric half of the
    batched ABI: every ``done()`` that fires while its delivery upcall
    is still open parks its packed response here, and ONE
    ``brpc_tpu_ici_respond_batch`` crossing flushes them all when the
    upcall closes.  A ``done()`` arriving later (async handler, tasklet,
    usercode pool) misses the window and responds as a batch of one."""

    __slots__ = ("_binding", "_lock", "_items", "_open")

    _GUARDED_BY = {"_items": "_lock", "_open": "_lock"}

    def __init__(self, binding: "ServerBinding"):
        self._binding = binding
        self._lock = _dbg.make_lock("_RespondCollector._lock")
        self._items: List[tuple] = []
        self._open = True

    def add(self, item: tuple) -> bool:
        with self._lock:
            if not self._open:
                return False
            self._items.append(item)
            return True

    def close_and_flush(self) -> None:
        with self._lock:
            self._open = False
            items, self._items = self._items, []
        if items:
            self._binding._respond_flush(items)


class ServerBinding:
    """Native listener for one device id, dispatching into an
    ``rpc.Server``'s method table (the Python-handler tier; echo-class
    methods can additionally be served fully native via
    ``register_native_echo``).

    Request boundary: the BATCHED one-struct upcall ABI — native
    accumulates ready requests and one ctypes crossing delivers an
    ``IciReqC`` array; responses accumulate in a _RespondCollector and
    one ``brpc_tpu_ici_respond_batch`` crossing writes them back.  Server
    Controllers come from the shared pool and recycle at response time.
    """

    def __init__(self, server, device_id: int):
        lib = native.load()
        if lib is None or not ensure_hooks():
            raise RuntimeError("native core unavailable")
        self._lib = lib
        self._server = server
        self.device_id = device_id
        self._echo_methods: set = set()   # served fully in C, inline
        self._peer_eps: Dict[int, Any] = {}
        self._method_names: Dict[bytes, str] = {}   # decode cache
        self._tenant_names: Dict[bytes, str] = {}   # decode cache
        self._mdcache: Dict[str, tuple] = {}   # full -> (md, status)
        self._tls = threading.local()          # reused respond array
        self._cb = _ICI_BATCH_FN(self._on_batch)   # pinned for lifetime
        # handler rides the listen call: the listener is never visible
        # half-initialized (a racing caller could otherwise ENOMETHOD)
        h = lib.brpc_tpu_ici_listen_batch(device_id, self._cb)
        if h == 0:
            raise OSError(errors.EINVAL,
                          f"ici://{device_id} already listening (native)")
        self._handle = h
        lib.brpc_tpu_ici_set_batch_params(
            h, int(_flags.get_flag("ici_upcall_max_batch")),
            int(_flags.get_flag("ici_upcall_batch_age_us")))
        # native att custody: device-seg lists arrive as parked handles
        # (IciReqC.att_handle) instead of take-during-upcall seg walks.
        # Snapshot at bind time — the A/B bench flips the flag between
        # server generations, never mid-listener.
        self._att_custody = bool(
            _flags.get_flag("ici_native_att_custody"))
        lib.brpc_tpu_ici_set_att_handles(h, 1 if self._att_custody else 0)
        # fused dispatch (ISSUE 13), snapshot at bind like att custody:
        # the inline hot path runs through _process_fused — one flat
        # code object per request — with the per-method dispatch tuple
        # resolved once per raw method key and every hot module handle
        # bound HERE instead of re-resolved per call
        self._fused = bool(_flags.get_flag("ici_fused_dispatch"))
        # the batch-of-1 fast lane's gate, snapshot at bind (options
        # are final once start() ran; the A/B flips flags between
        # server generations, never mid-listener)
        self._fused_inline1 = self._fused and bool(
            getattr(server.options, "usercode_inline", False))
        self._fcache: Dict[bytes, tuple] = {}   # mkey -> dispatch tuple
        self._stage_flag, self._record_stage = _stage_modules()
        self._pool = _controller_pool()
        # dispatch-route truth (OBSERVABILITY.md): how many requests ran
        # the fused body vs the legacy chain on this listener — plain
        # ints bumped on the hot path (an Adder op per RPC is real µs),
        # published by describe()/bench
        self.fused_dispatched = 0
        self.legacy_dispatched = 0
        with _server_bindings_lock:
            _server_bindings[device_id] = self

    def register_native_echo(self, full_method: str) -> None:
        self._lib.brpc_tpu_ici_register_echo(self._handle,
                                             full_method.encode())
        self._echo_methods.add(full_method)

    def stop(self) -> None:
        if self._handle:
            self._lib.brpc_tpu_ici_unlisten(self._handle)
            self._handle = 0
            with _server_bindings_lock:
                if _server_bindings.get(self.device_id) is self:
                    del _server_bindings[self.device_id]

    def requests(self) -> int:
        return self._lib.brpc_tpu_ici_requests(self._handle)

    def batch_stats(self) -> Tuple[int, int, int]:
        """(upcalls, requests_delivered, max_batch_seen) — the batching
        amortization counters (native side)."""
        u = ctypes.c_uint64()
        r = ctypes.c_uint64()
        m = ctypes.c_uint64()
        self._lib.brpc_tpu_ici_batch_stats(
            self._handle, ctypes.byref(u), ctypes.byref(r),
            ctypes.byref(m))
        return u.value, r.value, m.value

    # ---- data-plane upcall (batched one-struct ABI) -------------------

    def _on_batch(self, reqs, n):
        """ONE ctypes crossing for up to ici_upcall_max_batch ready
        requests.  Inline servers process every request here and flush
        every ready response through one respond_batch crossing; other
        dispatch modes fan the requests out (tasklets / usercode pool —
        the queued counter counts BATCH CONTENTS, one per request, so
        the lame-duck drain gate sees each of them)."""
        # the idle/low-load fast lane: ONE fused inline request, no
        # collector, no loop setup — the dominant shape on the echo
        # bench (the snapshot below is taken at bind; options are
        # final once the server started)
        if n == 1 and self._fused_inline1:
            try:
                self._process_fused(reqs[0], None)
            except Exception as e:
                self._batch_request_failed(reqs[0], e)
            return
        try:
            server = self._server
            inline = getattr(server.options, "usercode_inline", False)
            pool = getattr(server, "usercode_pool", None)
            fused = self._fused and inline
            # a batch of ONE (the idle/low-load shape) responds directly —
            # the collector only earns its lock when there is something
            # to amortize
            collector = _RespondCollector(self) if inline and n > 1 \
                else None
            names = self._method_names
            scheduler = None
            try:
                for i in range(n):
                    # per-request failure isolation: an unexpected error
                    # on request i must answer ITS token EINTERNAL and
                    # release ITS seg custody, never abandon the rest of
                    # the batch (their clients would block to timeout and
                    # their untaken device refs would pin HBM forever)
                    r = reqs[i]
                    token = r.token
                    try:
                        if fused:
                            # inline hot path: the whole request — method
                            # resolve, gates, controller setup, parse,
                            # invoke, completion — runs in ONE flat code
                            # object (custody exits inside match the
                            # legacy chain exactly; the except arm below
                            # still covers a failure here, and its
                            # dispose of an already-exited handle is a
                            # table-miss no-op)
                            self._process_fused(r, collector)
                            continue
                        mkey = r.method
                        full = names.get(mkey)
                        if full is None:
                            full = names[mkey] = mkey.decode()
                        payload = ctypes.string_at(r.payload,
                                                   r.payload_len) \
                            if r.payload_len else b""
                        att_host = ctypes.string_at(r.att_host,
                                                    r.att_host_len) \
                            if r.att_host_len else b""
                        nsegs = r.nsegs
                        if nsegs or att_host:
                            ah = r.att_handle
                            if ah:
                                # native custody: the seg list stays
                                # PARKED under ah — one small view
                                # object, zero registry ops, zero
                                # Block builds on this path
                                meta, total = _seg_meta_from_req(
                                    r, nsegs)
                                attachment = NativeAttachment(
                                    ah, total, meta)
                            else:
                                # legacy walk: the registry takes
                                # happen HERE, inside the upcall —
                                # native clears its seg lists when we
                                # return
                                try:
                                    attachment = \
                                        build_attachment_from_c(
                                            att_host, r.segs, nsegs)
                                except KeyError as e:
                                    self._respond_one(
                                        token, errors.EINTERNAL,
                                        str(e))
                                    continue
                        else:
                            attachment = None
                        # admission meta: (wire priority, tenant,
                        # deadline_left_ms) — decoded here once so every
                        # dispatch mode sees identical values
                        tb = r.tenant
                        if tb:
                            tenant = self._tenant_names.get(tb)
                            if tenant is None:
                                tenant = tb.decode()
                                # wire input: cap the decode cache so a
                                # caller cycling tenant names can't grow
                                # it without bound
                                if len(self._tenant_names) < 1024:
                                    self._tenant_names[tb] = tenant
                        else:
                            tenant = ""
                        adm_meta = (r.priority, tenant,
                                    r.deadline_left_ms)
                        if inline:
                            self._process(token, full, payload, attachment,
                                          r.log_id, r.peer_dev, r.recv_ns,
                                          collector, adm_meta)
                        elif pool is not None:
                            # usercode_in_pthread under batching: EVERY
                            # request in the batch is counted queued
                            # individually — the drain gate counts batch
                            # contents, not batches
                            server.on_usercode_queued()
                            reg = server._isolated.get(full) \
                                if server._isolated else None
                            try:
                                if reg is not None:
                                    # isolated method (usercode_pool):
                                    # the payload crosses as bytes to a
                                    # subinterpreter worker; gates —
                                    # admission included — and custody
                                    # run in _run_isolated on the
                                    # backup thread
                                    pool.submit(self._run_isolated,
                                                token, full, payload,
                                                attachment, reg,
                                                adm_meta, r.recv_ns)
                                else:
                                    pool.submit(self._run_usercode,
                                                token, full, payload,
                                                attachment, r.log_id,
                                                r.peer_dev, r.recv_ns,
                                                adm_meta)
                            except RuntimeError:
                                server.on_usercode_done()
                                if reg is not None:
                                    # isolation workers are gone too:
                                    # bounce retryable, like the drain
                                    self._release_attachment_custody(
                                        attachment)
                                    self._respond_one(
                                        token, errors.ELOGOFF,
                                        "server stopping")
                                else:
                                    # pool shut down mid-stop: run here
                                    self._process(token, full, payload,
                                                  attachment, r.log_id,
                                                  r.peer_dev, r.recv_ns,
                                                  None, adm_meta)
                        else:
                            if scheduler is None:
                                from ..bthread import scheduler
                            scheduler.start_background(
                                self._process, token, full, payload,
                                attachment, r.log_id, r.peer_dev,
                                r.recv_ns, None, adm_meta,
                                name=f"ici-req:{full}")
                    except Exception as e:
                        log.error("ici batch request failed: %s", e,
                                  exc_info=True)
                        try:
                            if r.att_handle:
                                # per-request failure isolation, handle
                                # mode: dispose the PARKED entry (a
                                # table miss is a no-op, so racing the
                                # view's own __del__ is safe — handles
                                # are never reused)
                                lib = self._lib
                                lib.brpc_tpu_ici_att_dispose(
                                    r.att_handle)
                            else:
                                for j in range(r.nsegs):  # custody rel.
                                    sg = r.segs[j]
                                    if sg.is_dev:
                                        _registry.release(sg.key)
                        except Exception:
                            pass
                        try:
                            self._respond_one(token, errors.EINTERNAL,
                                              f"{type(e).__name__}: {e}")
                        except Exception:
                            pass
            finally:
                # executed requests' parked responses flush even when a
                # later request in the batch blew up
                if collector is not None:
                    collector.close_and_flush()
        except Exception as e:       # never let an exception cross ctypes
            log.error("ici batch upcall failed: %s", e, exc_info=True)

    def _run_usercode(self, token, full, payload, attachment, log_id,
                      peer_dev, recv_ns, adm_meta=None) -> None:
        try:
            self._process(token, full, payload, attachment, log_id,
                          peer_dev, recv_ns, None, adm_meta)
        finally:
            self._server.on_usercode_done()

    def _run_isolated(self, token, full, payload, attachment, reg,
                      adm_meta=None, recv_ns=0) -> None:
        """A registered isolated method on a backup thread: gates
        (including the SAME admission decision tree every other plane
        runs), the share-nothing pool call (payload bytes →
        subinterpreter worker → response bytes), attachment custody,
        respond.  Isolated methods have no MethodDescriptor — the
        handler source lives in the pool's workers
        (Server.register_isolated)."""
        server = self._server
        try:
            if server._draining:
                self._release_attachment_custody(attachment)
                self._respond_one(token, errors.ELOGOFF,
                                  "server is draining (lame duck)")
                return
            pri_wire, tenant, ddl = adm_meta or (0, "", 0)
            adm = server.admission
            if adm is not None:
                from ..rpc import admission as admission_mod

                def _admitted(queued_us: int) -> None:
                    # the budget shrank while queued: bound the worker
                    # wait by what is LEFT, not the at-recv value
                    left = max(ddl - queued_us // 1000, 1) if ddl else 0
                    self._isolated_admitted(token, full, payload,
                                            attachment, reg, left)

                def _shed(code: int, text: str, retry_after: int) -> None:
                    self._release_attachment_custody(attachment)
                    self._respond_one(token, code, text,
                                      retry_after=retry_after)

                adm.submit(
                    priority=(pri_wire - 1) if pri_wire else None,
                    tenant=tenant,
                    deadline_left_ms=ddl or None,
                    recv_us=(recv_ns // 1000) if recv_ns else 0,
                    try_enter=admission_mod.server_method_gate(server,
                                                               None),
                    run=_admitted, shed=_shed)
                return
            if not server.on_request_in():
                self._release_attachment_custody(attachment)
                self._respond_one(token, errors.ELIMIT,
                                  "server max_concurrency reached")
                return
            self._isolated_admitted(token, full, payload, attachment,
                                    reg, ddl)
        finally:
            server.on_usercode_done()

    def _isolated_admitted(self, token, full, payload, attachment, reg,
                           deadline_left_ms) -> None:
        """Gates held: the pool round trip + custody + respond.  The
        wait on the isolation worker is bounded by the request's OWN
        remaining deadline when it carried one (a 100 ms client must
        not pin a backup thread for the pool's default bound)."""
        server = self._server
        _src, att_mode = reg
        start_ns = _time.monotonic_ns()
        pool = server.usercode_pool
        try:
            if pool is None:
                raise RuntimeError("usercode pool stopped")
            resp = pool.call_isolated(
                full, payload,
                timeout=(deadline_left_ms / 1000.0)
                if deadline_left_ms else None)
        except TimeoutError:
            # budget spent waiting on the worker: the same
            # ERPCTIMEDOUT every other plane reports for a spent
            # deadline, not an internal error
            self._release_attachment_custody(attachment)
            item = (token, errors.ERPCTIMEDOUT,
                    f"isolated handler exceeded deadline "
                    f"({deadline_left_ms}ms)".encode(), b"", b"",
                    (), (None, errors.ERPCTIMEDOUT, 0, server), 0, 0)
            self._respond_item(item)
            return
        except Exception as e:
            self._release_attachment_custody(attachment)
            item = (token, errors.EINTERNAL,
                    f"{type(e).__name__}: {e}".encode(), b"", b"",
                    (), (None, errors.EINTERNAL, 0, server), 0, 0)
            self._respond_item(item)
            return
        latency_us = (_time.monotonic_ns() - start_ns) // 1000
        pass_h = 0
        att_host = b""
        segs = ()
        if attachment is not None:
            if isinstance(attachment, NativeAttachment) \
                    and not attachment._mat:
                if att_mode == "echo":
                    pass_h = attachment._surrender_native()
                else:
                    attachment._dispose_native()
            elif att_mode == "echo" and attachment.backing_block_num():
                # legacy-walk attachment: the echo pays the split
                att_host, segs = split_attachment(attachment)
        item = (token, 0, b"", resp, att_host, segs,
                (None, 0, latency_us, server), 0, pass_h)
        self._respond_item(item)

    # ---- fused dispatch (ISSUE 13) -----------------------------------

    def _batch_request_failed(self, r, e) -> None:
        """Per-request failure isolation for the fused batch-of-1 fast
        lane — mirrors the loop's except arm: answer THIS token
        EINTERNAL and release THIS request's seg custody (a dispose of
        an already-exited handle is a table-miss no-op)."""
        log.error("ici batch request failed: %s", e, exc_info=True)
        try:
            if r.att_handle:
                self._lib.brpc_tpu_ici_att_dispose(r.att_handle)
            else:
                for j in range(r.nsegs):
                    sg = r.segs[j]
                    if sg.is_dev:
                        _registry.release(sg.key)
        except Exception:
            pass
        try:
            self._respond_one(r.token, errors.EINTERNAL,
                              f"{type(e).__name__}: {e}")
        except Exception:
            pass

    def _fused_entry(self, mkey: bytes):
        """Resolve + memoize the per-method dispatch tuple for a raw
        method key: (full, handler fn, request_cls, response_cls,
        status).  Everything per-method — name decode, method lookup,
        codec classes, the limiter handle — resolves ONCE per listener
        instead of per call.  Services cannot be added after start, so
        the cache never goes stale; a miss (unknown method) is NOT
        cached so a typo probe can't grow the table."""
        full = mkey.decode()
        md = self._server.find_method(full)
        if md is None:
            return None
        ent = (full, md.fn, md.request_cls, md.response_cls,
               self._server.method_status(full))
        self._fcache[mkey] = ent
        return ent

    def _process_fused(self, r, collector) -> None:
        """The whole inline request path as ONE flat code object —
        the fusion of _on_batch's extraction, _process's gates, and
        _execute's setup/parse/invoke (completion lives in _FusedDone).
        Semantics mirror the legacy chain exactly; admission-controlled
        servers delegate to it (the shed/WFQ decision tree is not a
        hot-path shape).  Custody: every exit point below matches the
        legacy chain's exactly-one-exit discipline."""
        server = self._server
        token = r.token
        mkey = r.method
        ent = self._fcache.get(mkey)
        if ent is None:
            ent = self._fused_entry(mkey)
        nsegs = r.nsegs
        ahl = r.att_host_len
        attachment = None
        if nsegs or ahl:
            ah = r.att_handle
            if ah:
                # native custody: the seg list stays PARKED under ah —
                # the dominant 1-seg shape reads the inline seg0 mirror
                if nsegs == 1:
                    total = r.seg0_nbytes
                    attachment = NativeAttachment(
                        ah, total, ((r.seg0_key, total, r.seg0_dev),))
                else:
                    meta, total = _seg_meta_from_req(r, nsegs)
                    attachment = NativeAttachment(ah, total, meta)
            else:
                att_host = _string_at(r.att_host, ahl) \
                    if ahl else b""
                try:
                    attachment = build_attachment_from_c(
                        att_host, r.segs, nsegs)
                except KeyError as e:
                    self._respond_one(token, errors.EINTERNAL, str(e),
                                      collector)
                    return
        if server._draining:
            # lame-duck bounce comes BEFORE method resolution, like the
            # legacy chain
            if attachment is not None and \
                    type(attachment) is NativeAttachment:
                attachment._dispose_native()
            self._respond_one(token, errors.ELOGOFF,
                              "server is draining (lame duck)", collector)
            return
        if ent is None:
            if attachment is not None and \
                    type(attachment) is NativeAttachment:
                attachment._dispose_native()
            self._respond_one(token, errors.ENOMETHOD,
                              f"no method {mkey.decode()}", collector)
            return
        full, fn, request_cls, response_cls, status = ent
        pri_wire = r.priority
        tb = r.tenant
        ddl = r.deadline_left_ms
        # the wire tenant decodes BEFORE any gate or pool acquire: a
        # malformed (non-UTF-8) tenant must fail in the pre-gate region
        # — _on_batch's except arm answers EINTERNAL and releases
        # custody, but cannot roll back a concurrency slot or a pooled
        # Controller (the legacy chain decoded in _on_batch for the
        # same reason)
        if tb:
            tenant = self._tenant_names.get(tb)
            if tenant is None:
                tenant = tb.decode()
                if len(self._tenant_names) < 1024:
                    self._tenant_names[tb] = tenant
        else:
            tenant = ""
        payload = _string_at(r.payload, r.payload_len) \
            if r.payload_len else b""
        if server.admission is not None:
            # admission rides the legacy chain (identical decision tree
            # on all planes); the fused entry still saved the method
            # resolve — _process re-reads its own mdcache
            self._process(token, full, payload, attachment, r.log_id,
                          r.peer_dev, r.recv_ns, collector,
                          (pri_wire, tenant, ddl))
            return
        self.fused_dispatched += 1
        stages = self._stage_flag.value == "on"
        if stages:
            recv_ns = r.recv_ns
            if recv_ns:
                q_us = (_time.monotonic_ns() - recv_ns) // 1000
                self._record_stage("queue", max(q_us, 0), None)
        if not server.on_request_in():
            if attachment is not None and \
                    type(attachment) is NativeAttachment:
                attachment._dispose_native()
            self._respond_one(token, errors.ELIMIT,
                              "server max_concurrency reached", collector)
            return
        if status is not None and not status.on_requested():
            server.on_request_out()
            if attachment is not None and \
                    type(attachment) is NativeAttachment:
                attachment._dispose_native()
            self._respond_one(token, errors.ELIMIT,
                              f"{full} concurrency limit", collector)
            return
        cntl = self._pool.acquire()  # fablint: custody-moved(request-lifecycle) the shim rides the request; _maybe_recycle releases it back to the pool when the response (or failure path) completes
        d = cntl.__dict__
        log_id = r.log_id
        if log_id:
            d["log_id"] = log_id
        d["server"] = server
        peer_dev = r.peer_dev
        ep = self._peer_eps.get(peer_dev)
        d["remote_side"] = ep if ep is not None \
            else self._peer_endpoint(peer_dev)
        has_meta = False
        if pri_wire:
            d["priority"] = pri_wire - 1
            has_meta = True
        if tb:
            d["tenant"] = tenant
            has_meta = True
        if ddl:
            d["deadline_left_ms"] = ddl
            has_meta = True
        if attachment is not None:
            d["request_attachment"] = attachment
        start_ns = _time.monotonic_ns()
        try:
            request = request_cls()
            request.ParseFromString(payload)
        except Exception as e:
            cntl._maybe_recycle()
            item = (token, errors.EREQUEST,
                    f"fail to parse request: {e}".encode(), b"", b"", (),
                    (status, errors.EREQUEST, 0, server), 0, 0)
            if collector is None or not collector.add(item):
                self._respond_item(item)
            return
        if stages:
            self._record_stage(
                "parse", (_time.monotonic_ns() - start_ns) // 1000, None)
        response = response_cls()
        fd = _FusedDone(self, token, cntl, response, status, start_ns,
                        collector, stages)
        d["_server_done"] = fd       # cntl.send_response() support
        try:
            # the context scope installs only when it would matter: the
            # request carries admission meta, or an OUTER inline context
            # must be masked for this handler's own outbound calls
            # (nested in-process dispatch) — the no-meta echo shape pays
            # zero frames here.  Inlined _reqctx.scope (same
            # save/install/restore discipline, minus the class frames).
            prev_ctx = getattr(_reqctx_tls, "ctx", None)
            if has_meta or prev_ctx is not None:
                _reqctx_tls.ctx = _reqctx.InboundContext(
                    d.get("priority"), d.get("tenant", ""), ddl) \
                    if has_meta else None
                try:
                    fn(cntl, request, response, fd)
                finally:
                    _reqctx_tls.ctx = prev_ctx
            else:
                fn(cntl, request, response, fd)
        except Exception as e:
            log.error("ici method %s raised: %s", full, e, exc_info=True)
            if not fd.called:
                cntl.set_failed(errors.EINTERNAL,
                                f"{type(e).__name__}: {e}")
                fd()

    def _process(self, token, full, payload, attachment, log_id, peer_dev,
                 recv_ns, collector, adm_meta=None) -> None:
        self.legacy_dispatched += 1
        server = self._server
        stage_flag, record_stage = _stage_modules()
        stages = stage_flag.value == "on"
        if stages and recv_ns:
            q_us = (_time.monotonic_ns() - recv_ns) // 1000
            record_stage("queue", max(q_us, 0), None)
        if server._draining:
            # lame-duck: the native front door stays open through the
            # grace window so in-flight calls finish, but new ones bounce
            # with retryable ELOGOFF (mirrors tpu_std.process_request)
            self._release_attachment_custody(attachment)
            self._respond_one(token, errors.ELOGOFF,
                              "server is draining (lame duck)", collector)
            return
        hit = self._mdcache.get(full)
        if hit is None:
            md = server.find_method(full)
            if md is None:
                self._release_attachment_custody(attachment)
                self._respond_one(token, errors.ENOMETHOD,
                                  f"no method {full}", collector)
                return
            hit = self._mdcache[full] = (md, server.method_status(full))
        md, status = hit
        adm = server.admission
        if adm is not None:
            # admission-control path (rpc/admission.py): the same
            # shed-before-queue / WFQ / deadline decision as the wire
            # and loopback planes, in front of the same gates
            pri_wire, tenant, deadline_left = adm_meta or (0, "", 0)
            from ..rpc import admission as admission_mod

            def _admitted(queued_us: int,
                          _stages=stages, _rs=record_stage) -> None:
                if _stages and queued_us:
                    _rs("queue", queued_us, None)
                self._execute(token, full, payload, attachment, log_id,
                              peer_dev, collector, md, status, adm_meta)

            def _shed(code: int, text: str, retry_after: int) -> None:
                self._release_attachment_custody(attachment)
                self._respond_one(token, code, text, collector,
                                  retry_after=retry_after)

            adm.submit(
                priority=(pri_wire - 1) if pri_wire else None,
                tenant=tenant,
                deadline_left_ms=deadline_left or None,
                recv_us=(recv_ns // 1000) if recv_ns else 0,
                try_enter=admission_mod.server_method_gate(server, status),
                run=_admitted, shed=_shed)
            return
        if not server.on_request_in():
            self._release_attachment_custody(attachment)
            self._respond_one(token, errors.ELIMIT,
                              "server max_concurrency reached", collector)
            return
        if status is not None and not status.on_requested():
            server.on_request_out()
            self._release_attachment_custody(attachment)
            self._respond_one(token, errors.ELIMIT,
                              f"{full} concurrency limit", collector)
            return
        self._execute(token, full, payload, attachment, log_id, peer_dev,
                      collector, md, status, adm_meta)

    @staticmethod
    def _release_attachment_custody(attachment) -> None:
        """Drop a request attachment on a reject path.  Legacy walk:
        its device arrays left the registry at build time (Python owns
        them through the IOBuf) — letting the IOBuf go is the release.
        Native custody: the view still parks its seg list in the att
        table — dispose is the exactly-one exit (idempotent; a
        materialized or surrendered view holds no handle)."""
        if isinstance(attachment, NativeAttachment):
            attachment._dispose_native()
        return

    def _execute(self, token, full, payload, attachment, log_id,
                 peer_dev, collector, md, status, adm_meta=None) -> None:
        """Gates held: parse → invoke → batched write-back."""
        server_controller_pool = _controller_pool()
        server = self._server
        stage_flag, record_stage = _stage_modules()
        stages = stage_flag.value == "on"
        cntl = server_controller_pool.acquire()  # fablint: custody-moved(request-lifecycle) the shim rides the request; _maybe_recycle releases it back to the pool when the response (or failure path) completes
        if log_id:
            cntl.log_id = log_id
        cntl.server = server
        cntl.remote_side = self._peer_endpoint(peer_dev)
        if adm_meta is not None:
            pri_wire, tenant, deadline_left = adm_meta
            if pri_wire:
                cntl.priority = pri_wire - 1
            if tenant:
                cntl.tenant = tenant
            if deadline_left:
                cntl.deadline_left_ms = deadline_left
        if attachment is not None:
            cntl.request_attachment = attachment
        start_ns = _time.monotonic_ns()
        try:
            request = md.request_cls()
            request.ParseFromString(payload)
        except Exception as e:
            cntl._maybe_recycle()

            def parse_post(err=errors.EREQUEST):
                if status is not None:
                    status.on_responded(err, 0)
                server.on_request_out()

            self._respond_one(token, errors.EREQUEST,
                              f"fail to parse request: {e}", collector,
                              post=parse_post)
            return
        if stages:
            record_stage("parse",
                         (_time.monotonic_ns() - start_ns) // 1000, None)
        response = md.response_cls()
        done_called = [False]

        def done() -> None:
            if done_called[0]:
                return
            done_called[0] = True
            t_done = _time.monotonic_ns()
            latency_us = (t_done - start_ns) // 1000
            if stages:
                record_stage("handler", latency_us, None)
            cntl._release_session_data()
            err = cntl.error_code_

            def post() -> None:
                # drain-gate accounting runs AFTER the response crossed
                # back to native: inflight_requests() must never read
                # zero while an EXECUTED request's response still sits
                # in the collector — a lame-duck stop passing the gate
                # there would purge the tokens and turn completed
                # non-idempotent calls into retryable ELOGOFF
                # (duplicate execution), the exact straggler shape the
                # graceful-drain work ordered queued responses ahead of
                # connection failure to prevent
                if status is not None:
                    status.on_responded(err, latency_us)
                server.on_request_out()

            if err:
                # a handler-set shed hint (e.g. the serving pool's
                # saturation shed) rides the respond item like the
                # admission sheds — plane parity with tpu_std/loopback
                self._respond_one(token, err, cntl.error_text_, collector,
                                  post=post,
                                  retry_after=cntl.retry_after_ms or 0)
                return
            resp_att = cntl._peek_response_attachment()
            pass_h = 0
            if resp_att is not None:
                if isinstance(resp_att, NativeAttachment):
                    # echo pass-through: the UNMATERIALIZED request view
                    # assigned as the response — or a ResponseAttachment
                    # that ADOPTED one via append — hands the parked
                    # handle straight back to native; zero Python walks
                    # on the whole response side.  (A materialized view
                    # holds no handle and falls through to the split.)
                    pass_h = resp_att._surrender_native()
                if pass_h:
                    att_host, segs = b"", ()
                elif resp_att.backing_block_num():
                    att_host, segs = split_attachment(resp_att)
                else:
                    att_host, segs = b"", ()
            else:
                att_host, segs = b"", ()
            item = (token, 0, b"", response.SerializeToString(),
                    att_host, segs, post, 0, pass_h)
            if stages:
                record_stage("encode",
                             (_time.monotonic_ns() - t_done) // 1000,
                             None)
            if collector is None or not collector.add(item):
                self._respond_item(item)

        cntl._server_done = done
        try:
            md.invoke(cntl, request, response, done)
        except Exception as e:
            log.error("ici method %s raised: %s", full, e, exc_info=True)
            if not done_called[0]:
                cntl.set_failed(errors.EINTERNAL,
                                f"{type(e).__name__}: {e}")
                done()
                cntl._release_session_data()
                cntl._maybe_recycle()

    def _peer_endpoint(self, peer_dev: int):
        """Per-request endpoint objects are identical for a given peer —
        cache them (a default-mesh lock + EndPoint construction per
        request measured ~1 us on the handler tier).  EndPoints are pure
        (scheme, device-id) values, so the cache survives mesh swaps."""
        ep = self._peer_eps.get(peer_dev)
        if ep is None:
            from .mesh import IciMesh
            ep = self._peer_eps[peer_dev] = \
                IciMesh.default().endpoint(peer_dev)
        return ep

    # ---- batched write-back ------------------------------------------

    def _respond_one(self, token, err, text, collector=None,
                     post=None, retry_after: int = 0) -> None:
        item = (token, err,
                text.encode() if isinstance(text, str) else (text or b""),
                b"", b"", (), post, retry_after, 0)
        if collector is None or not collector.add(item):
            self._respond_item(item)

    def _respond_item(self, item) -> None:
        """Single-response write-back through a per-thread reused
        (IciRespC * 1) array — the batch-of-one fast lane (native copies
        everything during the call, so reuse is safe; every field is
        rewritten here including the NULL ones).  The item's ``post``
        hook (drain-gate accounting) runs AFTER the crossing."""
        tls = self._tls.__dict__
        arr = tls.get("resp1")
        if arr is None:
            arr = tls["resp1"] = (IciRespC * 1)()
        token, err, err_text, payload, att_host, segs, post, \
            retry_after, att_handle = item
        e = arr[0]
        e.token = token
        e.err = err
        e.err_text = err_text or None
        e.retry_after_ms = retry_after
        e.att_handle = att_handle
        if payload:
            e.data = ctypes.cast(payload, _U8P)
            e.len = len(payload)
        else:
            e.data = None
            e.len = 0
        if att_host:
            e.att_host = ctypes.cast(att_host, _U8P)
            e.att_host_len = len(att_host)
        else:
            e.att_host = None
            e.att_host_len = 0
        if segs:
            seg_arr = fill_seg_array(segs)
            e.segs = seg_arr
            e.nsegs = len(segs)
        else:
            seg_arr = None
            e.segs = None
            e.nsegs = 0
        if self._stage_flag.value == "on":
            t0 = _time.monotonic_ns()
            self._lib.brpc_tpu_ici_respond_batch(arr, 1)
            self._record_stage("write",
                               (_time.monotonic_ns() - t0) // 1000, None)
        else:
            self._lib.brpc_tpu_ici_respond_batch(arr, 1)
        del seg_arr, payload, att_host, err_text   # alive across the call
        if post is not None:
            if type(post) is tuple:
                # fused accounting (no per-RPC closure): see _FusedDone
                status, perr, lat, server = post
                if status is not None:
                    status.on_responded(perr, lat)
                server.on_request_out()
            else:
                post()

    def _respond_flush(self, items) -> None:
        """One ``brpc_tpu_ici_respond_batch`` crossing for every packed
        response in ``items`` (each: token, err, err_text, payload,
        att_host, segs, post).  Seg-key custody transfers to native,
        which owns release on EVERY drop path — no per-item return code
        needed.  Each item's ``post`` hook (drain-gate accounting) runs
        AFTER the crossing — see _process.done's ordering note."""
        n = len(items)
        arr = (IciRespC * n)()
        keep = []                      # buffers alive across the call
        for i, (token, err, err_text, payload, att_host, segs, _post,
                retry_after, att_handle) in enumerate(items):
            e = arr[i]
            e.token = token
            e.err = err
            e.retry_after_ms = retry_after
            e.att_handle = att_handle
            if err_text:
                e.err_text = err_text
                keep.append(err_text)
            if payload:
                e.data = ctypes.cast(payload, _U8P)
                e.len = len(payload)
                keep.append(payload)
            if att_host:
                e.att_host = ctypes.cast(att_host, _U8P)
                e.att_host_len = len(att_host)
                keep.append(att_host)
            if segs:
                seg_arr = fill_seg_array(segs)
                e.segs = seg_arr
                e.nsegs = len(segs)
                keep.append(seg_arr)
        if self._stage_flag.value == "on":
            t0 = _time.monotonic_ns()
            self._lib.brpc_tpu_ici_respond_batch(arr, n)
            # under batched delivery the write stage is the SHARED flush
            # crossing: every response in the batch records the same
            # crossing latency (what the request actually waited)
            w_us = (_time.monotonic_ns() - t0) // 1000
            for _ in range(n):
                self._record_stage("write", w_us, None)
        else:
            self._lib.brpc_tpu_ici_respond_batch(arr, n)
        del keep
        for it in items:
            post = it[6]
            if post is not None:
                if type(post) is tuple:
                    status, perr, lat, server = post
                    if status is not None:
                        status.on_responded(perr, lat)
                    server.on_request_out()
                else:
                    post()


class _FusedDone:
    """The fused completion: the legacy chain's done() + post() +
    wrapped_done() collapsed into one callable object — response
    encode, attachment custody exit (pass-through / split), the batched
    write-back, and the pool recycle, with the drain-gate accounting
    (status.on_responded + server.on_request_out) packed as a TUPLE
    into the respond item so it still runs AFTER the response crossed
    back to native (see _process.done's ordering note) without a
    per-RPC closure.  Idempotent like the legacy done."""

    __slots__ = ("binding", "token", "cntl", "response", "status",
                 "start_ns", "collector", "stages", "called")

    def __init__(self, binding, token, cntl, response, status, start_ns,
                 collector, stages):
        self.binding = binding
        self.token = token
        self.cntl = cntl
        self.response = response
        self.status = status
        self.start_ns = start_ns
        self.collector = collector
        self.stages = stages
        self.called = False

    def __call__(self) -> None:
        if self.called:
            return
        self.called = True
        b = self.binding
        cntl = self.cntl
        t_done = _time.monotonic_ns()
        latency_us = (t_done - self.start_ns) // 1000
        stages = self.stages
        if stages:
            b._record_stage("handler", latency_us, None)
        d = cntl.__dict__
        if d.get("_session_data") is not None:
            cntl._release_session_data()
        err = cntl.error_code_
        status = self.status
        server = b._server
        if err:
            text = cntl.error_text_
            item = (self.token, err,
                    text.encode() if isinstance(text, str)
                    else (text or b""), b"", b"", (),
                    (status, err, latency_us, server),
                    cntl.retry_after_ms or 0, 0)
        else:
            resp_att = d.get("response_attachment")
            pass_h = 0
            att_host = b""
            segs = ()
            if resp_att is not None:
                if isinstance(resp_att, NativeAttachment) \
                        and not resp_att._mat:
                    # echo pass-through (also the adopted append shape,
                    # ISSUE 13 satellite): hand the parked handle
                    # straight back — zero Python walks.  Inlined
                    # _surrender_native.
                    pass_h = resp_att._h
                    resp_att._h = 0
                if not pass_h and resp_att.backing_block_num():
                    att_host, segs = split_attachment(resp_att)
            item = (self.token, 0, b"", self.response.SerializeToString(),
                    att_host, segs, (status, 0, latency_us, server),
                    0, pass_h)
            if stages:
                b._record_stage(
                    "encode", (_time.monotonic_ns() - t_done) // 1000,
                    None)
        coll = self.collector
        if coll is None or not coll.add(item):
            b._respond_item(item)
        # attachment custody exits, inlined (the pool-release hooks
        # would re-discover them through getattr): a request view whose
        # handle never exited (handler ignored it) disposes HERE; a
        # surrendered/adopted/materialized one holds no handle and the
        # pop makes the pool's own duck-typed sweep a no-op
        ra = d.pop("request_attachment", None)
        if ra is not None and isinstance(ra, NativeAttachment):
            h = ra._h
            if h:
                ra._h = 0
                fns = _att_fns
                if fns is not None:
                    fns[1](h)
        ra = d.pop("response_attachment", None)
        if ra is not None and isinstance(ra, NativeAttachment):
            h = ra._h
            if h:
                ra._h = 0
                fns = _att_fns
                if fns is not None:
                    fns[1](h)
        # pool recycle (the wrapped_done tail): safe before the
        # collector flushes — the item owns its own buffers and the
        # accounting tuple carries no controller reference
        pool = d.get("_recycle_pool")
        if pool is not None:
            pool.release(cntl)


# ---------------------------------------------------------------------
# channel binding
# ---------------------------------------------------------------------

class ChannelBinding:
    """Client half: one native connection (with its credit window) to the
    in-process native listener at ``remote_dev``."""

    # class-attribute alias: Channel.call_method compares the fused
    # result against the sentinel without an import frame per call
    FUSED_FALLTHROUGH = FUSED_FALLTHROUGH

    def __init__(self, remote_dev: int, local_dev: Optional[int] = None,
                 window_bytes: int = 0):
        lib = native.load()
        if lib is None or not ensure_hooks():
            raise RuntimeError("native core unavailable")
        from .mesh import IciMesh
        mesh = IciMesh.default()
        if local_dev is None:
            local_dev = (remote_dev + 1) % mesh.size
        self._lib = lib
        self.local_dev = local_dev
        self.remote_dev = remote_dev
        self.window_bytes = window_bytes if window_bytes > 0 else (4 << 20)
        self.remote_side = mesh.endpoint(remote_dev)
        self._names: Dict[str, bytes] = {}      # method encode cache
        self._tenants: Dict[str, bytes] = {}    # tenant encode cache
        self._tls = threading.local()           # reused IciCallOut
        # native att custody (snapshot at init, like ServerBinding):
        # call4 parks device-only response attachments under a handle
        # and releases error-path segs natively — the client sheds its
        # take-walks both ways
        self._att_custody = bool(
            _flags.get_flag("ici_native_att_custody"))
        self._call3 = lib.brpc_tpu_ici_call4 if self._att_custody \
            else lib.brpc_tpu_ici_call3         # bound once: attr-chain
        self._free = lib.brpc_tpu_buf_free      # lookups are per-call
        # fused client path (ISSUE 13), snapshot at connect like att
        # custody: Channel.call_method routes sync calls through
        # call_fused — the preamble/screen/issue/response chain as one
        # flat code object.  Hot module handles resolve on first call
        # (the lazy import dance exists only for load-time cycles).
        self._fused = bool(_flags.get_flag("ici_fused_dispatch"))
        self._callf = _fused_call_binding(self._att_custody) \
            if self._fused else None
        self._hot = None
        from ..rpc import span as _span_mod
        self._rpcz_flag = _span_mod._rpcz_flag
        self._start_span = _span_mod.maybe_start_client_span
        self._end_span = _span_mod.end_client_span
        h = lib.brpc_tpu_ici_connect(local_dev, remote_dev, window_bytes)
        if h == 0:
            raise ConnectionRefusedError(
                f"no native listener at ici://{remote_dev}")
        self._handle = h

    def close(self) -> None:
        if self._handle:
            self._lib.brpc_tpu_ici_close(self._handle)
            self._handle = 0

    def __del__(self):                   # noqa: D105 — native conn must not
        try:                             # outlive its Python owner
            self.close()
        except Exception:
            pass

    def window_left(self) -> int:
        return self._lib.brpc_tpu_ici_window_left(self._handle)

    def call(self, full_name: str, cntl, request: Any,
             response_cls: Optional[type] = None):
        """Unary call over the native datapath.  Fills cntl; returns the
        parsed response (or raw payload bytes when response_cls is None)."""
        _fi, scheduler, _t = _hot_modules()
        # fault injection covers the fast plane too, with the SAME
        # semantics as the Python plane's Socket.write boundary: DROP =
        # bytes vanish, the call waits out its deadline; ERROR = the
        # connection is severed (every later call on this binding fails
        # until the channel re-routes/reconnects).
        injector = _fi.active()
        if injector is not None:
            action = injector.decide(self)
            if action == _fi.DROP:
                tms = cntl.timeout_ms
                # no deadline = a genuine hang; bound it so a
                # misconfigured test fails instead of wedging forever
                _time.sleep((tms / 1000.0) if tms and tms > 0 else 60.0)
                cntl.set_failed(errors.ERPCTIMEDOUT
                                if tms and tms > 0 else errors.EFAILEDSOCKET,
                                "rpc timeout (injected drop)")
                return None
            if action == _fi.ERROR:
                cntl.set_failed(errors.EFAILEDSOCKET, "injected fault")
                self.close()             # severed, like Socket.set_failed
                return None
        t0 = _time.monotonic_ns()
        try:
            req = request.SerializeToString()
        except AttributeError:
            req = bytes(request) if request is not None else b""
        req_att = cntl._peek_request_attachment()
        if req_att is not None and req_att.backing_block_num():
            att_host, segs = split_attachment(req_att)
            dev_bytes = sum(s[1] for s in segs if s[3])
        else:
            att_host, segs, dev_bytes = b"", (), 0
        # bytes objects pass by pointer (cast, no copy): the native side
        # never writes through request pointers and copies before returning
        u8p = _U8P
        reqb = ctypes.cast(req, u8p) if req else None
        attb = ctypes.cast(att_host, u8p) if att_host else None
        seg_arr = fill_seg_array(segs) if segs else None
        # one out-block instead of seven byref temporaries: the 17-arg
        # ctypes conversion measured ~3-4 us/call (VERDICT r4 weak #3).
        # Reused per thread — native zeroes every field on entry, so a
        # fresh allocation per call buys nothing
        tls = self._tls.__dict__
        out = tls.get("out")
        if out is None:
            out = tls["out"] = IciCallOut()
            tls["out_ref"] = ctypes.byref(out)
        out_ref = tls["out_ref"]
        name_b = self._names.get(full_name)
        if name_b is None:
            name_b = self._names[full_name] = full_name.encode()
        # timeout_ms <= 0 means NO deadline (controller.py:169 semantics);
        # the native side treats timeout_us <= 0 the same way
        tms = cntl.timeout_ms
        timeout_us = int(tms * 1000) if tms is not None and tms > 0 else 0
        # admission meta rides the native frame: wire-encoded priority
        # (0 = unset), tenant, and the remaining deadline budget (the
        # full per-try budget at this hop's send time)
        pri_wire = cntl.priority + 1 if cntl.priority is not None else 0
        tenant = cntl.tenant
        if tenant:
            tenant_b = self._tenants.get(tenant)
            if tenant_b is None:
                tenant_b = self._tenants[tenant] = tenant.encode()
        else:
            tenant_b = None
        # the FFI call can park on a C condvar (Python-tier handler): a
        # tasklet-pool worker must note itself blocked so the scheduler
        # compensates — otherwise handler tasklets starve behind us and
        # the call deadlocks until timeout (review finding r4)
        blocked = scheduler.in_worker()
        if blocked:
            scheduler.note_worker_blocked()
        try:
            rc = self._call3(
                self._handle, name_b, reqb, len(req), attb,
                len(att_host), seg_arr, len(segs), timeout_us, pri_wire,
                tenant_b, int(tms) if tms is not None and tms > 0 else 0,
                out_ref)
        finally:
            if blocked:
                scheduler.note_worker_unblocked()
        try:
            cntl.remote_side = self.remote_side
            nsegs = out.nsegs
            if rc != 0:
                if not self._att_custody:
                    # native copies response segs to segs_out even when
                    # the handler responded with an error: release their
                    # device keys or they strand in the registry forever
                    # (the exactly-one-exit custody invariant).  call4
                    # releases them native-side — no walk at all.
                    for i in range(nsegs):
                        if out.segs[i].is_dev and out.segs[i].key:
                            _registry.release(out.segs[i].key)
                text = ctypes.string_at(out.err_text).decode() \
                    if out.err_text else errors.berror(int(rc))
                cntl.set_failed(int(rc), text)
                if out.retry_after_ms:
                    # admission shed hint (retryable ELIMIT backoff)
                    cntl.retry_after_ms = int(out.retry_after_ms)
                return None
            payload = ctypes.string_at(out.resp, out.resp_len) \
                if out.resp_len else b""
            if nsegs or out.att_len:
                ah = out.att_handle
                if ah:
                    # native custody: the response seg list stays
                    # parked — wrap it lazily (seg0 rides inline for
                    # the 1-seg shape; the >1 metadata copy is read
                    # NOW, before the finally block frees it)
                    if nsegs == 1:
                        total = out.seg0_nbytes
                        meta = ((out.seg0_key, total, out.seg0_dev),)
                    else:
                        segs_p = out.segs
                        lst = []
                        total = 0
                        for i in range(nsegs):
                            s = segs_p[i]
                            lst.append((s.key, s.nbytes, s.dev))
                            total += s.nbytes
                        meta = tuple(lst)
                    rbuf = NativeAttachment(ah, total, meta)
                else:
                    r_att_host = ctypes.string_at(out.att, out.att_len) \
                        if out.att_len else b""
                    rbuf = build_attachment_from_c(r_att_host, out.segs,
                                                   nsegs)
                prev = cntl._peek_response_attachment()
                if prev is None:
                    cntl.response_attachment = rbuf
                else:
                    prev.append(rbuf)
            # transport accounting (the Python plane's counters — one
            # fabric-wide truth regardless of datapath)
            with _t._ici_stats_lock:
                _t._ici_bytes_moved += len(req) + len(att_host) + dev_bytes
                _t._ici_device_bytes_moved += dev_bytes
            cntl.error_code_ = 0
            if response_cls is None:
                return payload
            response = response_cls()
            response.ParseFromString(payload)
            cntl.response = response
            return response
        finally:
            cntl.latency_us = (_time.monotonic_ns() - t0) // 1000
            # free AND NULL every out pointer: the struct is reused (per
            # thread, and re-entered by nested calls from inline
            # handlers) — a stale pointer surviving into a call whose
            # response leaves that field untouched would double-free
            free = self._free
            if out.resp:
                free(out.resp)
                out.resp = None
            if out.att:
                free(out.att)
                out.att = None
            if out.segs:
                free(out.segs)
                out.segs = None
            if out.err_text:
                free(out.err_text)
                out.err_text = None

    def call_fused(self, full_name: str, cntl, request: Any,
                   response_cls, chan):
        """The fused sync client path (ISSUE 13): Channel.call_method's
        context/default preamble, the per-call screens, and the whole
        ``call`` body as ONE flat code object, with the dominant
        1-device-block attachment shape inlined (no split/fill frames)
        and the shed-retry / fallback helpers entered ONLY when their
        error actually occurred.  Must mirror ``call_method`` +
        ``call`` semantics exactly — the ``ici_fused_dispatch=False``
        leg A/Bs them.  Returns FUSED_FALLTHROUGH when the call must
        re-route to the Python plane (frame too large, hedging
        configured, dead-conn re-route)."""
        opts = chan.options
        # ---- cascading inbound context + channel defaults ------------
        ctx = getattr(_reqctx_tls, "ctx", None)
        if ctx is not None:
            if cntl.priority is None and ctx.priority is not None:
                cntl.priority = ctx.priority
            if not cntl.tenant and ctx.tenant:
                cntl.tenant = ctx.tenant
            residual = ctx.residual_deadline_ms()
            if residual is not None:
                if residual <= 0:
                    cntl.set_failed(
                        errors.ERPCTIMEDOUT,
                        "inherited deadline budget spent before call")
                    if cntl.span is not None:
                        self._end_span(cntl)
                    return None
                base = cntl.timeout_ms if cntl.timeout_ms is not None \
                    else opts.timeout_ms
                if base is None or base <= 0 or base > residual:
                    cntl.timeout_ms = max(int(residual), 1)
        if cntl.priority is None and opts.priority is not None:
            cntl.priority = opts.priority
        if not cntl.tenant and opts.tenant:
            cntl.tenant = opts.tenant
        # ---- per-call screens (mirrors _fast_call_fits) --------------
        if opts.backup_request_ms > 0:
            return FUSED_FALLTHROUGH
        req_att = cntl.__dict__.get("request_attachment")
        if req_att is None:
            att_len = 0
        elif type(req_att) is IOBuf:
            att_len = req_att._size
        else:
            att_len = len(req_att)     # lazy views answer w/o inflating
        try:
            req_sz = request.ByteSize()
        except Exception:
            req_sz = 0
        if att_len + req_sz + 65536 > self.window_bytes:
            return FUSED_FALLTHROUGH
        if cntl.timeout_ms is None:
            cntl.timeout_ms = opts.timeout_ms
        if cntl.span is None and self._rpcz_flag.value:
            self._start_span(cntl, full_name)
        hot = self._hot
        if hot is None:
            hot = self._hot = _hot_modules()
        _fi, scheduler, _t = hot
        if _fi._active is not None:
            # fault injection armed: the legacy body implements the
            # drop/sever semantics — not a hot shape
            result = self.call(full_name, cntl, request, response_cls)
        else:
            t0 = _time.monotonic_ns()
            try:
                req = request.SerializeToString()
            except AttributeError:
                req = bytes(request) if request is not None else b""
            tls = self._tls.__dict__
            att_host = b""
            seg_arr = None
            nseg = 0
            dev_bytes = 0
            if req_att is not None and att_len:
                fast = None
                if type(req_att) is IOBuf:
                    refs = req_att._refs
                    if len(refs) == 1:
                        ref = refs[0]
                        blk = ref.block
                        if (blk.kind == DEVICE and not ref.offset
                                and ref.length == blk.size):
                            fast = (blk.data, ref.length)
                if fast is not None:
                    # the dominant shape — one whole device block:
                    # registry put + reused 1-seg array, zero
                    # split/fill frames; the residence cache hit is
                    # inlined (a steady workload re-posts the same
                    # arrays)
                    arr, nbytes = fast
                    seg_arr = tls.get("seg1")
                    if seg_arr is None:
                        seg_arr = tls["seg1"] = (IciSegC * 1)()
                    e = seg_arr[0]
                    e.key = _registry.put(arr)  # fablint: custody-moved(wire-segment) the key rides the IciSeg to the native sender, which takes/releases it after the DMA posts
                    e.nbytes = nbytes
                    IM = _IciMesh
                    hit = _devidx_cache.get(id(arr)) \
                        if IM is not None else None
                    if hit is not None and hit[0] == IM.generation:
                        e.dev = hit[1]
                    else:
                        e.dev = _device_index(arr)
                    e.is_dev = 1
                    nseg = 1
                    dev_bytes = nbytes
                else:
                    att_host, segs = split_attachment(req_att)
                    if segs:
                        seg_arr = fill_seg_array(segs)
                        nseg = len(segs)
                        dev_bytes = sum(s[1] for s in segs if s[3])
            out = tls.get("out")
            if out is None:
                out = tls["out"] = IciCallOut()
                tls["out_ref"] = ctypes.byref(out)
            out_ref = tls["out_ref"]
            name_b = self._names.get(full_name)
            if name_b is None:
                name_b = self._names[full_name] = full_name.encode()
            tms = cntl.timeout_ms
            timeout_us = int(tms * 1000) if tms is not None and tms > 0 \
                else 0
            pri_wire = cntl.priority + 1 if cntl.priority is not None \
                else 0
            tenant = cntl.tenant
            if tenant:
                tenant_b = self._tenants.get(tenant)
                if tenant_b is None:
                    tenant_b = self._tenants[tenant] = tenant.encode()
            else:
                tenant_b = None
            # inlined scheduler.in_worker (one thread-local read)
            blocked = getattr(scheduler._tls, "group", None) is not None
            if blocked:
                scheduler.note_worker_blocked()
            try:
                rc = self._callf(
                    self._handle, name_b, req or None, len(req),
                    att_host or None, len(att_host), seg_arr, nseg,
                    timeout_us, pri_wire, tenant_b,
                    int(tms) if tms is not None and tms > 0 else 0,
                    out_ref)
            finally:
                if blocked:
                    scheduler.note_worker_unblocked()
            result = None
            # read each out pointer ONCE into locals: the finally frees
            # from these instead of re-reading the struct
            resp_p = out.resp
            att_p = out.att
            segs_p0 = out.segs
            err_p = out.err_text
            try:
                cntl.remote_side = self.remote_side
                nsegs = out.nsegs
                if rc != 0:
                    if not self._att_custody:
                        for i in range(nsegs):
                            if out.segs[i].is_dev and out.segs[i].key:
                                _registry.release(out.segs[i].key)
                    text = _string_at(err_p, -1).decode() \
                        if err_p else errors.berror(int(rc))
                    cntl.set_failed(int(rc), text)
                    if out.retry_after_ms:
                        cntl.retry_after_ms = int(out.retry_after_ms)
                else:
                    payload = _string_at(resp_p, out.resp_len) \
                        if out.resp_len else b""
                    if nsegs or out.att_len:
                        ah = out.att_handle
                        if ah:
                            if nsegs == 1:
                                total = out.seg0_nbytes
                                meta = ((out.seg0_key, total,
                                         out.seg0_dev),)
                            else:
                                segs_p = out.segs
                                lst = []
                                total = 0
                                for i in range(nsegs):
                                    s = segs_p[i]
                                    lst.append((s.key, s.nbytes, s.dev))
                                    total += s.nbytes
                                meta = tuple(lst)
                            rbuf = NativeAttachment(ah, total, meta)
                        else:
                            r_att_host = _string_at(
                                att_p, out.att_len) if out.att_len \
                                else b""
                            rbuf = build_attachment_from_c(
                                r_att_host, out.segs, nsegs)
                        prev = cntl.__dict__.get("response_attachment")
                        if prev is None:
                            cntl.response_attachment = rbuf
                        else:
                            prev.append(rbuf)
                    with _t._ici_stats_lock:
                        _t._ici_bytes_moved += \
                            len(req) + len(att_host) + dev_bytes
                        _t._ici_device_bytes_moved += dev_bytes
                    cntl.error_code_ = 0
                    if response_cls is None:
                        result = payload
                    else:
                        response = response_cls()
                        response.ParseFromString(payload)
                        cntl.response = response
                        result = response
            finally:
                cntl.latency_us = (_time.monotonic_ns() - t0) // 1000
                free = self._free
                if resp_p:
                    free(resp_p)
                    out.resp = None
                if att_p:
                    free(att_p)
                    out.att = None
                if segs_p0:
                    free(segs_p0)
                    out.segs = None
                if err_p:
                    free(err_p)
                    out.err_text = None
        # ---- legacy tail, entered only on the error that needs it ----
        ec = cntl.error_code_
        if ec:
            if ec == errors.ELIMIT and cntl.retry_after_ms > 0:
                result = chan._native_shed_retry(
                    self, full_name, cntl, request, response_cls, result)
                ec = cntl.error_code_
            if ec == errors.EFAILEDSOCKET or (
                    ec == errors.EOVERCROWDED
                    and cntl.error_text_.startswith("frame larger")):
                if chan._native_ici_fallback(cntl):
                    return FUSED_FALLTHROUGH
        if cntl.span is not None:
            self._end_span(cntl)
        return result


def native_ici_echo_p50_us(iters: int = 3000, payload: int = 128,
                           device_array=None) -> float:
    """Native-loop ici echo p50 (µs): the C++ client loop over the full
    native ici datapath (window → frame codec → queue hop → dispatch →
    correlation wake).  With ``device_array``, the frame carries that
    array as a device ref (resident = the pure-HBM round trip).  -1 when
    unavailable."""
    lib = native.load()
    if lib is None or not ensure_hooks():
        return -1.0
    key, nbytes, dev = 0, 0, 0
    if device_array is not None:
        # compute the descriptor BEFORE registering: _device_index can
        # raise (stale mesh), and a raise after put would leak the key
        # past the try/finally below (fablint custody true positive)
        nbytes = device_array.nbytes
        dev = _device_index(device_array)
        key = _registry.put(device_array)    # borrowed for the bench
    try:
        ns = lib.brpc_tpu_ici_echo_p50_ns(iters, payload, key, nbytes, dev)
        return ns / 1000.0 if ns > 0 else -1.0
    finally:
        if key:
            _registry.release(key)
