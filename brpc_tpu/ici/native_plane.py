"""Native ici:// plane — Python control plane over native/rpc.cpp's ici
datapath.

This is the fusion VERDICT r2/r3 task #1 demanded: the full unary hot path
(window reservation → TRPC frame encode → queue hop → dispatch →
correlation wake) runs in C++; Python appears on the datapath ONLY for
device-ref relocation (``jax.device_put``, the HBM→HBM ICI transfer), and
only when a ref is not already resident on the target chip.  Reference
anchors: the wait-free write discipline src/brpc/socket.cpp:1584-1596 and
the RDMA endpoint's zero-copy post + completion custody
src/brpc/rdma/rdma_endpoint.cpp:771,926.

Three pieces:

* **device-ref registry** — keeps jax arrays alive while their keys are in
  native custody.  Custody rules (must mirror native/rpc.cpp exactly):
  a key given to native exits custody either INTO Python (``take`` at an
  upcall or response boundary) or via the release upcall on drop paths.
* **ServerBinding** — attaches an ``rpc.Server``'s method table to a
  native listener; per-request upcall parses + dispatches user code
  (inline or on a tasklet, mirroring InputMessenger's dispatch).
* **ChannelBinding** — the client side used by ``rpc.Channel`` when the
  target device has a native listener in this process.
"""
from __future__ import annotations

import ctypes
import threading
import time as _time
from typing import Any, Dict, List, Optional, Tuple

from ..butil import logging as log
from ..butil import native
from ..butil.iobuf import IOBuf, DEVICE
from ..butil.native import IciCallOut, IciSegC, _ICI_RELEASE_FN, \
    _ICI_RELOCATE_FN, _ICI_REQ_FN
from ..rpc import errors

_U8P = ctypes.POINTER(ctypes.c_uint8)

# hot-path module handles, resolved once at first call: the per-call
# `from x import y` dance measured ~1 us/call on the fast plane (the
# lazy-at-call-time form exists only to dodge import cycles at load)
_hot = None


def _hot_modules():
    global _hot
    if _hot is None:
        from ..bthread import scheduler
        from ..rpc import fault_injection
        from . import transport
        _hot = (fault_injection, scheduler, transport)
    return _hot


# ---------------------------------------------------------------------
# device-ref registry
# ---------------------------------------------------------------------

class _DevRegistry:
    """key → jax.Array, alive while the key is in native custody."""

    def __init__(self):
        self._m: Dict[int, Any] = {}
        self._lock = threading.Lock()
        self._next = 1

    def put(self, arr) -> int:
        with self._lock:
            key = self._next
            self._next += 1
            self._m[key] = arr
            return key

    def peek(self, key: int):
        with self._lock:
            return self._m.get(key)

    def take(self, key: int):
        """Remove and return — the Python side assumes custody."""
        with self._lock:
            return self._m.pop(key, None)

    def release(self, key: int) -> None:
        with self._lock:
            self._m.pop(key, None)

    def live(self) -> int:
        with self._lock:
            return len(self._m)


_registry = _DevRegistry()


def registry() -> _DevRegistry:
    return _registry


# ---------------------------------------------------------------------
# hooks (relocation = the only Python on the datapath)
# ---------------------------------------------------------------------

def _relocate(key: int, target_dev: int) -> int:
    """Move the array behind ``key`` to mesh device ``target_dev``; returns
    a NEW key for the moved array (native releases the old one) or the same
    key when already resident.  0 = failure (native fails the RPC).

    Payloads at/above ``ici_device_plane_threshold`` cross through the
    device plane's compiled transfer program (post_send + rendezvous —
    the no-host datapath); smaller or refused ones keep device_put."""
    try:
        import jax
        from .mesh import IciMesh
        arr = _registry.peek(key)
        if arr is None:
            return 0
        mesh = IciMesh.default()
        target = mesh.device(target_dev)
        if not hasattr(arr, "devices"):
            # host-delivered fabric bulk payload (a ctypes-backed numpy
            # view over the native receive buffer) being forwarded into
            # an in-process call: detach into an owned copy first —
            # device_put zero-copy ALIASES such views WITHOUT retaining
            # them, and the native pool recycles the buffer under the
            # alias (same discipline as transport.py _relocate)
            import numpy as np
            arr = np.array(arr, copy=True)
        else:
            try:
                if target in arr.devices():
                    return key                   # resident: pure ref pass
            except Exception:
                pass
            from . import device_plane as _dp
            nbytes = int(arr.shape[0]) if arr.ndim == 1 else 0
            if nbytes and _dp.eligible(nbytes):
                src_idx = _dp.mesh_index_of(arr, mesh)
                if src_idx >= 0 and src_idx != target_dev:
                    try:
                        t = _dp.plane().transfer_local(arr, src_idx,
                                                       target_dev)
                        return _registry.put(t.out)
                    except _dp.DevicePlaneError:
                        pass       # counted by the plane; device_put path
        moved = jax.device_put(arr, target)      # HBM→HBM over ICI
        return _registry.put(moved)
    except Exception as e:                       # never raise across ctypes
        log.error("ici relocate(key=%d, dev=%d) failed: %s", key,
                  target_dev, e)
        return 0


def _release(key: int) -> None:
    _registry.release(key)


_hooks_installed = False
_hooks_lock = threading.Lock()
_relocate_cb = None
_release_cb = None


def ensure_hooks() -> bool:
    """Install the relocate/release upcalls once per process."""
    global _hooks_installed, _relocate_cb, _release_cb
    lib = native.load()
    if lib is None:
        return False
    with _hooks_lock:
        if not _hooks_installed:
            _relocate_cb = _ICI_RELOCATE_FN(_relocate)
            _release_cb = _ICI_RELEASE_FN(_release)
            lib.brpc_tpu_ici_set_hooks(_relocate_cb, _release_cb)
            _hooks_installed = True
    return True


def available() -> bool:
    return native.available()


def has_listener(device_id: int) -> bool:
    lib = native.load()
    return lib is not None and \
        lib.brpc_tpu_ici_has_listener(device_id) == 1


# Python-side view of live ServerBindings, for properties native cannot
# answer (dispatch mode).  device_id -> ServerBinding.
_server_bindings: Dict[int, "ServerBinding"] = {}
_server_bindings_lock = threading.Lock()


def listener_dispatch_inline(device_id: int,
                             method: Optional[str] = None) -> Optional[bool]:
    """True when the in-process listener at ``device_id`` answers
    ``method`` INLINE on the caller's thread — usercode_inline servers
    (every method), or the compiled echo tier (that method is served
    fully in C regardless of the server's dispatch mode).  False when
    the handler parks on a tasklet, None when unknown.  Fan-out issuers
    use this: against an inline answer a sub-call-per-tasklet buys no
    concurrency (the work runs in the caller's stack either way) and
    costs a scheduling hop."""
    with _server_bindings_lock:
        b = _server_bindings.get(device_id)
    if b is None:
        return None
    if method is not None and method in b._echo_methods:
        return True
    return bool(getattr(b._server.options, "usercode_inline", False))


# ---------------------------------------------------------------------
# IOBuf ⇄ (att_host, segs) marshalling
# ---------------------------------------------------------------------

def split_attachment(buf: IOBuf) -> Tuple[bytes, List[IciSegC]]:
    """Decompose an attachment IOBuf into the host byte-stream plus the
    ordered segment descriptor list.  Device blocks are registered (native
    custody begins); host runs merge into one descriptor each."""
    if buf.backing_block_num() == 1:
        # the dominant fast-plane shape: one whole device block
        r = buf.backing_block(0)
        if (r.block.kind == DEVICE and not r.offset
                and r.length == len(r.block.data)):
            arr = r.block.data
            return b"", [IciSegC(_registry.put(arr), r.length,
                                 _device_index(arr), 1)]
    host_parts: List[bytes] = []
    segs: List[IciSegC] = []
    run = 0
    for i in range(buf.backing_block_num()):
        r = buf.backing_block(i)
        if r.block.kind == DEVICE:
            if run:
                segs.append(IciSegC(0, run, 0, 0))
                run = 0
            arr = r.block.data
            if r.offset or r.length != len(arr):
                arr = arr[r.offset:r.offset + r.length]
            dev = _device_index(arr)
            segs.append(IciSegC(_registry.put(arr), r.length, dev, 1))
        else:
            host_parts.append(bytes(r.block.host_view(r.offset, r.length)))
            run += r.length
    if run:
        segs.append(IciSegC(0, run, 0, 0))
    return b"".join(host_parts), segs


def build_attachment(att_host: bytes, segs) -> IOBuf:
    """Inverse of split_attachment on the receiving side: takes each
    device key out of the registry (custody moves to this IOBuf).
    Arrays from the registry were shape-validated when they entered it
    (append_device_array / the relocate hook), so re-validation is
    skipped here — worth ~0.5 us/call on the fast plane."""
    buf = IOBuf()
    off = 0
    for s in segs:
        if s.is_dev:
            arr = _registry.take(s.key)
            if arr is None:
                raise KeyError(f"ici device ref {s.key} missing")
            buf.append_device_array_unchecked(arr, s.nbytes)
        else:
            buf.append(att_host[off:off + s.nbytes])
            off += s.nbytes
    return buf


# id(arr) -> (mesh generation, mesh index), evicted by a finalizer when
# the array dies (the id is unique until then).  A steady workload
# re-posts the same payload arrays, and arr.device + the mesh lookup
# measured ~2-3 us/call on the axon backend.  An array cannot change
# residence in place, but the MESH can be swapped (IciMesh.set_default)
# — entries are keyed on the mesh generation so a swap invalidates them
# instead of silently stamping a wrong logical id (review finding r5).
# idx == -1 ("not in the mesh") is never cached: it usually means the
# mesh isn't configured yet, and pinning it would force a relocate
# upcall on every later send of that array.
_devidx_cache: Dict[int, Tuple[int, int]] = {}


def _device_index(arr) -> int:
    """Logical mesh id of the array's residence, or -1 when the device is
    not in the mesh.  -1 never equals a target id, so native relocation
    always upcalls for such refs — the relocate hook then does the real
    residency check/device_put, preserving Python-plane semantics instead
    of silently skipping relocation (review finding: a 0 default would
    alias device 0)."""
    from .mesh import IciMesh
    gen = IciMesh.generation
    key = id(arr)
    hit = _devidx_cache.get(key)
    if hit is not None and hit[0] == gen:
        return hit[1]
    mesh = IciMesh.default()
    idx = -1
    try:
        idx = mesh.device_index(arr.device)      # single-device fast path
    except Exception:
        pass
    if idx < 0:
        try:
            for d in arr.devices():
                i = mesh.device_index(d)
                if i >= 0:
                    idx = i
                    break
        except Exception:
            pass
    if idx >= 0:
        try:
            import weakref
            if hit is None:
                weakref.finalize(arr, _devidx_cache.pop, key, None)
            _devidx_cache[key] = (gen, idx)
        except TypeError:
            pass                 # not weakref-able: skip caching
    return idx


def release_segs(segs) -> None:
    for s in segs:
        if s.is_dev:
            _registry.release(s.key)


# ---------------------------------------------------------------------
# server binding
# ---------------------------------------------------------------------

class ServerBinding:
    """Native listener for one device id, dispatching into an
    ``rpc.Server``'s method table (the Python-handler tier; echo-class
    methods can additionally be served fully native via
    ``register_native_echo``)."""

    def __init__(self, server, device_id: int):
        lib = native.load()
        if lib is None or not ensure_hooks():
            raise RuntimeError("native core unavailable")
        self._lib = lib
        self._server = server
        self.device_id = device_id
        self._echo_methods: set = set()   # served fully in C, inline
        self._peer_eps: Dict[int, Any] = {}
        self._cb = _ICI_REQ_FN(self._on_request)   # pinned for lifetime
        # handler rides the listen call: the listener is never visible
        # half-initialized (a racing caller could otherwise ENOMETHOD)
        h = lib.brpc_tpu_ici_listen(device_id, self._cb)
        if h == 0:
            raise OSError(errors.EINVAL,
                          f"ici://{device_id} already listening (native)")
        self._handle = h
        with _server_bindings_lock:
            _server_bindings[device_id] = self

    def register_native_echo(self, full_method: str) -> None:
        self._lib.brpc_tpu_ici_register_echo(self._handle,
                                             full_method.encode())
        self._echo_methods.add(full_method)

    def stop(self) -> None:
        if self._handle:
            self._lib.brpc_tpu_ici_unlisten(self._handle)
            self._handle = 0
            with _server_bindings_lock:
                if _server_bindings.get(self.device_id) is self:
                    del _server_bindings[self.device_id]

    def requests(self) -> int:
        return self._lib.brpc_tpu_ici_requests(self._handle)

    # ---- data-plane upcall -------------------------------------------

    def _on_request(self, token, method, payload_p, payload_len,
                    att_p, att_len, segs_p, nsegs, log_id, peer_dev):
        try:
            full = method.decode()
            payload = ctypes.string_at(payload_p, payload_len) \
                if payload_len else b""
            att_host = ctypes.string_at(att_p, att_len) if att_len else b""
            # custody: the registry takes happen HERE, inside the upcall —
            # native clears its seg list when we return
            segs = [IciSegC(segs_p[i].key, segs_p[i].nbytes, segs_p[i].dev,
                            segs_p[i].is_dev) for i in range(nsegs)]
            try:
                attachment = build_attachment(att_host, segs)
            except KeyError as e:
                self._respond_err(token, errors.EINTERNAL, str(e))
                return
            if getattr(self._server.options, "usercode_inline", False):
                self._process(token, full, payload, attachment, log_id,
                              peer_dev)
            else:
                from ..bthread import scheduler
                scheduler.start_background(
                    self._process, token, full, payload, attachment,
                    log_id, peer_dev, name=f"ici-req:{full}")
        except Exception as e:       # never let an exception cross ctypes
            log.error("ici upcall failed: %s", e, exc_info=True)
            try:
                self._respond_err(token, errors.EINTERNAL, str(e))
            except Exception:
                pass

    def _process(self, token, full, payload, attachment, log_id, peer_dev):
        from ..rpc.controller import Controller
        server = self._server
        if server.is_draining():
            # lame-duck: the native front door stays open through the
            # grace window so in-flight calls finish, but new ones bounce
            # with retryable ELOGOFF (mirrors tpu_std.process_request)
            self._respond_err(token, errors.ELOGOFF,
                              "server is draining (lame duck)")
            return
        md = server.find_method(full)
        if md is None:
            self._respond_err(token, errors.ENOMETHOD, f"no method {full}")
            return
        status = server.method_status(full)
        if not server.on_request_in():
            self._respond_err(token, errors.ELIMIT,
                              "server max_concurrency reached")
            return
        if status is not None and not status.on_requested():
            server.on_request_out()
            self._respond_err(token, errors.ELIMIT,
                              f"{full} concurrency limit")
            return
        cntl = Controller()
        cntl.log_id = log_id
        cntl.server = server
        cntl.remote_side = self._peer_endpoint(peer_dev)
        cntl.request_attachment = attachment
        cntl._session_data = server._get_session_data()
        start_ns = _time.monotonic_ns()
        try:
            request = md.request_cls()
            request.ParseFromString(payload)
        except Exception as e:
            server.on_request_out()
            if status is not None:
                status.on_responded(errors.EREQUEST, 0)
            self._respond_err(token, errors.EREQUEST,
                              f"fail to parse request: {e}")
            return
        response = md.response_cls()
        done_called = [False]

        def done() -> None:
            if done_called[0]:
                return
            done_called[0] = True
            latency_us = (_time.monotonic_ns() - start_ns) // 1000
            server.on_request_out()
            if status is not None:
                status.on_responded(cntl.error_code_, latency_us)
            server._return_session_data(
                getattr(cntl, "_session_data", None))
            if cntl.failed():
                self._respond_err(token, cntl.error_code_, cntl.error_text_)
                return
            if cntl.response_attachment.backing_block_num():
                att_host, segs = split_attachment(cntl.response_attachment)
            else:
                att_host, segs = b"", ()
            self._respond(token, 0, "", response.SerializeToString(),
                          att_host, segs)

        cntl.set_server_done(done)
        try:
            md.invoke(cntl, request, response, done)
        except Exception as e:
            log.error("ici method %s raised: %s", full, e, exc_info=True)
            if not done_called[0]:
                cntl.set_failed(errors.EINTERNAL,
                                f"{type(e).__name__}: {e}")
                done()

    def _peer_endpoint(self, peer_dev: int):
        """Per-request endpoint objects are identical for a given peer —
        cache them (a default-mesh lock + EndPoint construction per
        request measured ~1 us on the handler tier).  EndPoints are pure
        (scheme, device-id) values, so the cache survives mesh swaps."""
        ep = self._peer_eps.get(peer_dev)
        if ep is None:
            from .mesh import IciMesh
            ep = self._peer_eps[peer_dev] = \
                IciMesh.default().endpoint(peer_dev)
        return ep

    def _respond(self, token, err, err_text, payload, att_host, segs):
        p = ctypes.cast(payload, _U8P) if payload else None
        a = ctypes.cast(att_host, _U8P) if att_host else None
        seg_arr = (IciSegC * len(segs))(*segs) if segs else None
        rc = self._lib.brpc_tpu_ici_respond(
            token, err, err_text.encode() if err_text else b"", p,
            len(payload), a, len(att_host), seg_arr, len(segs))
        if rc != 0 and segs:
            # token vanished before custody transferred (server stopping):
            # native never saw the keys, release them here
            release_segs(segs)

    def _respond_err(self, token, err, text):
        self._respond(token, err, text, b"", b"", [])


# ---------------------------------------------------------------------
# channel binding
# ---------------------------------------------------------------------

class ChannelBinding:
    """Client half: one native connection (with its credit window) to the
    in-process native listener at ``remote_dev``."""

    def __init__(self, remote_dev: int, local_dev: Optional[int] = None,
                 window_bytes: int = 0):
        lib = native.load()
        if lib is None or not ensure_hooks():
            raise RuntimeError("native core unavailable")
        from .mesh import IciMesh
        mesh = IciMesh.default()
        if local_dev is None:
            local_dev = (remote_dev + 1) % mesh.size
        self._lib = lib
        self.local_dev = local_dev
        self.remote_dev = remote_dev
        self.window_bytes = window_bytes if window_bytes > 0 else (4 << 20)
        self.remote_side = mesh.endpoint(remote_dev)
        h = lib.brpc_tpu_ici_connect(local_dev, remote_dev, window_bytes)
        if h == 0:
            raise ConnectionRefusedError(
                f"no native listener at ici://{remote_dev}")
        self._handle = h

    def close(self) -> None:
        if self._handle:
            self._lib.brpc_tpu_ici_close(self._handle)
            self._handle = 0

    def __del__(self):                   # noqa: D105 — native conn must not
        try:                             # outlive its Python owner
            self.close()
        except Exception:
            pass

    def window_left(self) -> int:
        return self._lib.brpc_tpu_ici_window_left(self._handle)

    def call(self, full_name: str, cntl, request: Any,
             response_cls: Optional[type] = None):
        """Unary call over the native datapath.  Fills cntl; returns the
        parsed response (or raw payload bytes when response_cls is None)."""
        _fi, scheduler, _t = _hot_modules()
        # fault injection covers the fast plane too, with the SAME
        # semantics as the Python plane's Socket.write boundary: DROP =
        # bytes vanish, the call waits out its deadline; ERROR = the
        # connection is severed (every later call on this binding fails
        # until the channel re-routes/reconnects).
        injector = _fi.active()
        if injector is not None:
            action = injector.decide(self)
            if action == _fi.DROP:
                tms = cntl.timeout_ms
                # no deadline = a genuine hang; bound it so a
                # misconfigured test fails instead of wedging forever
                _time.sleep((tms / 1000.0) if tms and tms > 0 else 60.0)
                cntl.set_failed(errors.ERPCTIMEDOUT
                                if tms and tms > 0 else errors.EFAILEDSOCKET,
                                "rpc timeout (injected drop)")
                return None
            if action == _fi.ERROR:
                cntl.set_failed(errors.EFAILEDSOCKET, "injected fault")
                self.close()             # severed, like Socket.set_failed
                return None
        t0 = _time.monotonic_ns()
        try:
            req = request.SerializeToString()
        except AttributeError:
            req = bytes(request) if request is not None else b""
        if cntl.request_attachment.backing_block_num():
            att_host, segs = split_attachment(cntl.request_attachment)
            dev_bytes = sum(s.nbytes for s in segs if s.is_dev)
        else:
            att_host, segs, dev_bytes = b"", (), 0
        # bytes objects pass by pointer (cast, no copy): the native side
        # never writes through request pointers and copies before returning
        u8p = _U8P
        reqb = ctypes.cast(req, u8p) if req else None
        attb = ctypes.cast(att_host, u8p) if att_host else None
        seg_arr = (IciSegC * len(segs))(*segs) if segs else None
        # one out-block instead of seven byref temporaries: the 17-arg
        # ctypes conversion measured ~3-4 us/call (VERDICT r4 weak #3)
        out = IciCallOut()
        # timeout_ms <= 0 means NO deadline (controller.py:169 semantics);
        # the native side treats timeout_us <= 0 the same way
        tms = cntl.timeout_ms
        timeout_us = int(tms * 1000) if tms is not None and tms > 0 else 0
        # the FFI call can park on a C condvar (Python-tier handler): a
        # tasklet-pool worker must note itself blocked so the scheduler
        # compensates — otherwise handler tasklets starve behind us and
        # the call deadlocks until timeout (review finding r4)
        blocked = scheduler.in_worker()
        if blocked:
            scheduler.note_worker_blocked()
        try:
            rc = self._lib.brpc_tpu_ici_call2(
                self._handle, full_name.encode(), reqb, len(req), attb,
                len(att_host), seg_arr, len(segs), timeout_us,
                ctypes.byref(out))
        finally:
            if blocked:
                scheduler.note_worker_unblocked()
        try:
            cntl.remote_side = self.remote_side
            nsegs = out.nsegs
            if rc != 0:
                # native copies response segs to segs_out even when the
                # handler responded with an error: release their device
                # keys or they strand in the registry forever (the
                # exactly-one-exit custody invariant)
                for i in range(nsegs):
                    if out.segs[i].is_dev and out.segs[i].key:
                        _registry.release(out.segs[i].key)
                text = ctypes.string_at(out.err_text).decode() \
                    if out.err_text else errors.berror(int(rc))
                cntl.set_failed(int(rc), text)
                return None
            payload = ctypes.string_at(out.resp, out.resp_len) \
                if out.resp_len else b""
            if nsegs or out.att_len:
                r_att_host = ctypes.string_at(out.att, out.att_len) \
                    if out.att_len else b""
                rsegs = [IciSegC(out.segs[i].key, out.segs[i].nbytes,
                                 out.segs[i].dev, out.segs[i].is_dev)
                         for i in range(nsegs)]
                cntl.response_attachment.append(
                    build_attachment(r_att_host, rsegs))
            # transport accounting (the Python plane's counters — one
            # fabric-wide truth regardless of datapath)
            with _t._ici_stats_lock:
                _t._ici_bytes_moved += len(req) + len(att_host) + dev_bytes
                _t._ici_device_bytes_moved += dev_bytes
            cntl.error_code_ = 0
            if response_cls is None:
                return payload
            response = response_cls()
            response.ParseFromString(payload)
            cntl.response = response
            return response
        finally:
            cntl.latency_us = (_time.monotonic_ns() - t0) // 1000
            free = self._lib.brpc_tpu_buf_free
            if out.resp:
                free(out.resp)
            if out.att:
                free(out.att)
            if out.segs:
                free(out.segs)
            if out.err_text:
                free(out.err_text)


def native_ici_echo_p50_us(iters: int = 3000, payload: int = 128,
                           device_array=None) -> float:
    """Native-loop ici echo p50 (µs): the C++ client loop over the full
    native ici datapath (window → frame codec → queue hop → dispatch →
    correlation wake).  With ``device_array``, the frame carries that
    array as a device ref (resident = the pure-HBM round trip).  -1 when
    unavailable."""
    lib = native.load()
    if lib is None or not ensure_hooks():
        return -1.0
    key, nbytes, dev = 0, 0, 0
    if device_array is not None:
        key = _registry.put(device_array)    # borrowed for the bench
        nbytes = device_array.nbytes
        dev = _device_index(device_array)
    try:
        ns = lib.brpc_tpu_ici_echo_p50_ns(iters, payload, key, nbytes, dev)
        return ns / 1000.0 if ns > 0 else -1.0
    finally:
        if key:
            _registry.release(key)
