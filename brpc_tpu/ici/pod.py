"""Pod fabric membership: join/leave/epoch over the coordination KV.

The reference's cluster is whatever its naming services return
(src/brpc/policy/*naming_service.cpp); liveness is the health checker's
and circuit breaker's concern, never the registry's.  The pod layer keeps
that division of labor on a TPU pod:

  * **Membership** — every process that joined the pod publishes a member
    record under ``brpc_tpu/pod/<name>/<pid>`` in the jax coordination
    KV (the same store the fabric handshake uses): its owned devices, the
    device ids it is currently SERVING (a Server bound to ``ici://k``),
    the ones draining (lame-duck), and a per-member generation counter
    bumped on every transition.  ``key_value_dir_get`` lists the pod.
  * **Epoch** — the pod epoch is the SUM of member generations: every
    join / advertise / drain / withdraw / rejoin bumps exactly one gen,
    so the epoch strictly increases on every membership transition and
    every process computes the SAME epoch for the same set of records (a
    convergent derived counter — the KV has no atomic increment, and the
    fabric needs agreement, not linearizability).
  * **Liveness** — deliberately NOT here.  A member that crashes cannot
    update its record; its endpoints are discovered dead by the existing
    machinery (connect failures and socket death hand the endpoint to
    rpc/health_check.py, LBs exclude it, breakers gate it) and revived
    the same way.  GOODBYE (PR-4) remains the *proactive* per-socket
    drain signal; the pod record is the *membership* drain signal that
    also reaches processes holding no socket to the drainer.

``pod://<name>`` (policy/naming.py) turns the member table into a server
list — every serving, non-draining device of every up member — so any LB
channel (``Channel.init("pod://default", "rr")``) balances over the pod,
and N per-pair control+bulk planes are established lazily by the existing
``connect_any`` routing on first use, exactly like the 2-process fabric.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..butil import debug_sync as _dbg
from ..butil import flags as _flags
from ..butil import logging as log
from ..butil.endpoint import EndPoint

_flags.define_flag("ici_pod_watch_interval_s", 0.25,
                   "pod membership watch poll period")

_KV_POD_PREFIX = "brpc_tpu/pod/"

UP = "up"
DRAINING = "draining"
DOWN = "down"


class PodMember:
    """One member record as read from the KV (immutable snapshot).
    ``coll`` lists the method names this member registered DEVICE-SIDE
    handlers for (``Server.register_collective``) — the capability half
    of the compiled fan-out handshake: a client only lowers a fan-out
    onto members advertising the method (records written by older
    builds simply advertise none)."""

    __slots__ = ("pid", "gen", "state", "devices", "serving", "draining",
                 "ctrl", "ts", "coll", "load")

    def __init__(self, pid: int, gen: int, state: str,
                 devices: List[int], serving: List[int],
                 draining: List[int], ctrl: str = "", ts: float = 0.0,
                 coll: Optional[List[str]] = None, load: float = 0.0):
        self.pid = pid
        self.gen = gen
        self.state = state
        self.devices = devices
        self.serving = serving
        self.draining = draining
        self.ctrl = ctrl
        self.ts = ts
        self.coll = coll or []
        # published serving load in [0, 1] (telemetry, NOT membership:
        # load changes never bump the gen — the autoscaler polls it,
        # watchers don't fire on it)
        self.load = load

    @classmethod
    def from_json(cls, raw: str) -> "PodMember":
        d = json.loads(raw)
        return cls(d["pid"], d["gen"], d.get("state", UP),
                   d.get("devices", []), d.get("serving", []),
                   d.get("draining", []), d.get("ctrl", ""),
                   d.get("ts", 0.0), d.get("coll", []),
                   d.get("load", 0.0))

    def to_json(self) -> str:
        return json.dumps({
            "pid": self.pid, "gen": self.gen, "state": self.state,
            "devices": self.devices, "serving": self.serving,
            "draining": self.draining, "ctrl": self.ctrl, "ts": self.ts,
            "coll": self.coll, "load": self.load,
        })

    def describe(self) -> dict:
        return {"pid": self.pid, "gen": self.gen, "state": self.state,
                "devices": self.devices, "serving": self.serving,
                "draining": self.draining, "coll": self.coll,
                "load": self.load}


def epoch_of(members: Dict[int, PodMember]) -> int:
    """The convergent pod epoch for a membership snapshot: the sum of
    member generations.  Each transition bumps exactly one gen, so the
    epoch strictly increases across transitions and is identical on
    every process that reads the same records."""
    return sum(m.gen for m in members.values())


class Pod:
    """Per-process pod runtime: the local member record + a membership
    watch.  One pod per process (the FabricNode discipline)."""

    _instance: Optional["Pod"] = None
    _ilock = threading.Lock()

    # fablint guarded-state contract: the local record and the cached
    # membership view are written from the watch thread, server
    # start/stop paths, and user calls
    _GUARDED_BY = {
        "_members": "_lock",
        "_gen": "_lock",
        "_serving": "_lock",
        "_draining_devs": "_lock",
        "_state": "_lock",
        "_watchers": "_lock",
        "_coll": "_lock",
        "_load": "_lock",
        "_autoscaler": "_lock",
    }

    def __init__(self, name: str, node) -> None:
        self.name = name
        self.node = node                    # FabricNode
        self.pid = node.process_id
        self._kv = node._kv
        self._lock = _dbg.make_lock("Pod._lock")
        self._publish_lock = _dbg.make_lock("Pod._publish_lock")
        self._gen = 0
        self._state = DOWN
        self._serving: List[int] = []
        self._draining_devs: List[int] = []
        self._coll: List[str] = []
        self._load = 0.0
        self._autoscaler = None
        self._members: Dict[int, PodMember] = {}
        self._watchers: List[Callable[[Dict[int, PodMember]], None]] = []
        self._stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        import jax
        self._devices = [i for i, d in enumerate(jax.devices())
                         if d.process_index == self.pid]

    # ---- lifecycle -----------------------------------------------------
    @classmethod
    def current(cls) -> Optional["Pod"]:
        with cls._ilock:
            return cls._instance

    @classmethod
    def join(cls, name: str = "default") -> "Pod":
        """Join (or return the already-joined) pod.  Requires a live
        FabricNode — the pod rides the same coordination service the
        fabric handshake publishes through."""
        from .fabric import FabricNode
        node = FabricNode.instance()
        if node is None:
            raise RuntimeError("Pod.join requires FabricNode.initialize "
                               "(the pod lives on the coordination KV)")
        with cls._ilock:
            if cls._instance is not None:
                if cls._instance.name != name:
                    raise RuntimeError(
                        f"process already joined pod "
                        f"{cls._instance.name!r}, cannot join {name!r}")
                return cls._instance
            pod = Pod(name, node)
            cls._instance = pod
        try:
            pod._join()
        except BaseException:
            # a KV hiccup mid-join must not leave a half-joined
            # singleton that every later join() returns as-is
            with cls._ilock:
                if cls._instance is pod:
                    cls._instance = None
            raise
        return pod

    def _join(self) -> None:
        # Resume from a surviving record before bumping: a rejoin after
        # leave() (tombstone) or a supervisor restart with the
        # coordination KV still up must not overwrite a high gen with 1
        # — the epoch is the sum of gens and may NEVER regress, or every
        # peer's wait_epoch convergence primitive times out.
        prior = self._refresh().get(self.pid)
        # collective capability registered BEFORE the join (a server
        # built its method table first) rides the join publish — without
        # this the record says coll=[] forever and remote clients'
        # compiled-fan-out screens silently never engage on this member
        try:
            from ..channels.collective_fanout import registry as _cfreg
            coll = _cfreg().method_names()
        except Exception:
            coll = []
        with self._lock:
            if prior is not None and prior.gen > self._gen:
                self._gen = prior.gen
            self._gen += 1
            self._state = UP
            if coll:
                self._coll = coll
        self._publish()
        self._refresh()
        # fablint: thread-quiesced(leave() sets _stop and joins; the watch loop checks it every poll)
        t = threading.Thread(target=self._watch_loop,
                             name=f"pod_watch:{self.name}", daemon=True)
        self._watch_thread = t
        t.start()
        log.info("pod %s: process %d joined (epoch %d)", self.name,
                 self.pid, self.epoch())

    def leave(self) -> None:
        """Leave the pod: publish state=down (epoch bump) and stop the
        watch thread.  The record stays in the KV as a tombstone so the
        epoch never regresses for the remaining members."""
        with self._lock:
            if self._state == DOWN:
                return
            self._gen += 1
            self._state = DOWN
            self._serving = []
            self._draining_devs = []
        self._publish()
        self._stop.set()
        t = self._watch_thread
        if t is not None and t is not threading.current_thread():
            t.join(2.0)
        with Pod._ilock:
            if Pod._instance is self:
                Pod._instance = None

    # ---- local record --------------------------------------------------
    def _publish(self) -> None:
        # _publish_lock covers snapshot AND KV write: two concurrent
        # transitions (e.g. two servers advertising on one member) must
        # commit their records in snapshot order, or the stale snapshot
        # lands last and a gen bump is lost forever — the epoch would
        # regress for every peer and wait_epoch could never converge.
        # (Each snapshot reads the CURRENT state, so the later committer
        # always carries the newer gen.)  Ordering: _publish_lock is
        # taken before _lock, never the reverse.
        with self._publish_lock:
            with self._lock:
                rec = PodMember(self.pid, self._gen, self._state,
                                list(self._devices), list(self._serving),
                                list(self._draining_devs),
                                ctrl=self.node.ctrl_addr, ts=time.time(),
                                coll=list(self._coll), load=self._load)
            self._kv.key_value_set(self._key(self.pid), rec.to_json(),
                                   allow_overwrite=True)

    def _key(self, pid: int) -> str:
        return f"{_KV_POD_PREFIX}{self.name}/{pid}"

    def advertise(self, device_id: int) -> None:
        """A server came up on ``ici://device_id`` in this process: add
        it to the serving set.  ALWAYS bumps the gen, even when the
        device is already listed — a killed member whose record still
        says "serving" re-advertises on revival, and the bump is what
        lets every watcher observe the rejoin as an epoch transition."""
        with self._lock:
            if device_id not in self._serving:
                self._serving.append(device_id)
            if device_id in self._draining_devs:
                self._draining_devs.remove(device_id)
            self._gen += 1
            self._state = UP
        self._publish()

    def withdraw(self, device_id: int) -> None:
        """The server on ``ici://device_id`` stopped: drop it from the
        serving set (epoch bump).  Idempotent."""
        with self._lock:
            if device_id not in self._serving \
                    and device_id not in self._draining_devs:
                return
            if device_id in self._serving:
                self._serving.remove(device_id)
            if device_id in self._draining_devs:
                self._draining_devs.remove(device_id)
            self._gen += 1
        self._publish()

    def publish_collective(self, methods: List[str]) -> None:
        """Advertise the process's registered device-side handler
        methods (the compiled fan-out capability handshake).  A no-op
        when nothing changed; otherwise a gen bump — peers whose
        collective route degraded on this member re-screen at the epoch
        move, exactly like a serving transition."""
        with self._lock:
            if methods == self._coll:
                return
            self._coll = list(methods)
            self._gen += 1
        self._publish()

    def publish_load(self, load: float) -> None:
        """Publish the member's serving load (``[0, 1]``) into its pod
        record — telemetry for the elastic autoscaler, NOT a membership
        transition: the gen does not bump, the epoch does not move, and
        watchers do not fire.  Peers read it via ``loads()``."""
        load = min(max(float(load), 0.0), 1.0)
        with self._lock:
            if abs(load - self._load) < 1e-9:
                return
            self._load = load
        self._publish()

    def loads(self, refresh: bool = False) -> Dict[int, float]:
        """Every up member's published load — the autoscaler's
        pod-aggregate signal."""
        return {m.pid: m.load for m in
                self.members(refresh=refresh).values()
                if m.state == UP}

    def attach_autoscaler(self, autoscaler) -> None:
        """Register the serving autoscaler driving this member's
        elastic scale decisions; it appears in :meth:`describe` (the
        ``/ici`` pod block) so an operator sees the watermarks and the
        last action next to the membership it mutates."""
        with self._lock:
            self._autoscaler = autoscaler

    def mark_draining(self, device_id: int) -> None:
        """Lame-duck: the server on ``ici://device_id`` began its drain
        window.  The device stays in the record (the member is up) but
        pod:// membership stops listing it — the GOODBYE signal
        generalized to processes holding no socket to the drainer."""
        with self._lock:
            if device_id in self._draining_devs:
                return
            self._draining_devs.append(device_id)
            self._gen += 1
        self._publish()

    # ---- membership view -----------------------------------------------
    def _refresh(self) -> Dict[int, PodMember]:
        """Read every member record from the KV (one dir get)."""
        try:
            pairs = self._kv.key_value_dir_get(
                f"{_KV_POD_PREFIX}{self.name}/")
        except Exception as e:
            log.log_every_n(log.WARNING, 60, "pod %s: dir get failed: %s",
                            self.name, e)
            with self._lock:
                return dict(self._members)
        fresh: Dict[int, PodMember] = {}
        for _key, raw in pairs:
            try:
                m = PodMember.from_json(raw)
            except Exception:
                continue
            fresh[m.pid] = m
        with self._lock:
            self._members = fresh
            return dict(fresh)

    def members(self, refresh: bool = False) -> Dict[int, PodMember]:
        if refresh:
            return self._refresh()
        with self._lock:
            return dict(self._members)

    def epoch(self, refresh: bool = False) -> int:
        return epoch_of(self.members(refresh=refresh))

    def serving_endpoints(self) -> List[Tuple[EndPoint, int]]:
        """(endpoint, owner pid) for every serving, non-draining device
        of every up member — the pod:// naming source."""
        from .mesh import IciMesh
        mesh = IciMesh.default()
        out: List[Tuple[EndPoint, int]] = []
        for m in sorted(self.members().values(), key=lambda m: m.pid):
            if m.state != UP:
                continue
            for dev in m.serving:
                if dev in m.draining:
                    continue
                out.append((mesh.endpoint(dev), m.pid))
        return out

    def wait_epoch(self, at_least: int, timeout: float = 30.0) -> int:
        """Block until the pod epoch reaches ``at_least`` (refreshing),
        returning the epoch observed; raises TimeoutError past the
        deadline.  The N-process tests' convergence primitive."""
        deadline = time.monotonic() + timeout
        while True:
            e = self.epoch(refresh=True)
            if e >= at_least:
                return e
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"pod {self.name}: epoch {e} < {at_least} "
                    f"after {timeout}s")
            time.sleep(0.05)

    def add_watcher(self,
                    fn: Callable[[Dict[int, PodMember]], None]) -> None:
        """``fn(members)`` runs on the watch thread after every observed
        membership change (epoch moved)."""
        with self._lock:
            self._watchers.append(fn)

    # ---- watch loop ----------------------------------------------------
    def _watch_loop(self) -> None:
        last_epoch = -1
        while not self._stop.wait(
                _flags.get_flag("ici_pod_watch_interval_s")):
            members = self._refresh()
            e = epoch_of(members)
            if e == last_epoch:
                continue
            last_epoch = e
            with self._lock:
                watchers = list(self._watchers)
            for fn in watchers:
                try:
                    fn(members)
                except Exception:
                    log.error("pod %s: watcher failed", self.name,
                              exc_info=True)

    # ---- observability -------------------------------------------------
    def describe(self) -> dict:
        members = self.members()
        out = {
            "name": self.name,
            "pid": self.pid,
            "epoch": epoch_of(members),
            "members": [members[p].describe()
                        for p in sorted(members)],
        }
        with self._lock:
            autoscaler = self._autoscaler
        if autoscaler is not None:
            try:
                out["autoscaler"] = autoscaler.describe()
            except Exception:
                pass
        return out


# ---- server lifecycle hooks (rpc/server.py) ----------------------------
# Guarded no-ops when no pod is joined: a plain 2-process fabric (or a
# mem://-only test) never touches the pod layer.

def _pod_and_dev(ep: EndPoint) -> Tuple[Optional["Pod"], int]:
    pod = Pod.current()
    if pod is None or ep.scheme != "ici" or len(ep.coords) != 1:
        return None, -1
    return pod, ep.device_id


def on_server_started(ep: EndPoint) -> None:
    pod, dev = _pod_and_dev(ep)
    if pod is not None:
        pod.advertise(dev)


def on_server_draining(ep: EndPoint) -> None:
    pod, dev = _pod_and_dev(ep)
    if pod is not None:
        pod.mark_draining(dev)


def on_server_stopped(ep: EndPoint) -> None:
    pod, dev = _pod_and_dev(ep)
    if pod is not None:
        pod.withdraw(dev)
