"""ONE plane-health state machine for every data plane (ROADMAP item 1).

Route *selection* was centralized in :mod:`ici.route` (the PR-9 table);
the *robustness* half — degradation, re-probe, revival — stayed smeared
across the planes: the fabric bulk and shm tiers each carried a private
down/revival handshake and revival thread, the device plane a timer
latch, and the collective fan-out its own degrade/reprobe/epoch machine.
The reference delegates exactly this to one place: liveness is the
health-checker's job, never the naming/selection layer's.

This module is that one place.  A plane registers a :class:`PlaneHealth`
record (``register_plane``) and keeps only its MECHANICS — dial,
handshake payloads, teardown, the native alive probe.  The record owns:

  * the state transitions ``UP -> DOWN(reason) -> REESTABLISHING -> UP``
    and the one-transition-one-count discipline behind the unified
    ``rpc_fabric_plane_<name>_{down,reprobe,revived,ramp}`` counter
    family (ici/route.py);
  * the revival policy — exactly one of three, selected by what the
    plane registers:

      ``prober``     threaded revival (fabric bulk/shm): a background
                     loop with exponential backoff + seeded jitter calls
                     the plane's one-attempt prober until the plane's
                     attach path reports :meth:`revived`.  ``kick``
                     decides ``wanted``/``running`` under ONE lock hold,
                     so a kick can never land in the gap where a
                     finishing loop has decided to exit but
                     ``is_alive()`` would still read True — that gap
                     used to suppress revival forever when a freshly
                     attached plane died instantly;
      ``retry_s``    timer latch (device/xfer planes): ``mark_down``
                     arms a re-probe deadline; the first ``usable``
                     after it lapses revives optimistically (the next
                     failure re-latches);
      ``epoch_fn``   epoch gate (collective fan-out): revival when the
                     membership epoch moves past the one recorded at
                     degrade — plus, for ``transient_reasons`` only, a
                     ``reprobe_s`` timer (one bad execution must not
                     degrade the route forever under stable membership);

  * the circuit-breaker ramp: a revival arms ``half_open``; the first
    ``usable`` verdict under real traffic closes it and counts ``ramp``
    — "revived" is claimed by the handshake/timer, "ramped" only by
    actual traffic clearing the gate again.

The record's lock is SUPPLIED by the plane (``lock=``) so the health
flags commute with the plane's own handle swap under one lock — the
fabric socket passes its ``_bulk_lock``, which is what makes the
instant-death suppression above airtight.  ``attached()`` therefore runs
WITH that lock held; every other callback runs outside it.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, Tuple

from ..butil import debug_sync as _dbg
from . import route as _route

UP = "up"
DOWN = "down"
REESTABLISHING = "reestablishing"

# revival channels reported to on_revive: the threaded prober's
# handshake, a lapsed re-probe latch, or the membership epoch moving
VIA_HANDSHAKE = "handshake"
VIA_TIMER = "timer"
VIA_EPOCH = "epoch"


class PlaneHealth:
    """One plane's health record — see the module docstring for the
    split between state (here) and mechanics (the registering plane)."""

    # fablint guarded-state contract: every mutable flag commutes under
    # the plane-supplied lock (for the fabric planes that IS the
    # socket's _bulk_lock / _dplane_lock, so health decisions and the
    # handle swap serialize together).
    _GUARDED_BY = {
        "state": "_lock",
        "reason": "_lock",
        "down_at": "_lock",
        "down_epoch": "_lock",
        "down_until": "_lock",
        "wanted": "_lock",
        "running": "_lock",
        "half_open": "_lock",
        "probe_failures": "_lock",
        "downs": "_lock",
        "revivals": "_lock",
    }

    def __init__(self, name: str, lock, *,
                 probe: Optional[Callable[[int], bool]] = None,
                 gate: Optional[Callable[[], bool]] = None,
                 prober: Optional[Callable[[], bool]] = None,
                 attached: Optional[Callable[[], bool]] = None,
                 dead: Optional[Callable[[], bool]] = None,
                 on_down: Optional[Callable[[str], None]] = None,
                 on_revive: Optional[Callable[[str, str], None]] = None,
                 on_reprobe: Optional[Callable[[], None]] = None,
                 events: Optional[Callable] = None,
                 thread_name: str = "plane_revive",
                 seed: int = 0,
                 backoff_base: float = 0.05, backoff_cap: float = 1.0,
                 retry_s: Optional[Callable[[], float]] = None,
                 epoch_fn: Optional[Callable[[], int]] = None,
                 transient_reasons: Tuple[str, ...] = (),
                 reprobe_s: Optional[Callable[[], float]] = None):
        self.name = name
        self._lock = lock
        self._probe = probe
        self._gate = gate
        self._prober = prober
        self._attached = attached
        self._dead = dead
        self._on_down = on_down
        self._on_revive = on_revive
        self._on_reprobe = on_reprobe
        self._events = events
        self._thread_name = thread_name
        self._seed = seed
        self._backoff_base = backoff_base
        self._backoff_cap = backoff_cap
        self._retry_s = retry_s
        self._epoch_fn = epoch_fn
        self._transient_reasons = tuple(transient_reasons)
        self._reprobe_s = reprobe_s
        self.state = UP
        self.reason = ""
        self.down_at = 0.0
        self.down_epoch = -1
        self.down_until = 0.0        # timer policy: 0 = up
        self.wanted = False          # threaded policy: revival requested
        self.running = False         # threaded policy: one loop is up
        self.half_open = False       # revived, not yet ramped by traffic
        self.probe_failures = 0      # consecutive failed revival probes
        self.downs = 0
        self.revivals = 0

    # ---- degrade -------------------------------------------------------
    def mark_down(self, reason: str) -> bool:
        """Record the DOWN transition.  Returns True when THIS call did
        the transition (counters + callbacks fired); False when the
        plane was already down (the timer policy still re-arms its
        re-probe deadline, matching the old device-plane latch)."""
        now = time.monotonic()
        with self._lock:
            if self._retry_s is not None:
                first = not self.down_until > now
                self.down_until = now + float(self._retry_s())
            else:
                first = self.state == UP
            self.reason = reason
            if not first:
                return False
            self.state = DOWN
            self.down_at = now
            self.half_open = False
            if self._epoch_fn is not None:
                # under the lock, like the machine this replaces: the
                # epoch recorded can never postdate a move that a racing
                # usable() already revived on
                self.down_epoch = self._epoch_fn()
            self.downs += 1
        _route.record_plane(self.name, "down")
        if self._events is not None:
            self._events("degraded", reason)
        if self._on_down is not None:
            self._on_down(reason)
        return True

    # ---- threaded revival (fabric bulk / shm) --------------------------
    def kick(self) -> None:
        """Ensure exactly one revival loop is running.  ``wanted`` and
        ``running`` are decided under ONE lock hold — see the module
        docstring for why that single hold is load-bearing."""
        if self._prober is None:
            return
        if self._gate is not None and not self._gate():
            return
        with self._lock:
            self.wanted = True
            if self.running:
                return           # the live loop will observe `wanted`
            self.running = True
            if self.state != UP:
                self.state = REESTABLISHING
        # fablint: thread-quiesced(self-terminating: exits on attach, plane teardown or peer gone; the owning plane's close path sets its handshake event to unblock a parked prober)
        threading.Thread(target=self._revival_loop,
                         name=self._thread_name, daemon=True).start()

    def _revival_loop(self) -> None:
        rng = random.Random(self._seed)
        delay = self._backoff_base
        while True:
            if self._dead is not None and self._dead():
                with self._lock:
                    self.running = False
                return
            with self._lock:
                if self._attached() or not self.wanted:
                    # attached (or request consumed): exit — atomically
                    # with clearing `running`, so a racing kick either
                    # saw running=True before this point (and set
                    # `wanted`, keeping us looping) or spawns a new loop
                    self.wanted = False
                    self.running = False
                    return
            # backoff BEFORE each attempt (first one included): the
            # plane just died, and frames sent in the gap ride the
            # fallback route anyway — probing in the same instant the
            # peer is tearing down mostly burns a connection
            time.sleep(delay * (1.0 + 0.25 * rng.random()))
            delay = min(delay * 2, self._backoff_cap)
            with self._lock:
                if self._attached():
                    continue            # re-attached while we slept
            if self._dead is not None and self._dead():
                continue                # exit via the top-of-loop path
            _route.record_plane(self.name, "reprobe")
            if not self._prober():
                with self._lock:
                    self.probe_failures += 1
            # on success the plane's attach path called revived(); the
            # top-of-loop check exits (clearing `running` atomically) —
            # or keeps looping if the fresh plane already died and a
            # degrade re-set `wanted` in the meantime

    def revived(self) -> bool:
        """The plane's attach path reports the plane healthy again.
        Counts a revival only when the record was down (an INITIAL
        attach is not a revival) and arms the breaker's half-open ramp
        — the next ``usable`` verdict under real traffic closes it."""
        with self._lock:
            if self.state == UP:
                return False
            reason, self.reason = self.reason, ""
            self.state = UP
            self.down_until = 0.0
            self.probe_failures = 0
            self.half_open = True
            self.revivals += 1
        _route.record_plane(self.name, "revived")
        if self._events is not None:
            self._events("revived", reason)
        if self._on_revive is not None:
            self._on_revive(reason, VIA_HANDSHAKE)
        return True

    # ---- the route table's gate ----------------------------------------
    def usable(self, nbytes: int = 0) -> bool:
        """Gate one use of the plane (``route.candidates`` consults
        exactly this).  UP runs the plane's own capability probe;
        DOWN consults the revival policy; a threaded-revival plane
        stays unusable until its prober's attach lands."""
        with self._lock:
            state = self.state
            ramp = state == UP and self.half_open
            if ramp:
                self.half_open = False
        if ramp:
            _route.record_plane(self.name, "ramp")
        if state != UP:
            if self._prober is not None:
                return False     # the revival loop owns the comeback
            if self._retry_s is not None:
                if not self._lapse():
                    return False
            elif self._epoch_fn is not None:
                if not self._epoch_revive():
                    return False
            else:
                return False
        return self._probe(nbytes) if self._probe is not None else True

    def _lapse(self) -> bool:
        """Timer policy: revive when the re-probe deadline lapsed —
        optimistic, the next failure re-latches."""
        with self._lock:
            if self.state == UP:
                return True
            if self.down_until and time.monotonic() < self.down_until:
                return False
            reason, self.reason = self.reason, ""
            self.state = UP
            self.down_until = 0.0
            self.probe_failures = 0
            self.half_open = True
            self.revivals += 1
        _route.record_plane(self.name, "reprobe")
        _route.record_plane(self.name, "revived")
        if self._on_reprobe is not None:
            self._on_reprobe()
        if self._events is not None:
            self._events("revived", reason)
        if self._on_revive is not None:
            self._on_revive(reason, VIA_TIMER)
        return True

    def _epoch_revive(self) -> bool:
        """Epoch policy: healthy, or down-but-revivable — the epoch
        moved (a member re-advertised), or, for TRANSIENT reasons only,
        the reprobe window elapsed.  Without the timer one bad
        execution would degrade the route forever under stable
        membership; membership reasons stay epoch-gated (a dead member
        does not resurrect by waiting)."""
        with self._lock:
            if self.state == UP:
                return True
            down_epoch = self.down_epoch
            transient_expired = (
                self.reason in self._transient_reasons
                and self._reprobe_s is not None
                and time.monotonic() - self.down_at
                >= float(self._reprobe_s()))
        if not transient_expired and self._epoch_fn() <= down_epoch:
            return False
        with self._lock:
            if self.state == UP:
                return True
            reason, self.reason = self.reason, ""
            self.state = UP
            self.probe_failures = 0
            self.half_open = True
            self.revivals += 1
        _route.record_plane(self.name, "reprobe")
        _route.record_plane(self.name, "revived")
        if self._events is not None:
            self._events("revived", reason)
        if self._on_revive is not None:
            self._on_revive(reason,
                            VIA_TIMER if transient_expired else VIA_EPOCH)
        return True

    # ---- observability -------------------------------------------------
    def snapshot(self) -> dict:
        """The /ici ``planes`` block's per-plane row: state, reason,
        the epoch recorded at degrade, seconds until the next re-probe
        (timer policies), and the lifetime transition tallies."""
        now = time.monotonic()
        with self._lock:
            out = {"state": self.state, "reason": self.reason,
                   "down_epoch": self.down_epoch,
                   "downs": self.downs, "revivals": self.revivals,
                   "probe_failures": self.probe_failures,
                   "half_open": self.half_open}
            if self.down_until:
                out["reprobe_in"] = round(
                    max(0.0, self.down_until - now), 3)
            elif (self.state != UP and self._reprobe_s is not None
                    and self.reason in self._transient_reasons):
                out["reprobe_in"] = round(max(
                    0.0, self.down_at + float(self._reprobe_s()) - now), 3)
        return out


def register_plane(name: str, lock=None, **policy) -> PlaneHealth:
    """Register one plane with the shared engine: returns its
    :class:`PlaneHealth` record.  ``lock`` is the plane's own guard
    (defaulted to a fresh debug-tracked lock); ``policy`` is the
    keyword surface of :class:`PlaneHealth` — exactly one of
    ``prober``/``retry_s``/``epoch_fn`` selects the revival policy,
    ``probe``/``gate`` wire the capability checks, and the ``on_*`` /
    ``events`` hooks keep logs and legacy counter families with the
    registering plane."""
    if lock is None:
        lock = _dbg.make_lock(f"plane_health.{name}")
    return PlaneHealth(name, lock, **policy)
