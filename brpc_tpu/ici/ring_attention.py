"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The task's long-context mandate, built directly on the fabric's collective
substrate (SURVEY.md §5.7 maps the reference's sliding-window streaming to
exactly this machinery):

  * ``ring_attention`` — K/V shards rotate around the ring (one ppermute
    per step, the RingStream pattern fused into the kernel's math) while
    every device keeps a numerically-stable running softmax over its local
    Q block (flash-attention style m/l accumulators).  Sequence length
    scales with mesh size; peak memory per chip stays O(seq/n).
  * ``ulysses_attention`` — the all-to-all alternative: reshard from
    sequence-sharded to head-sharded (one all_to_all), run plain attention
    per head group, reshard back.  Better when heads ≥ devices and ICI
    all-to-all bandwidth is plentiful.

Both compile to ONE XLA program via shard_map and are verified against the
dense reference in tests on the 8-device CPU mesh.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from .mesh import IciMesh

_cache: Dict[Tuple, Callable] = {}
_lock = threading.Lock()


def _cached(key, builder):
    with _lock:
        fn = _cache.get(key)
        if fn is None:
            fn = builder()
            _cache[key] = fn
        return fn


def ring_attention(q, k, v, mesh: Optional[IciMesh] = None, causal: bool = False):
    """Blockwise ring attention.

    q, k, v: (n, block, heads, dim) — sequence sharded over the mesh axis
    (row i = tokens [i*block, (i+1)*block)).  Returns attention output with
    the same layout.  ``causal=True`` masks by absolute token position.
    """
    mesh = mesh or IciMesh.default()
    key = ("ring_attn", tuple(q.shape), str(q.dtype), causal, mesh.size)
    fn = _cached(key, lambda: _build_ring_attention(
        mesh, tuple(q.shape[1:]), q.dtype, causal))
    return fn(q, k, v)


def _build_ring_attention(mesh: IciMesh, block_shape, dtype, causal: bool):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ..butil.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.size
    ax = mesh.axis_name
    perm = [(i, (i + 1) % n) for i in range(n)]
    block, heads, dim = block_shape
    scale = dim ** -0.5

    def local_block(q_blk, k_blk, v_blk, q_pos, k_pos):
        """One (Q-block × K-block) panel with running-softmax stats.
        q_blk: (B, H, D); returns (scores_exp@v, row_max, row_sum)."""
        # (H, B, B) logits
        s = jnp.einsum("qhd,khd->hqk", q_blk, k_blk) * scale
        if causal:
            mask = (q_pos[None, :, None] >= k_pos[None, None, :])
            s = jnp.where(mask, s, -jnp.inf)
        m = jnp.max(s, axis=-1)                        # (H, B)
        # guard fully-masked rows
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        l = jnp.sum(p, axis=-1)                        # (H, B)
        o = jnp.einsum("hqk,khd->qhd", p, v_blk)       # (B, H, D)
        return o, m_safe, l, jnp.isfinite(m)

    def body(q_l, k_l, v_l):
        # locals arrive as (1, B, H, D)
        q_blk = q_l[0]
        my_id = lax.axis_index(ax)
        q_pos = my_id * block + jnp.arange(block)

        def step(carry, step_idx):
            k_cur, v_cur, o_acc, m_acc, l_acc = carry
            src_dev = lax.rem(my_id - step_idx + n, n)  # owner of current k/v
            k_pos = src_dev * block + jnp.arange(block)
            o_new, m_new, l_new, any_valid = local_block(
                q_blk, k_cur[0], v_cur[0], q_pos, k_pos)
            # merge running softmax (flash-attention accumulator update)
            m_next = jnp.maximum(m_acc, m_new)
            alpha = jnp.exp(m_acc - m_next)
            beta = jnp.exp(m_new - m_next)
            # rows with no valid entries in this panel contribute nothing
            beta = jnp.where(any_valid, beta, 0.0)
            l_next = l_acc * alpha + l_new * beta
            o_next = (o_acc * alpha.T[:, :, None]
                      + o_new * beta.T[:, :, None])
            # rotate k/v one hop for the next step
            k_rot = lax.ppermute(k_cur, ax, perm)
            v_rot = lax.ppermute(v_cur, ax, perm)
            return (k_rot, v_rot, o_next, m_next, l_next), None

        o0 = jnp.zeros((block, heads, dim), jnp.float32)
        m0 = jnp.full((heads, block), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((heads, block), jnp.float32)
        (k_f, v_f, o_acc, m_acc, l_acc), _ = lax.scan(
            step, (k_l.astype(jnp.float32), v_l.astype(jnp.float32),
                   o0, m0, l0),
            jnp.arange(n))
        out = o_acc / jnp.maximum(l_acc.T[:, :, None], 1e-20)
        return out.astype(dtype)[None]

    return jax.jit(shard_map(
        body, mesh=mesh.mesh, in_specs=(P(ax), P(ax), P(ax)),
        out_specs=P(ax), check_vma=False))


def ulysses_attention(q, k, v, mesh: Optional[IciMesh] = None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses shape):
    q,k,v (n, block, heads, dim) sequence-sharded, heads divisible by n.
    Reshard to head-sharded full-sequence, attend, reshard back."""
    mesh = mesh or IciMesh.default()
    key = ("ulysses", tuple(q.shape), str(q.dtype), mesh.size)
    fn = _cached(key, lambda: _build_ulysses(mesh, tuple(q.shape[1:]),
                                             q.dtype))
    return fn(q, k, v)


def _build_ulysses(mesh: IciMesh, block_shape, dtype):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ..butil.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.size
    ax = mesh.axis_name
    block, heads, dim = block_shape
    assert heads % n == 0, "ulysses needs heads % devices == 0"
    hpg = heads // n
    scale = dim ** -0.5

    def reshard_to_heads(x_l):
        # local (1, B, H, D) → (1, n*B, H/n, D): all_to_all over head groups
        x = x_l[0].reshape(block, n, hpg, dim)          # (B, n, hpg, D)
        x = jnp.moveaxis(x, 1, 0)                        # (n, B, hpg, D)
        g = lax.all_to_all(x, ax, split_axis=0, concat_axis=0, tiled=True)
        return g.reshape(n * block, hpg, dim)            # full seq, my heads

    def reshard_to_seq(y):
        # (n*B, hpg, D) → back to (1, B, H, D)
        y = y.reshape(n, block, hpg, dim)
        y = lax.all_to_all(y, ax, split_axis=0, concat_axis=0, tiled=True)
        # y now: (n, B, hpg, D) where axis0 = head groups
        y = jnp.moveaxis(y, 0, 1)                        # (B, n, hpg, D)
        return y.reshape(block, heads, dim)[None]

    def body(q_l, k_l, v_l):
        qh = reshard_to_heads(q_l).astype(jnp.float32)
        kh = reshard_to_heads(k_l).astype(jnp.float32)
        vh = reshard_to_heads(v_l).astype(jnp.float32)
        s = jnp.einsum("qhd,khd->hqk", qh, kh) * scale
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("hqk,khd->qhd", p, vh)
        return reshard_to_seq(o.astype(dtype))

    return jax.jit(shard_map(
        body, mesh=mesh.mesh, in_specs=(P(ax), P(ax), P(ax)),
        out_specs=P(ax), check_vma=False))


def reference_attention(q, k, v, causal: bool = False):
    """Dense single-device reference for testing: q,k,v (S, H, D)."""
    import jax.numpy as jnp
    import jax
    S = q.shape[0]
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32)).astype(q.dtype)
