"""Ring pipelines: chained Send/Recv with credit flow control.

SURVEY.md §5.7: the reference's closest thing to sequence parallelism is
Streaming RPC's sliding window (stream.cpp:274,307) and RDMA's explicit-ACK
window (rdma_endpoint.cpp) — ordered chunk pipelines with credits.  Here
that machinery becomes what ring/context-parallel patterns are made of:

  * ``ring_all_reduce`` — the classic 2(n−1)-hop ring expressed as a
    ``lax.scan`` of ``ppermute`` (reduce-scatter phase + all-gather phase),
    compiled to ONE XLA program whose steady state keeps every ICI link busy
    both directions of the scan.  This is the rdma_performance analogue.
  * ``RingStream`` — host-paced chunk pipeline: a large device payload moves
    hop-by-hop as fixed-size chunks with a sliding credit window; receiver
    consumption returns credits (the StreamingRPC feedback loop), device
    completion observed through the device waiter.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

from ..bthread.device_waiter import DeviceEventDispatcher
from .mesh import IciMesh
from .collective import Collectives


def ring_all_reduce(x, mesh: Optional[IciMesh] = None):
    """All-reduce (sum) of a (n, chunk...) sharded array via explicit ring
    hops.  Equivalent to ``Collectives.all_reduce`` but lowered as 2(n−1)
    chained ppermutes — the chained-Send/Recv benchmark path.  Returns the
    summed value replicated as (n, chunk...) rows (row i = full sum of
    chunk i's shards … i.e. a reduce-scatter + all-gather pipeline)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from ..butil.jax_compat import shard_map

    mesh = mesh or IciMesh.default()
    n = mesh.size
    ax = mesh.axis_name
    if n == 1:
        return x

    perm = [(i, (i + 1) % n) for i in range(n)]

    def program(xs):                      # xs: (1, ...) local shard
        chunk = xs[0]

        def rs_step(carry, _):
            acc = jax.lax.ppermute(carry, ax, perm)
            return acc + chunk, None

        # reduce-scatter phase: after n-1 hops every device holds the sum
        acc, _ = jax.lax.scan(rs_step, chunk, None, length=n - 1)
        return acc[None]

    fn = jax.jit(shard_map(program, mesh=mesh.mesh, in_specs=P(ax),
                           out_specs=P(ax), check_vma=False))
    return fn(x)


class RingStream:
    """Sliding-window chunk pipeline between ring neighbors.

    Sender pushes chunks (device arrays); each chunk advances one hop per
    tick via ppermute; the receiver's ``on_chunk`` consumes it and returns a
    credit.  ``window`` bounds in-flight chunks exactly like the reference
    stream's ``_produced - _remote_consumed < window`` check
    (stream.cpp:274); device completion is the delivery signal.
    """

    def __init__(self, hops: int = 1, window: int = 4,
                 mesh: Optional[IciMesh] = None,
                 on_chunk: Optional[Callable] = None):
        self.mesh = mesh or IciMesh.default()
        self.coll = Collectives(self.mesh)
        self.hops = hops
        self.window = window
        self.on_chunk = on_chunk
        # window accounting: produced - consumed < window, one condition
        # guards both (the stream.cpp:274 check, host-side pacing only)
        self._cv = threading.Condition()
        self._produced = 0
        self._consumed = 0

    def write(self, chunk, timeout: float = 30.0) -> bool:
        """Send one chunk ((n, ...) sharded row layout); blocks while the
        window is exhausted (AppendIfNotFull semantics)."""
        import time
        from ..bthread import scheduler
        deadline = time.monotonic() + timeout
        # check-and-RESERVE under one lock (the stream.cpp:274
        # AppendIfNotFull discipline): two concurrent writers must not
        # both pass the check before either counts itself, or the window
        # overshoots and a racing flush() reports drained while a chunk
        # is mid-dispatch (ADVICE r2 finding, fixed r4)
        with self._cv:
            while self._produced - self._consumed >= self.window:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                scheduler.note_worker_blocked()
                try:
                    self._cv.wait(left)
                finally:
                    scheduler.note_worker_unblocked()
            self._produced += 1          # reservation
        try:
            moved = chunk
            for _ in range(self.hops):
                moved = self.coll.ppermute(moved, 1)
            DeviceEventDispatcher.instance().on_ready(
                moved, lambda m=moved: self._delivered(m))
        except BaseException:
            # failed dispatch returns its reserved credit and wakes both
            # blocked writers and flush()ers
            with self._cv:
                self._produced -= 1
                self._cv.notify_all()
            raise
        return True

    def _delivered(self, chunk) -> None:
        try:
            if self.on_chunk is not None:
                self.on_chunk(chunk)
        finally:
            # feedback: credit returns to the sender (SendFeedback
            # analogue) and flush()ers see consumption progress
            with self._cv:
                self._consumed += 1
                self._cv.notify_all()

    def flush(self, timeout: float = 60.0) -> bool:
        """Wait until every produced chunk was consumed (no busy-poll:
        rides the same condition as the window credits)."""
        from ..bthread import scheduler
        with self._cv:
            scheduler.note_worker_blocked()
            try:
                return self._cv.wait_for(
                    lambda: self._consumed >= self._produced, timeout)
            finally:
                scheduler.note_worker_unblocked()

    @property
    def in_flight(self) -> int:
        with self._cv:
            return self._produced - self._consumed
