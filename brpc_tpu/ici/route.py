"""Bulk payload routing — ONE table deciding which data plane carries a
payload: payload class × size × peer capability × plane health.

Before this module the selection logic was smeared across call sites
(``_encode_data``'s host flush, its device-chunk ladder, and
``stream.py``'s threshold check each re-derived eligibility).  The
table centralizes the *ordering* decision and the per-route counters;
the *mechanics* (send, claim, degrade, revive) stay with each plane.
This is the scoped seam toward ROADMAP item 5's unified payload router:
a new plane is added by teaching ``candidates`` one clause, not by
touching every encode site.

Routes, fastest first for same-host pairs:

  shm     mmap'd ring segment (``native/fabric.cpp`` nshm): one sender
          copy into shared memory, ZERO receiver copies, no syscalls on
          the byte path — the third bulk tier
  bulk    the dedicated per-pair socket conn (UDS same-host / TCP
          cross-host): syscall + kernel copy each way
  xfer    jax transfer-server pull (device payloads; on TPU pods the
          premapped HBM DMA path)
  inline  bytes ride the control channel frame itself

The sequenced device plane (kind 4) is NOT a row here: it is an SPMD
program both processes enter, not a byte mover, and is consulted before
this table by ``_encode_data``.

Per-route observability: ``rpc_fabric_route_<route>_frames`` /
``_bytes`` Adders, where the ``bulk`` row splits into ``uds``/``tcp``
by how the socket's bulk conn was actually dialed.
"""
from __future__ import annotations

from typing import List

from ..butil import debug_sync as _dbg
from ..butil import flags as _flags

SHM = "shm"
BULK = "bulk"
XFER = "xfer"
INLINE = "inline"

# payload classes (thresholds differ; preserved from the pre-table code)
HOST = "host"          # joined host byte blobs (kind 0/3/6)
DEVICE = "device"      # device-array payloads (kind 1/2/5)
STREAM = "stream"      # stream DATA frames (FRAME_DATA_BULK/_SHM)

# label -> (frames Adder, bytes Adder).  Publish-only dict: entries are
# created exactly once under _counters_lock, READS are lock-free
# (dict.get is GIL-atomic and nothing is ever removed or replaced) —
# the PR-8 device-ref-registry discipline, because record() sits on the
# per-frame fast path.
_counters_lock = _dbg.make_lock("ici.route._counters_lock")
_counters = {}


def candidates(sock, cls: str, nbytes: int) -> List[str]:
    """Ordered candidate routes for one payload on ``sock``.  The caller
    tries them in order; a route that fails mid-frame degrades its plane
    and falls through to the next — nothing is committed to the control
    stream until a route accepted the bytes.

    Small payloads skip the descriptor planes entirely (below the
    class threshold the descriptor + claim round trip costs more than
    the inline copy); oversized-for-the-ring payloads skip shm without
    degrading it."""
    if cls == HOST:
        if nbytes < _flags.get_flag("ici_fabric_bulk_host_min"):
            return [INLINE]
    elif cls == STREAM:
        if nbytes < _flags.get_flag("ici_stream_bulk_threshold"):
            return [INLINE]
    out: List[str] = []
    if sock.plane_usable(SHM, nbytes):
        out.append(SHM)
    if sock.plane_usable(BULK, nbytes):
        out.append(BULK)
    if cls == DEVICE and sock.plane_usable(XFER, nbytes):
        out.append(XFER)
    out.append(INLINE)
    return out


def _counter_pair(label: str):
    """(frames, bytes) Adder pair for ``label`` — the publish-once /
    read-lock-free discipline in ONE place (dict.get is GIL-atomic and
    entries are only ever added; the module lock guards creation)."""
    pair = _counters.get(label)
    if pair is None:
        with _counters_lock:
            pair = _counters.get(label)
            if pair is None:
                from .. import bvar
                pair = _counters[label] = (
                    bvar.Adder(name=f"rpc_fabric_route_{label}_frames"),
                    bvar.Adder(name=f"rpc_fabric_route_{label}_bytes"))
    return pair


def record(sock, route: str, nbytes: int, frames: int = 1) -> None:
    """Count ``frames`` frame(s) on ``route``; the ``bulk`` row is
    labeled by the transport the socket's bulk conn actually uses
    (uds/tcp).  This sits on the per-frame fast path — see
    _counter_pair for the lock discipline."""
    if route == BULK:
        label = "uds" if getattr(sock, "_bulk_is_uds", False) else "tcp"
    else:
        label = route
    pair = _counter_pair(label)
    pair[0] << frames
    pair[1] << nbytes


def record_shm_stripe(stripe: int, nbytes: int, frames: int = 1) -> None:
    """Per-stripe shm accounting (``rpc_fabric_route_shm_stripe_<i>_
    frames/bytes``) — the route-assertion surface for the striped
    plane: a striped transfer is proven striped by these counters, not
    assumed.  Only the striped path records here (1-stripe planes keep
    the plain ``shm`` row, byte-identical to PR 10)."""
    pair = _counter_pair(f"shm_stripe_{stripe}")
    pair[0] << frames
    pair[1] << nbytes


def route_stats() -> dict:
    """Snapshot {label: {frames, bytes}} for /ici and the tools."""
    with _counters_lock:
        items = list(_counters.items())
    return {label: {"frames": f.get_value(), "bytes": b.get_value()}
            for label, (f, b) in items}


# ---- the unified plane-health event family (ici/plane_health.py) -------
#
# One taxonomy for EVERY data plane's health transitions:
# ``rpc_fabric_plane_<name>_<event>`` where event is ``down`` (UP ->
# DOWN, counted once per transition), ``reprobe`` (one revival attempt
# — a prober dial or a lapsed timer latch), ``revived`` (back UP), and
# ``ramp`` (the breaker's half-open gate cleared by real traffic after
# a revival).  Emitted ONLY by the PlaneHealth engine, so /vars shows
# the same four verbs for bulk, shm, device, xfer, and collective.
# Same publish-once/read-lock-free discipline as _counter_pair.

_plane_events = {}


def record_plane(name: str, event: str, n: int = 1) -> None:
    """Count one plane-health event (``down``/``reprobe``/``revived``/
    ``ramp``) for plane ``name``."""
    label = f"{name}_{event}"
    adder = _plane_events.get(label)
    if adder is None:
        with _counters_lock:
            adder = _plane_events.get(label)
            if adder is None:
                from .. import bvar
                adder = _plane_events[label] = bvar.Adder(
                    name=f"rpc_fabric_plane_{label}")
    adder << n


def plane_stats() -> dict:
    """Snapshot {``<plane>_<event>``: count} for /ici's ``planes``
    block and the chaos-matrix assertions."""
    with _counters_lock:
        items = list(_plane_events.items())
    return {label: a.get_value() for label, a in items}


# ---- the COLLECTIVE route (channels/collective_fanout.py) --------------
#
# Not a byte mover, so it is not a row in candidates(): a compiled
# fan-out is an SPMD program every participant enters, selected by the
# plane's own screen BEFORE any per-member RPC is issued.  What the
# table owns is its observability — the selected/degraded/revived
# event counters (per degrade reason), same publish-once/read-lock-free
# discipline as the byte-route pair above.  Event Adders are named
# ``rpc_fabric_route_collective_<event>[_<reason>]`` so they surface in
# /vars alongside the byte-route counters.

_events = {}


def record_collective(event: str, reason: str = "", n: int = 1) -> None:
    """Count one collective-route event (``selected``, ``degraded``,
    ``revived``, ``ineligible``, ``member_entries``, ...) with an
    optional reason suffix."""
    label = f"collective_{event}" + (f"_{reason}" if reason else "")
    adder = _events.get(label)
    if adder is None:
        with _counters_lock:
            adder = _events.get(label)
            if adder is None:
                from .. import bvar
                adder = _events[label] = bvar.Adder(
                    name=f"rpc_fabric_route_{label}")
    adder << n


def collective_stats() -> dict:
    """Snapshot {event_label: count} for /ici, bench extra, and the
    tools' route assertions."""
    with _counters_lock:
        items = list(_events.items())
    return {label: a.get_value() for label, a in items}
