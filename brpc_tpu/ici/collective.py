"""Mesh collectives: the lowering target for combo channels.

SURVEY.md §2.6: the reference's ParallelChannel broadcast/scatter + merge is
re-expressed here as XLA collectives over the ICI mesh — psum/all_gather/
reduce_scatter/ppermute compiled once per (op, shape, dtype) via shard_map
and cached.  These are *scheduled* device programs, not per-socket writes:
every mesh participant enters the same program (the SPMD ordering constraint
called out in SURVEY.md §7 "hard parts"), which is why combo-channel calls
compile to ONE program instead of N point-to-point sockets.

All functions take/return global ``jax.Array``s sharded over the mesh axis
(leading dimension = mesh size unless noted).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from .mesh import IciMesh


class Collectives:
    def __init__(self, mesh: Optional[IciMesh] = None):
        self.mesh = mesh or IciMesh.default()
        self._cache: Dict[Tuple, Callable] = {}
        self._building: Dict[Tuple, threading.Event] = {}
        self._cache_lock = threading.Lock()

    # -- plumbing --------------------------------------------------------
    def _cached(self, key: Tuple, builder: Callable[[], Callable]) -> Callable:
        """Compile-or-fetch with the build OUTSIDE the cache lock: an
        XLA compile can take seconds, and holding ``_cache_lock`` across
        it blocked every OTHER key's lookup for the duration (ISSUE 11
        satellite bugfix; the once-guard idiom lives in
        butil/once_cache.py, shared with the fan-out plane's cache)."""
        from ..butil.once_cache import build_once
        return build_once(self._cache_lock, self._cache, self._building,
                          key, builder)

    def _shard_map(self, fn, in_spec, out_spec):
        import jax
        from jax.sharding import PartitionSpec as P
        from ..butil.jax_compat import shard_map
        return jax.jit(shard_map(
            fn, mesh=self.mesh.mesh, in_specs=in_spec, out_specs=out_spec,
            check_vma=False))

    def shard(self, x):
        """Place a (mesh_size, ...) array with one row per device."""
        import jax
        from jax.sharding import PartitionSpec as P
        return jax.device_put(
            x, jax.sharding.NamedSharding(self.mesh.mesh,
                                          P(self.mesh.axis_name)))

    def replicate(self, x):
        import jax
        from jax.sharding import PartitionSpec as P
        return jax.device_put(
            x, jax.sharding.NamedSharding(self.mesh.mesh, P()))

    # -- collectives -----------------------------------------------------
    def all_reduce(self, x):
        """Sum over the mesh axis; in: (n, ...) sharded, out: (...) summed,
        replicated (ParallelChannel response-merge as a reduction)."""
        import jax
        from jax.sharding import PartitionSpec as P
        ax = self.mesh.axis_name
        key = ("all_reduce", x.shape, str(x.dtype))

        def build():
            def f(xs):                      # xs: (1, ...) local shard
                return jax.lax.psum(xs[0], ax)
            return self._shard_map(f, P(ax), P())
        return self._cached(key, build)(x)

    def all_gather(self, x):
        """in: (n, ...) sharded → out: (n, ...) fully replicated (every
        device sees every response)."""
        import jax
        from jax.sharding import PartitionSpec as P
        ax = self.mesh.axis_name
        key = ("all_gather", x.shape, str(x.dtype))

        def build():
            def f(xs):
                return jax.lax.all_gather(xs[0], ax)
            return self._shard_map(f, P(ax), P())
        return self._cached(key, build)(x)

    def reduce_scatter(self, x):
        """in: (n, n, ...) sharded on dim0 → out: (n, ...) sharded: device d
        gets sum_s x[s, d] (gradient-bucket exchange)."""
        import jax
        from jax.sharding import PartitionSpec as P
        ax = self.mesh.axis_name
        key = ("reduce_scatter", x.shape, str(x.dtype))

        def build():
            def f(xs):                      # xs: (1, n, ...)
                return jax.lax.psum_scatter(
                    xs[0], ax, scatter_dimension=0, tiled=True)[None]
            return self._shard_map(f, P(ax), P(ax))
        return self._cached(key, build)(x)

    def ppermute(self, x, shift: int = 1):
        """Rotate shards around the ring by ``shift`` hops (the chained
        Send/Recv primitive; streaming/sequence pipelines build on this)."""
        import jax
        from jax.sharding import PartitionSpec as P
        ax = self.mesh.axis_name
        n = self.mesh.size
        perm = [(i, (i + shift) % n) for i in range(n)]
        key = ("ppermute", x.shape, str(x.dtype), shift)

        def build():
            def f(xs):
                return jax.lax.ppermute(xs, ax, perm)
            return self._shard_map(f, P(ax), P(ax))
        return self._cached(key, build)(x)

    def broadcast(self, x, root: int = 0):
        """Replicate device ``root``'s row to all devices
        (ParallelChannel request replication)."""
        import jax
        from jax.sharding import PartitionSpec as P
        ax = self.mesh.axis_name
        key = ("broadcast", x.shape, str(x.dtype), root)

        def build():
            def f(xs):                      # (1, ...) local
                g = jax.lax.all_gather(xs[0], ax)   # (n, ...)
                return g[root]
            return self._shard_map(f, P(ax), P())
        return self._cached(key, build)(x)

    def all_to_all(self, x):
        """in: (n, n, ...) sharded dim0 — row s holds what s sends to every
        d → out: (n, n, ...) sharded: row d holds what every s sent to d
        (PartitionChannel resharding)."""
        import jax
        from jax.sharding import PartitionSpec as P
        ax = self.mesh.axis_name
        key = ("all_to_all", x.shape, str(x.dtype))

        def build():
            def f(xs):                      # (1, n, ...) local row
                return jax.lax.all_to_all(xs, ax, split_axis=1,
                                          concat_axis=1, tiled=True)
            return self._shard_map(f, P(ax), P(ax))
        return self._cached(key, build)(x)


_default_collectives: Optional[Collectives] = None
_default_lock = threading.Lock()


def default_collectives() -> Collectives:
    global _default_collectives
    with _default_lock:
        if _default_collectives is None:
            _default_collectives = Collectives()
        return _default_collectives
