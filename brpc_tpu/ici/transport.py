"""ici:// transport: RPC frames between device endpoints, payloads in HBM.

This is the analogue of the reference's RDMA transport (SURVEY.md §3.5,
src/brpc/rdma/rdma_endpoint.cpp): where RdmaEndpoint posts zero-copy SGEs
from registered IOBuf blocks and completions arrive via CQ events, the ici
transport moves IOBuf *device blocks* between chips with XLA transfers and
completions arrive via device-stream readiness (bthread.device_waiter — the
CQ/EventDispatcher analogue).

Wire model (single-controller JAX):
  * An IciSocket connects two endpoints ``ici://a`` ↔ ``ici://b``.
  * ``write(iobuf)`` splits the buffer into the host-byte stream (protocol
    frames/meta — small) and its DEVICE block refs (bulk payload).  Host
    bytes are handed to the peer directly; device blocks are relocated to
    the peer's device with ``jax.device_put`` — on TPU hardware this is a
    direct HBM→HBM ICI transfer that never touches the host.  The delivered
    IOBuf has the same layout with device refs now resident on the target
    chip.
  * Delivery order per socket is preserved by a per-socket ExecutionQueue;
    the payload transfer is awaited through DeviceEventDispatcher before
    the peer's input path runs — "read event fires when the data is in
    local HBM", exactly the RDMA completion contract.

In a future multi-controller deployment the relocation step becomes paired
XLA Send/Recv (the handshake already exchanges device ids, mirroring the
reference's GID/QPN TCP handshake rdma_endpoint.h:37); everything above
Socket is unaffected.  Collectives (combo-channel lowering) do NOT go
through point-to-point sockets — see collective.py.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

from ..butil.endpoint import EndPoint
from ..butil import flags as _flags
from ..butil import debug_sync as _dbg
from ..butil.iobuf import IOBuf, IOPortal, DEVICE
from ..bthread.butex import Butex
from ..bthread.device_waiter import DeviceEventDispatcher
from ..rpc import errors
from ..rpc.socket import Socket
from .mesh import IciMesh

_ici_stats_lock = _dbg.make_lock("ici.transport._ici_stats_lock")
_ici_bytes_moved = 0
_ici_device_bytes_moved = 0

# fablint guarded-state contract for the module-level registries
_GUARDED_BY_GLOBALS = {
    "_ici_bytes_moved": "_ici_stats_lock",
    "_ici_device_bytes_moved": "_ici_stats_lock",
    "_listeners": "_listeners_lock",
}

# Transport-level sliding window (reference: the RDMA explicit-ACK window,
# rdma_endpoint.cpp:771 CutFromIOBufList checks _window_size before posting;
# credits return piggybacked on completions).  A writer may have at most
# this many un-CONSUMED bytes at the peer; beyond it _do_write reports
# not-writable and the KeepWrite tasklet blocks until the reader drains.
# This bounds the peer inbox (a slow reader exerts backpressure instead of
# growing memory) — the flow-control VERDICT.md item #3.
_flags.define_flag("ici_socket_window_bytes", 4 * 1024 * 1024,
                   "per-ici-socket send window (unconsumed bytes at peer)",
                   _flags.positive_integer)


def ici_transport_stats() -> Tuple[int, int]:
    with _ici_stats_lock:
        return _ici_bytes_moved, _ici_device_bytes_moved


class CreditWindow:
    """Mixin: explicit-ACK sliding window shared by the in-process
    IciSocket and the multi-controller FabricSocket (reference
    rdma_endpoint.cpp:771 window check; credits return on consume).

    Contract for the host class (a Socket subclass): call
    ``_init_window(window_bytes)`` in __init__, gate each ``_do_write``
    through ``_consume_window(len)``, and call ``_on_credits(n)`` when the
    peer reports n consumed bytes.  A writer stalled past the
    ``_wait_writable`` timeout FAILS the socket — pending writes complete
    with an error instead of silently wedging forever."""

    _GUARDED_BY = {"_send_window": "_window_lock"}

    # fablint: init
    def _init_window(self, window_bytes: Optional[int]) -> None:
        self.window_bytes = (window_bytes if window_bytes is not None
                             else _flags.get_flag("ici_socket_window_bytes"))
        self._send_window = self.window_bytes
        self._window_lock = _dbg.make_lock("CreditWindow._window_lock")
        self._window_gen = Butex(0)       # bumped whenever credits return

    def send_window_left(self) -> int:
        with self._window_lock:
            return self._send_window

    def unacked_send_bytes(self) -> int:
        """Bytes written but not yet consumed by the peer (≤ window)."""
        with self._window_lock:
            return self.window_bytes - self._send_window

    def _consume_window(self, want: int) -> int:
        """Take up to ``want`` bytes of window; -1 when the window is
        closed (transport not writable)."""
        with self._window_lock:
            if self._send_window <= 0:
                return -1
            n = min(want, self._send_window)
            self._send_window -= n
            return n

    def _on_credits(self, n: int) -> None:
        """Peer consumed n bytes: replenish the window, wake blocked
        writers (the piggybacked-ACK path of rdma_endpoint.cpp)."""
        with self._window_lock:
            self._send_window = min(self.window_bytes, self._send_window + n)
        self._wake_window()

    def _wake_window(self) -> None:
        self._window_gen.fetch_add(1)
        self._window_gen.wake_all()

    def _peer_gone(self) -> bool:
        """Transport-specific: the far side can no longer return credits."""
        return False

    def _wait_writable(self, timeout: float = 30.0) -> bool:
        deadline = _time.monotonic() + timeout
        while not self.failed:
            gen = self._window_gen.value
            with self._window_lock:
                if self._send_window > 0:
                    return True
            if self._peer_gone():
                self.set_failed(errors.EFAILEDSOCKET,
                                "ici peer closed while window full")
                return False
            left = deadline - _time.monotonic()
            if left <= 0:
                # a stalled window must not black-hole the socket: fail it
                # so queued writes complete with an error and callers see
                # EFAILEDSOCKET rather than waiting forever
                self.set_failed(
                    errors.EFAILEDSOCKET,
                    f"ici send window stalled >{timeout:.0f}s "
                    f"(peer not consuming)")
                return False
            self._window_gen.wait(gen, min(left, 0.5))
        return False


class OrderedDelivery:
    """Mixin: per-socket in-order commit of received frames whose device
    payloads become ready asynchronously.  A host-only frame arriving
    after a device-bearing one must not jump the queue (byte-stream
    ordering is the transport contract the parsers rely on).

    Waits may be plain device arrays (gated through the per-device
    completion poller) or device-plane transfers / any object exposing
    ``add_done_callback`` (gated on its completion — the CQ entry)."""

    _GUARDED_BY = {"_dq": "_dq_lock", "_dq_draining": "_dq_lock"}

    # fablint: init
    def _init_delivery(self) -> None:
        import collections
        self._dq = collections.deque()    # entries: [ready, commit_fn]
        self._dq_lock = _dbg.make_lock("OrderedDelivery._dq_lock")
        self._dq_draining = False

    def _enqueue_delivery(self, waits: List,
                          commit_fn: Callable[[], None]) -> None:
        entry = [False, commit_fn]
        with self._dq_lock:
            self._dq.append(entry)

        arrays = [w for w in waits if not hasattr(w, "add_done_callback")]
        handles = [w for w in waits if hasattr(w, "add_done_callback")]
        gates = len(handles) + (1 if arrays and not _all_ready(arrays)
                                else 0)
        if gates == 0:
            entry[0] = True
            self._drain_deliveries()
            return

        left = [gates]
        left_lock = threading.Lock()

        def one_gate(_err=None):
            with left_lock:
                left[0] -= 1
                if left[0] > 0:
                    return
            entry[0] = True
            self._drain_deliveries()

        if arrays and not _all_ready(arrays):
            DeviceEventDispatcher.instance().on_ready(arrays, one_gate)
        for h in handles:
            h.add_done_callback(one_gate)

    def _drain_deliveries(self) -> None:
        while True:
            with self._dq_lock:
                if (self._dq_draining or not self._dq
                        or not self._dq[0][0]):
                    return
                self._dq_draining = True
                fn = self._dq.popleft()[1]
            try:
                fn()
            finally:
                with self._dq_lock:
                    self._dq_draining = False


class IciSocket(CreditWindow, OrderedDelivery, Socket):
    # fablint guarded-state contract: the inbox and the pinned-send
    # table are touched from the writer, the reader, and the device
    # completion poller
    _GUARDED_BY = {
        "_inbox": "_inbox_lock",
        "_inflight_sends": "_inflight_lock",
        "_inflight_seq": "_inflight_lock",
    }

    def __init__(self, local_dev: int, remote_dev: int,
                 mesh: Optional[IciMesh] = None,
                 window_bytes: Optional[int] = None):
        self.mesh = mesh or IciMesh.default()
        super().__init__(remote_side=self.mesh.endpoint(remote_dev))
        self.local_dev = local_dev
        self.remote_dev = remote_dev
        self.local_side = self.mesh.endpoint(local_dev)
        self.peer: Optional["IciSocket"] = None
        self._inbox = IOBuf()
        self._inbox_lock = _dbg.make_lock("IciSocket._inbox_lock")
        self.read_chunk_hint = 1 << 26    # _do_read cuts, never allocates
        self._peer_closed = False
        self._init_window(window_bytes)
        self._init_delivery()
        # source device blocks pinned until their ICI transfer completed
        # (reference frees _sbuf refs only on CQ completion,
        # rdma_endpoint.cpp:926 HandleCompletion) — load-bearing once
        # buffer donation reuses send blocks
        self._inflight_sends: Dict[int, Tuple] = {}
        self._inflight_seq = 0
        self._inflight_lock = _dbg.make_lock("IciSocket._inflight_lock")

    def inflight_send_blocks(self) -> int:
        """Device source blocks pinned awaiting transfer completion."""
        with self._inflight_lock:
            return len(self._inflight_sends)

    # -- transport hooks -------------------------------------------------
    def _do_write(self, data: IOBuf) -> int:
        peer = self.peer
        if peer is None or peer.failed:
            raise ConnectionError("ici peer closed")
        n = self._consume_window(len(data))
        if n < 0:
            return -1                     # window full: not writable now
        frame = data.cut(n)
        chunks = self._relocate(frame)
        self._deliver(peer, chunks)
        global _ici_bytes_moved
        with _ici_stats_lock:
            _ici_bytes_moved += n
        return n

    def _relocate(self, frame: IOBuf) -> List:
        """Move DEVICE refs to the peer's chip (HBM→HBM over ICI); host
        refs pass through as bytes.  Device-resident payloads at/above
        ``ici_device_plane_threshold`` post a send WR on the device plane
        instead — the payload then crosses through a COMPILED transfer
        program (shard_map + ppermute / Pallas remote DMA) with only a
        descriptor riding the delivery path; the matching recv is
        enqueued by ``_deliver`` (the QP rendezvous).  A refused post
        (chaos, unbuildable program) degrades to device_put in the same
        frame."""
        import jax
        from . import device_plane as _dp
        target = self.mesh.device(self.remote_dev)
        chunks: List = []
        pending_host: List[bytes] = []
        global _ici_device_bytes_moved
        for i in range(frame.backing_block_num()):
            r = frame.backing_block(i)
            if r.block.kind == DEVICE:
                if pending_host:
                    chunks.append(b"".join(pending_host))
                    pending_host = []
                arr = r.block.data
                if r.offset or r.length != len(arr):
                    arr = arr[r.offset:r.offset + r.length]
                if not hasattr(arr, "devices"):
                    # host-resident numpy delivered by the fabric bulk
                    # plane, now being forwarded in-process: detach into
                    # an owned copy before device_put — jax zero-copy
                    # ALIASES ctypes-backed views without retaining them
                    import numpy as _np
                    arr = _np.array(arr, copy=True)
                    resident = False
                else:
                    try:
                        resident = target in arr.devices()
                    except Exception:
                        resident = False
                # already in the target chip's HBM: pure ref pass — the
                # zero-copy case the block_pool discipline exists for
                if resident:
                    chunks.append((arr, r.length))
                    with _ici_stats_lock:
                        _ici_device_bytes_moved += r.length
                    continue
                if _dp.eligible(r.length):
                    src_idx = _dp.mesh_index_of(arr, self.mesh)
                    if src_idx >= 0 and src_idx != self.remote_dev:
                        try:
                            t = _dp.plane().post_send(
                                arr, src_idx, self.remote_dev, socket=self)
                            t.add_source_release(
                                getattr(r.block, "on_send_complete", None))
                            chunks.append(_PlaneDesc(t, r.length))
                            with _ici_stats_lock:
                                _ici_device_bytes_moved += r.length
                            continue
                        except _dp.DevicePlaneError:
                            pass         # counted by the plane; fall back
                moved = jax.device_put(arr, target)
                self._pin_until_sent(r.block, moved)
                chunks.append((moved, r.length))
                with _ici_stats_lock:
                    _ici_device_bytes_moved += r.length
            else:
                pending_host.append(bytes(r.block.host_view(r.offset, r.length)))
        if pending_host:
            chunks.append(b"".join(pending_host))
        return chunks

    def _deliver(self, peer: "IciSocket", chunks: List) -> None:
        from . import device_plane as _dp
        waits: List = []
        for c in chunks:
            if isinstance(c, _PlaneDesc):
                # the matching recv: rendezvous with the posted send —
                # both sides join the same compiled transfer program
                c.transfer = _dp.plane().post_recv(c.transfer.uuid)
                waits.append(c.transfer)
            elif isinstance(c, tuple):
                waits.append(c[0])

        def commit() -> None:
            buf = IOBuf()
            for c in chunks:
                if isinstance(c, _PlaneDesc):
                    buf.append_device_array(c.transfer.out)
                elif isinstance(c, tuple):
                    buf.append_device_array(c[0])
                else:
                    buf.append(c)
            with peer._inbox_lock:
                peer._inbox.append(buf)
            ok_inline = (not peer.is_server_side
                         or getattr(peer, "usercode_inline", False))
            peer.start_input_event(inline=ok_inline)

        # ordered per-socket commit: the read event fires only after the
        # payload landed in peer HBM, and never out of arrival order
        peer._enqueue_delivery(waits, commit)

    def _pin_until_sent(self, src_block, moved) -> None:
        """Hold the SOURCE device block (and the moved array) until the
        ICI transfer completes; only then may the source block be reused /
        donated.  Mirrors the reference's completion-driven `_sbuf` free
        (rdma_endpoint.cpp:926): the completion source here is the device
        stream, observed through the per-device poller."""
        with self._inflight_lock:
            seq = self._inflight_seq
            self._inflight_seq += 1
            self._inflight_sends[seq] = (src_block, moved)

        def _done(seq=seq):
            with self._inflight_lock:
                entry = self._inflight_sends.pop(seq, None)
            if entry is not None:
                blk = entry[0]
                cb = getattr(blk, "on_send_complete", None)
                if cb is not None:
                    try:
                        cb()
                    except Exception:
                        pass

        DeviceEventDispatcher.instance().on_ready([moved], _done)

    def _do_read(self, portal: IOPortal, max_count: int) -> int:
        with self._inbox_lock:
            avail = len(self._inbox)
            if avail == 0:
                return 0 if self._peer_closed else -1
            n = min(avail, max_count)
            self._inbox.cutn(portal, n)
        # consumed-bytes feedback: replenish the writer's window (in
        # multi-controller mode this rides the control channel as an ACK
        # frame — see fabric.py)
        peer = self.peer
        if peer is not None and not peer.failed:
            peer._on_credits(n)
        return n

    def _peer_gone(self) -> bool:
        peer = self.peer
        return peer is None or peer.failed or self._peer_closed

    # ---- lame-duck (GOODBYE) -------------------------------------------
    def send_goodbye(self) -> None:
        """Server drain: the in-process flavor of the fabric GOODBYE
        control frame — notify the peer socket directly (same process,
        no wire needed)."""
        peer = self.peer
        if peer is not None and not peer.failed:
            peer.on_peer_goodbye()

    def on_peer_goodbye(self) -> None:
        # the peer endpoint is draining: no new calls ride this socket
        # (SocketMap replaces logoff sockets on next use) and every live
        # LB pulls the endpoint now — before any health-check probe
        self.logoff = True
        try:
            from ..rpc import lameduck
            lameduck.notify_peer_draining(self.remote_side)
        except Exception:
            pass

    def _transport_close(self) -> None:
        peer = self.peer
        if peer is not None and not peer.failed:
            if self.failed_error == errors.ELOGOFF:
                # lame-duck hard stop: the peer's in-flight calls fail
                # with the retryable server code, applied on the EOF
                # path AFTER queued responses drain (see mem_transport —
                # failing immediately would retry already-executed
                # calls)
                peer._eof_error_code = errors.ELOGOFF
            with peer._inbox_lock:
                peer._peer_closed = True
            peer.start_input_event()
            # wake the peer's blocked writers so they observe _peer_gone
            # instead of stalling out their full timeout
            peer._wake_window()
        # release our own writers blocked on the (now dead) window
        self._wake_window()


class _PlaneDesc:
    """A device-plane descriptor riding the in-process delivery path: the
    posted send's WR handle plus the payload length — the peer's
    ``post_recv`` fills in the dst-resident output at rendezvous."""

    __slots__ = ("transfer", "length")

    def __init__(self, transfer, length: int):
        self.transfer = transfer
        self.length = length


def _all_ready(arrays) -> bool:
    """True when every transfer already completed (skip the poller hop)."""
    try:
        return all(a.is_ready() for a in arrays)
    except AttributeError:
        return False


# ---- listener registry (ici "ports") ----------------------------------

_listeners: Dict[int, "IciListener"] = {}
_listeners_lock = _dbg.make_lock("ici.transport._listeners_lock")


class IciListener:
    def __init__(self, device_id: int, on_accept, mesh: IciMesh):
        self.device_id = device_id
        self.on_accept = on_accept
        self.mesh = mesh

    def connect(self, client_dev: int) -> IciSocket:
        client = IciSocket(client_dev, self.device_id, self.mesh)
        serv = IciSocket(self.device_id, client_dev, self.mesh)
        client.peer, serv.peer = serv, client
        serv.is_server_side = True
        self.on_accept(serv)
        return client


def ici_listen(device_id: int, on_accept,
               mesh: Optional[IciMesh] = None) -> IciListener:
    mesh = mesh or IciMesh.default()
    with _listeners_lock:
        if device_id in _listeners:
            raise OSError(errors.EINVAL, f"ici://{device_id} already listening")
        l = IciListener(device_id, on_accept, mesh)
        _listeners[device_id] = l
        return l


def ici_unlisten(device_id: int) -> None:
    with _listeners_lock:
        _listeners.pop(device_id, None)


def ici_connect(ep: EndPoint, local_dev: Optional[int] = None) -> IciSocket:
    with _listeners_lock:
        l = _listeners.get(ep.device_id)
    if l is None:
        raise ConnectionRefusedError(f"no server at {ep}")
    if local_dev is None:
        # default client residence: the neighbor that makes the hop one ICI
        # link (or the same chip when the mesh is size 1)
        local_dev = (ep.device_id + 1) % l.mesh.size
    return l.connect(local_dev)
