"""ici:// transport: RPC frames between device endpoints, payloads in HBM.

This is the analogue of the reference's RDMA transport (SURVEY.md §3.5,
src/brpc/rdma/rdma_endpoint.cpp): where RdmaEndpoint posts zero-copy SGEs
from registered IOBuf blocks and completions arrive via CQ events, the ici
transport moves IOBuf *device blocks* between chips with XLA transfers and
completions arrive via device-stream readiness (bthread.device_waiter — the
CQ/EventDispatcher analogue).

Wire model (single-controller JAX):
  * An IciSocket connects two endpoints ``ici://a`` ↔ ``ici://b``.
  * ``write(iobuf)`` splits the buffer into the host-byte stream (protocol
    frames/meta — small) and its DEVICE block refs (bulk payload).  Host
    bytes are handed to the peer directly; device blocks are relocated to
    the peer's device with ``jax.device_put`` — on TPU hardware this is a
    direct HBM→HBM ICI transfer that never touches the host.  The delivered
    IOBuf has the same layout with device refs now resident on the target
    chip.
  * Delivery order per socket is preserved by a per-socket ExecutionQueue;
    the payload transfer is awaited through DeviceEventDispatcher before
    the peer's input path runs — "read event fires when the data is in
    local HBM", exactly the RDMA completion contract.

In a future multi-controller deployment the relocation step becomes paired
XLA Send/Recv (the handshake already exchanges device ids, mirroring the
reference's GID/QPN TCP handshake rdma_endpoint.h:37); everything above
Socket is unaffected.  Collectives (combo-channel lowering) do NOT go
through point-to-point sockets — see collective.py.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..butil.endpoint import EndPoint, SCHEME_ICI
from ..butil.iobuf import IOBuf, IOPortal, DEVICE
from ..bthread.device_waiter import DeviceEventDispatcher
from ..rpc import errors
from ..rpc.socket import Socket
from .mesh import IciMesh

_ici_stats_lock = threading.Lock()
_ici_bytes_moved = 0
_ici_device_bytes_moved = 0


def ici_transport_stats() -> Tuple[int, int]:
    with _ici_stats_lock:
        return _ici_bytes_moved, _ici_device_bytes_moved


class _Delivery:
    """One ordered unit: host bytes interleaved with relocated device refs."""
    __slots__ = ("chunks",)

    def __init__(self, chunks: List):
        self.chunks = chunks        # list of bytes | (jax.Array, length)


class IciSocket(Socket):
    def __init__(self, local_dev: int, remote_dev: int,
                 mesh: Optional[IciMesh] = None):
        self.mesh = mesh or IciMesh.default()
        super().__init__(remote_side=self.mesh.endpoint(remote_dev))
        self.local_dev = local_dev
        self.remote_dev = remote_dev
        self.local_side = self.mesh.endpoint(local_dev)
        self.peer: Optional["IciSocket"] = None
        self._inbox = IOBuf()
        self._inbox_lock = threading.Lock()
        self._peer_closed = False

    # -- transport hooks -------------------------------------------------
    def _do_write(self, data: IOBuf) -> int:
        peer = self.peer
        if peer is None or peer.failed:
            raise ConnectionError("ici peer closed")
        n = len(data)
        frame = data.cut(n)
        chunks = self._relocate(frame)
        self._deliver(peer, chunks)
        global _ici_bytes_moved
        with _ici_stats_lock:
            _ici_bytes_moved += n
        return n

    def _relocate(self, frame: IOBuf) -> List:
        """Move DEVICE refs to the peer's chip (HBM→HBM over ICI); host
        refs pass through as bytes."""
        import jax
        target = self.mesh.device(self.remote_dev)
        chunks: List = []
        pending_host: List[bytes] = []
        global _ici_device_bytes_moved
        for i in range(frame.backing_block_num()):
            r = frame.backing_block(i)
            if r.block.kind == DEVICE:
                if pending_host:
                    chunks.append(b"".join(pending_host))
                    pending_host = []
                arr = r.block.data
                if r.offset or r.length != len(arr):
                    arr = arr[r.offset:r.offset + r.length]
                try:
                    resident = target in arr.devices()
                except Exception:
                    resident = False
                # already in the target chip's HBM: pure ref pass — the
                # zero-copy case the block_pool discipline exists for
                moved = arr if resident else jax.device_put(arr, target)
                chunks.append((moved, r.length))
                with _ici_stats_lock:
                    _ici_device_bytes_moved += r.length
            else:
                pending_host.append(bytes(r.block.host_view(r.offset, r.length)))
        if pending_host:
            chunks.append(b"".join(pending_host))
        return chunks

    def _deliver(self, peer: "IciSocket", chunks: List) -> None:
        device_arrays = [c[0] for c in chunks if isinstance(c, tuple)]

        def commit(inline: bool) -> None:
            buf = IOBuf()
            for c in chunks:
                if isinstance(c, tuple):
                    buf.append_device_array(c[0])
                else:
                    buf.append(c)
            with peer._inbox_lock:
                peer._inbox.append(buf)
            ok_inline = (not peer.is_server_side
                         or getattr(peer, "usercode_inline", False))
            peer.start_input_event(inline=inline and ok_inline)

        if device_arrays and not _all_ready(device_arrays):
            # read event only after the payload landed in peer HBM
            DeviceEventDispatcher.instance().on_ready(
                device_arrays, lambda: commit(True))
        else:
            commit(True)

    def _do_read(self, portal: IOPortal, max_count: int) -> int:
        with self._inbox_lock:
            avail = len(self._inbox)
            if avail == 0:
                return 0 if self._peer_closed else -1
            n = min(avail, max_count)
            self._inbox.cutn(portal, n)
            return n

    def _transport_close(self) -> None:
        peer = self.peer
        if peer is not None and not peer.failed:
            with peer._inbox_lock:
                peer._peer_closed = True
            peer.start_input_event()


def _all_ready(arrays) -> bool:
    """True when every transfer already completed (skip the poller hop)."""
    try:
        return all(a.is_ready() for a in arrays)
    except AttributeError:
        return False


# ---- listener registry (ici "ports") ----------------------------------

_listeners: Dict[int, "IciListener"] = {}
_listeners_lock = threading.Lock()


class IciListener:
    def __init__(self, device_id: int, on_accept, mesh: IciMesh):
        self.device_id = device_id
        self.on_accept = on_accept
        self.mesh = mesh

    def connect(self, client_dev: int) -> IciSocket:
        client = IciSocket(client_dev, self.device_id, self.mesh)
        serv = IciSocket(self.device_id, client_dev, self.mesh)
        client.peer, serv.peer = serv, client
        serv.is_server_side = True
        self.on_accept(serv)
        return client


def ici_listen(device_id: int, on_accept,
               mesh: Optional[IciMesh] = None) -> IciListener:
    mesh = mesh or IciMesh.default()
    with _listeners_lock:
        if device_id in _listeners:
            raise OSError(errors.EINVAL, f"ici://{device_id} already listening")
        l = IciListener(device_id, on_accept, mesh)
        _listeners[device_id] = l
        return l


def ici_unlisten(device_id: int) -> None:
    with _listeners_lock:
        _listeners.pop(device_id, None)


def ici_connect(ep: EndPoint, local_dev: Optional[int] = None) -> IciSocket:
    with _listeners_lock:
        l = _listeners.get(ep.device_id)
    if l is None:
        raise ConnectionRefusedError(f"no server at {ep}")
    if local_dev is None:
        # default client residence: the neighbor that makes the hop one ICI
        # link (or the same chip when the mesh is size 1)
        local_dev = (ep.device_id + 1) % l.mesh.size
    return l.connect(local_dev)
