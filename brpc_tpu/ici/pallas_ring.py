"""Pallas ring collectives: the kernel-level RdmaEndpoint.

Reference mapping (SURVEY.md §3.5): RdmaEndpoint posts zero-copy sends from
registered blocks with a double-buffered sliding window and waits CQ
completions.  On TPU the same machinery is a Pallas kernel:

  * ``pltpu.make_async_remote_copy``  = ibv_post_send over ICI
  * send/recv DMA semaphores          = completion queue events
  * double-buffered VMEM comm slots   = the registered block ring (_sbuf/_rbuf)
  * neighbor barrier semaphore        = the QP handshake

Two kernels, each one hop per step around the logical ring:

  * ``ring_all_gather(x)``  — every device ends with every chunk
  * ``ring_all_reduce(x)``  — every device ends with the sum of all chunks

Compiled natively on TPU; on CPU/test meshes they run in Pallas interpret
mode (auto-detected) so CI exercises the exact kernel control flow the TPU
executes.  The lax.ppermute-based path in ring.py remains the XLA-scheduled
alternative; this module is the hand-scheduled one for when the compiler's
schedule is the bottleneck.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from .mesh import IciMesh

_cache: Dict[Tuple, Callable] = {}
_cache_lock = threading.Lock()


def _interpret_default() -> bool:
    import jax
    return jax.devices()[0].platform != "tpu"


def _build_all_gather(mesh: IciMesh, chunk_shape, dtype, interpret: bool):
    import jax
    from jax import lax
    from ..butil.jax_compat import shard_map, tpu_compiler_params
    from jax.sharding import PartitionSpec as P
    import jax.experimental.pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    n = mesh.size
    ax = mesh.axis_name

    def kernel(local_ref, out_ref, comm_buf, send_sem, recv_sem):
        my_id = lax.axis_index(ax)
        out_ref[pl.dslice(my_id, 1)] = local_ref[:][None]
        comm_buf[0] = local_ref[:]

        def step_body(step, _):
            send_slot = lax.rem(step, 2)
            recv_slot = 1 - send_slot
            dst = lax.rem(my_id + 1, n)
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[send_slot],
                dst_ref=comm_buf.at[recv_slot],
                send_sem=send_sem.at[send_slot],
                recv_sem=recv_sem.at[recv_slot],
                device_id=dst,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait()
            src_dev = lax.rem(my_id - step - 1 + 2 * n, n)
            out_ref[pl.dslice(src_dev, 1)] = comm_buf[recv_slot][None]
            return 0

        lax.fori_loop(0, n - 1, step_body, 0)

    def per_device(x_local):            # (1, *chunk)
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n,) + chunk_shape, dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((2,) + chunk_shape, dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            compiler_params=tpu_compiler_params(has_side_effects=True,
                                                collective_id=0),
            interpret=interpret,
        )(x_local[0])
        return out[None]

    return jax.jit(shard_map(per_device, mesh=mesh.mesh, in_specs=P(ax),
                             out_specs=P(ax), check_vma=False))


def _build_all_reduce(mesh: IciMesh, chunk_shape, dtype, interpret: bool):
    import jax
    from jax import lax
    from ..butil.jax_compat import shard_map, tpu_compiler_params
    from jax.sharding import PartitionSpec as P
    import jax.experimental.pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    n = mesh.size
    ax = mesh.axis_name

    def kernel(local_ref, out_ref, acc_buf, comm_buf, send_sem, recv_sem):
        """Ring accumulate: carry moves one hop per step, adding the local
        chunk at every stop; after n-1 hops every carry holds the sum."""
        my_id = lax.axis_index(ax)
        acc_buf[0] = local_ref[:]       # the travelling carry (send side)

        def step_body(step, _):
            send_slot = lax.rem(step, 2)
            recv_slot = 1 - send_slot
            dst = lax.rem(my_id + 1, n)
            comm_buf[send_slot] = acc_buf[0]
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[send_slot],
                dst_ref=comm_buf.at[recv_slot],
                send_sem=send_sem.at[send_slot],
                recv_sem=recv_sem.at[recv_slot],
                device_id=dst,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait()
            acc_buf[0] = comm_buf[recv_slot] + local_ref[:]
            return 0

        lax.fori_loop(0, n - 1, step_body, 0)
        out_ref[:] = acc_buf[0]

    def per_device(x_local):            # (1, *chunk)
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(chunk_shape, dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((1,) + chunk_shape, dtype),
                pltpu.VMEM((2,) + chunk_shape, dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            compiler_params=tpu_compiler_params(has_side_effects=True,
                                                collective_id=1),
            interpret=interpret,
        )(x_local[0])
        return out[None]

    return jax.jit(shard_map(per_device, mesh=mesh.mesh, in_specs=P(ax),
                             out_specs=P(ax), check_vma=False))


def _cached(key: Tuple, builder: Callable) -> Callable:
    with _cache_lock:
        fn = _cache.get(key)
        if fn is None:
            fn = builder()
            _cache[key] = fn
        return fn


def ring_all_gather(x, mesh: Optional[IciMesh] = None,
                    interpret: Optional[bool] = None):
    """x: (n, *chunk) sharded one row per device → (n, n, *chunk) sharded:
    device d's row holds every device's chunk."""
    mesh = mesh or IciMesh.default()
    if mesh.size == 1:
        return x[:, None]
    interp = _interpret_default() if interpret is None else interpret
    chunk_shape = tuple(x.shape[1:])
    key = ("ag", mesh.size, chunk_shape, str(x.dtype), interp)
    fn = _cached(key, lambda: _build_all_gather(mesh, chunk_shape, x.dtype,
                                                interp))
    return fn(x)


def ring_all_reduce(x, mesh: Optional[IciMesh] = None,
                    interpret: Optional[bool] = None):
    """x: (n, *chunk) sharded → (n, *chunk) sharded where every row is the
    elementwise sum over all rows."""
    mesh = mesh or IciMesh.default()
    if mesh.size == 1:
        return x
    interp = _interpret_default() if interpret is None else interpret
    chunk_shape = tuple(x.shape[1:])
    key = ("ar", mesh.size, chunk_shape, str(x.dtype), interp)
    fn = _cached(key, lambda: _build_all_reduce(mesh, chunk_shape, x.dtype,
                                                interp))
    return fn(x)
