"""Multi-controller ici://: cross-process handshake + device data plane.

Reference analogue (SURVEY.md §3.5, src/brpc/rdma/rdma_endpoint.h:37-108):
RdmaEndpoint forms a connection with an out-of-band TCP handshake that
exchanges GID/QPN, then moves payloads over verbs with an explicit-ACK
window, freeing send buffers only on CQ completion.  The TPU translation:

  * **Out-of-band channel** — the JAX coordination service
    (jax.distributed): each process publishes its fabric contact info
    (control TCP address, transfer-server address, owned device ids) under
    a well-known KV key; peers resolve it with a blocking get.  This is
    the GID/QPN exchange.
  * **Control plane** — a plain TCP connection per socket pair carries
    protocol bytes (frames, meta — small) plus the window bookkeeping
    (CREDIT) and transfer completions (PULLED — the CQ-completion
    analogue).
  * **Data plane** — DEVICE payloads never ride the control TCP: the
    sender stages arrays on its jax.experimental.transfer server under a
    uuid (``await_pull``) and ships only a descriptor; the receiver pulls
    straight into its local device memory (on TPU pods this is a
    DMA-style fetch, the RDMA-READ model).  Source blocks stay pinned
    until the peer's PULLED ack — the rdma_endpoint.cpp:926 discipline.
  * **Flow control** — same credit window as the in-process IciSocket
    (rdma_endpoint.cpp:771): at most ``ici_socket_window_bytes``
    unconsumed bytes per socket; CREDIT frames replenish on consume.

Addressing: ``ici://k`` is position k in the GLOBAL jax.devices() list
(identical in every process); ownership is ``devices[k].process_index``.
``connect_any(ep)`` routes in-process targets through the zero-copy
IciSocket and remote ones through a FabricSocket transparently, so
Server/Channel code is identical single- or multi-controller.
"""
from __future__ import annotations

import ctypes
import itertools
import json
import os as _os
import socket as _pysocket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..butil import flags as _flags
from ..butil import debug_sync as _dbg
from ..butil import logging as log
from ..butil.iobuf import IOBuf, IOPortal, DEVICE
from ..rpc import errors
from ..rpc import fault_injection as _fi
from ..rpc import rpc_dump as _rdump
from ..rpc.socket import Socket
from . import device_plane as _dp
from . import plane_health as _ph
from . import route as _route
from .transport import CreditWindow, OrderedDelivery

_KV_PREFIX = "brpc_tpu/fabric/"

# Data-plane selection for cross-process payloads.  The native bulk plane
# (native/fabric.cpp: uuid-tagged frames over a dedicated TCP connection,
# synchronous-send custody) measured ~2.3 GB/s on a 1-core loopback host
# where the jax transfer-server pull path measured 0.23 GB/s serial /
# 0.5 GB/s pipelined.  On real TPU pods the transfer server is the
# premapped HBM->HBM DMA path that never stages through the host — set
# this flag False there to route device payloads over it instead.
_flags.define_flag("ici_fabric_bulk", True,
                   "cross-process fabric device payloads ride the native "
                   "bulk plane (False: jax transfer-server DMA pulls)")
# Host byte-blobs at least this large also ride the bulk plane (below it
# the descriptor + claim round trip costs more than the inline copy).
_flags.define_flag("ici_fabric_bulk_host_min", 64 * 1024,
                   "min host-chunk bytes routed over the bulk plane",
                   _flags.positive_integer)
# Bulk-plane payload delivery semantics.  True (default): a received
# device payload is delivered as a HOST-RESIDENT array zero-copied over
# the native receive buffer — the reference's RDMA contract exactly
# (rdma delivers into registered HOST memory, rdma_endpoint.cpp:926; the
# application moves bytes to the accelerator when it uses them, which on
# TPU pods is the H2D DMA stage).  False: eagerly device_put on arrival,
# paying a host->device copy before the read event fires — the
# in-process IciSocket's "resident before read" semantics, at ~2x the
# per-byte CPU on CPU-backend fabrics where the "device" is the host.
_flags.define_flag("ici_fabric_host_delivery", True,
                   "deliver fabric bulk payloads host-resident (False: "
                   "eager device_put before the read event)")
# Failure semantics.  A fabric socket is NOT terminal (the reference's
# resilience doctrine, src/brpc/socket.cpp SetFailed/HealthCheck): when
# its control channel dies, in-flight correlation ids fail fast and the
# endpoint is handed to rpc/health_check.py, which probes with
# exponential backoff + jitter until a reconnect (a fresh HELLO/bulk
# handshake under a NEW versioned socket id) can succeed.
_flags.define_flag("ici_fabric_health_check", True,
                   "hand failed fabric endpoints to the health checker "
                   "for backoff-probed revival")
# How long a bulk claim tolerates descriptor/payload skew between the
# control and bulk connections before declaring the bytes lost.  Chaos
# tests shrink this so a dropped bulk frame resolves quickly.
_flags.define_flag("ici_bulk_claim_timeout_s", 60.0,
                   "max seconds a bulk claim waits for its frame")
# Same-host SHARED-MEMORY ring tier (native/fabric.cpp nshm): when both
# ends of a fabric pair run on one host and both advertise the "shm"
# capability, the dialing side creates an mmap'd /dev/shm segment at
# handshake (two SPSC rings, one per direction) and payloads >= the
# bulk thresholds move through it — ONE sender copy into shared memory,
# ZERO receiver copies (claims are zero-copy views into the ring,
# retired on release: consume-to-release credit), no syscalls on the
# byte path, futex doorbells for wakeups.  Only the (uuid, len)
# slot-descriptor rides the control channel (kinds 5/6 + stream
# FRAME_DATA_SHM).  Death (segment kill, peer crash mid-slot, mapping
# failure) degrades to the UDS/TCP bulk tier through the same PR-2
# machinery and revives in the background (the shm revival handshake).
_flags.define_flag("ici_fabric_shm", True,
                   "same-host fabric pairs add the mmap ring bulk tier "
                   "(False: UDS/TCP bulk only)")
_flags.define_flag("ici_shm_ring_bytes", 32 * 1024 * 1024,
                   "per-direction shm ring capacity per socket pair",
                   _flags.positive_integer)
_flags.define_flag("ici_shm_send_timeout_s", 20.0,
                   "max seconds an shm ring send waits for space before "
                   "the plane is declared dead")
# STRIPED shm (ISSUE 12): on multi-core hosts the segment holds N
# independent SPSC ring pairs (per-stripe futex doorbells and locks) so
# concurrent sender/claimer threads stop serializing on one ring — the
# single-core shm plane is copy-count-bounded near 2x, and stripes are
# how the remaining headroom is reached when there are cores to use.
# The descriptor carries its stripe in the uuid's top byte; frames of
# one STREAM share a stripe (affinity by stream id) so per-stream
# ordering is decided by one ring, while unary bulk frames round-robin.
# Health stays plane-wide: one dead stripe degrades the whole plane
# IN-FRAME exactly like the single ring.  0 = auto (1 on a 1-core
# host — the v1 single-ring layout, byte-identical to PR 10 — else
# min(4, cores)).
_flags.define_flag("ici_shm_stripes", 0,
                   "SPSC ring-pair stripes per shm segment (0 = auto: "
                   "1 on 1-core hosts, else min(4, host cores))")

_SHM_STRIPE_SHIFT = 56          # stripe id rides the uuid's top byte


def _resolve_shm_stripes() -> int:
    n = int(_flags.get_flag("ici_shm_stripes"))
    if n <= 0:
        cores = _os.cpu_count() or 1
        return 1 if cores <= 1 else min(4, cores)
    return min(n, 64)
# Cross-process device plane: device payloads cross through the
# SEQUENCED xproc plane — every transfer (both directions) is assigned a
# slot in one total order agreed over the control channel
# (CollectiveSequencer), and each side's single executor enters it at
# that slot.  On backends with multi-controller collectives (TPU pods)
# the transfer is a compiled XLA program both processes enter (shard_map
# + ppermute / Pallas remote DMA over the 2-device submesh — the SPMD
# contract); elsewhere the bytes ride the native bulk plane under the
# SAME sequencer (ici_device_plane_xproc_compiled=auto — this repo's CPU
# jaxlib raises "Multiprocess computations aren't implemented on the CPU
# backend").  Eligibility still requires the master ici_device_plane
# flag and its platform gate (TPU by default; host meshes opt in via
# ici_device_plane_host_mesh).  A failed/refused post degrades to
# bulk/inline in the same frame and the plane re-probes after
# ici_device_plane_retry_s.
_flags.define_flag("ici_device_plane_xproc", True,
                   "route cross-process device payloads through the "
                   "sequenced device plane (compiled collectives on TPU "
                   "pods, bulk-carried under the same total order "
                   "elsewhere)")
_flags.define_flag("ici_device_plane_retry_s", 2.0,
                   "seconds a degraded fabric device plane waits before "
                   "re-probing")

_u8p = ctypes.POINTER(ctypes.c_uint8)


class _NativeBufOwner:
    """Releases a native receive buffer when the last numpy view over
    it is collected (chained via the view's base -> ctypes array ->
    ._owner).  The exactly-once release for zero-copy host delivery;
    ``release_fn`` is the plane's release entry point — the socket
    tier's ``brpc_tpu_fab_buf_release`` (recycles into the conn's
    buffer pool, frees when the conn is gone) or the shm tier's
    ``brpc_tpu_shm_release`` (retires the ring slot)."""

    __slots__ = ("_release", "_conn", "_ptr", "_len")

    def __init__(self, release_fn, conn, ptr, length):
        self._release, self._conn, self._ptr = release_fn, conn, ptr
        self._len = length

    def __del__(self):
        try:
            self._release(self._conn, self._ptr, self._len)
        except Exception:
            pass


def _ShmBufOwner(lib, conn, ptr, length):
    """Owner for an shm ring slot: releasing retires it — the
    consume-to-release credit return; the ring space becomes reusable
    for the producer only now, and after the conn closed the LAST
    release also unmaps the segment (the native side defers the munmap
    exactly for this).  Same exactly-once discipline as the socket
    tier's buffers, so it IS that owner with the shm release symbol."""
    return _NativeBufOwner(lib.brpc_tpu_shm_release, conn, ptr, length)


class _ShmOversize(Exception):
    """The frame can never fit this ring — route it elsewhere without
    degrading the (healthy) shm plane."""


def _bulk_lib():
    """The native core, when present and the bulk plane is enabled."""
    if not _flags.get_flag("ici_fabric_bulk"):
        return None
    from ..butil import native as _native
    return _native.load()

# control-channel frame types
_F_HELLO = 1       # json: {target_dev, client_dev, pid}
_F_HELLO_OK = 2
_F_HELLO_ERR = 3
_F_DATA = 4        # chunk list: host bytes + device descriptors
_F_CREDIT = 5      # u64 consumed bytes
_F_PULLED = 6      # u64 uuid — receiver finished pulling (CQ completion)
_F_FIN = 7
# bulk-plane degradation + revival (self-healing; the control channel
# stays the source of truth so every transition is ORDERED relative to
# the descriptors that reference the bulk plane).  Consecutive ops:
# DOWN (sender observed death; peer degrades too), REESTABLISH (json
# {bulk_key} — client re-parked a conn), OK (server claimed + attached
# it), ERR (claim failed/refused; client backs off, retries).
_F_BULK_DOWN, _F_BULK_REESTABLISH, _F_BULK_OK, _F_BULK_ERR = 8, 9, 10, 11
# connectionless liveness probe (rpc/health_check.py): answers whether a
# server is listening at ici://target WITHOUT creating a fabric socket
_F_PING = 12              # u32 target_dev
_F_PING_OK = 13
_F_PING_ERR = 14
# lame-duck announcement (rpc/server.py drain): the sender is draining —
# the receiver pulls the endpoint from its LBs NOW (no probe-timeout
# wait), stops issuing new work on this socket (logoff), and hands the
# endpoint to the health checker for revival after the restart.  Older
# peers ignore unknown frame types, so GOODBYE is compatible both ways.
_F_GOODBYE = 15
# device-plane total order (CollectiveSequencer): the socket's order
# master (server side) assigns every cross-process transfer a dense seq;
# a client-side send goes out with seq -1 in its kind-4 descriptor and
# receives its assignment in this frame (u64 uuid, i64 seq)
_F_DPLANE_SEQ = 16
# shm ring degradation + revival (mirrors the bulk row above, same
# consecutive DOWN/REESTABLISH/OK/ERR ops — REESTABLISH carries json
# {shm_seg}, a fresh segment for the server to attach + unlink): the
# control channel stays the source of truth so every transition is
# ORDERED relative to the kind-5/6 and FRAME_DATA_SHM descriptors that
# reference the ring.  Older peers ignore unknown frame types.
_F_SHM_DOWN, _F_SHM_REESTABLISH, _F_SHM_OK, _F_SHM_ERR = 17, 18, 19, 20
# read-loop dispatch for the two self-healing planes rides ONE table
# (op index = ftype - the plane's DOWN base, relying on the consecutive
# numbering above): {ftype: (plane, op)} with op 0..3 =
# down/reestablish/ok/err — see FabricSocket._on_plane_frame
_PLANE_FRAMES = {b + i: (w, i)
                 for w, b in (("bulk", _F_BULK_DOWN), ("shm", _F_SHM_DOWN))
                 for i in range(4)}
# Compiled collective fan-out announce (channels/collective_fanout.py):
# the fan-out client is the order master — it commits a fan-out group at
# a dense seq and announces it over each remote member's control channel
# (FIFO per member, so every member observes the client's order); the
# member accepts (PARKING the SPMD entry until the client's commit) or
# refuses with a reason, and a refusal/timeout degrades the client's
# collective route in-call.  Two-phase: only after EVERY member accepted
# does the client send GO — an accepted member must never enter a
# program a degraded client will not join (its serial entry runner
# would wedge on the rendezvous forever); parked entries expire on the
# announce timeout.  Older peers ignore unknown frame types; the client
# then degrades on the announce timeout — compatible both ways.
_F_COLL_CALL = 21    # json: {method, seq, devices, mapping, merge,
                     #        shape, dtype, uuid}
_F_COLL_OK = 22      # json: {uuid, pid} — member accepted + parked entry
_F_COLL_ERR = 23     # json: {uuid, pid, reason} — refused, degrade
_F_COLL_GO = 24      # json: {uuid} — commit: the parked entry runs
# Clock alignment (ici/clock.py) deliberately adds NO frame type: the
# NTP-style exchange piggybacks on the HELLO/HELLO_OK handshake (the
# client's wall t0 rides the HELLO json; HELLO_OK echoes it with the
# server's wall), so the chaos suite's deterministic control-frame
# counting — and the read loop — never see it.  The dialing side derives
# the peer offset ± RTT/2; since every pod-scope stitch query DIALS its
# members (client-side sockets), the querier always holds an estimate.

_HDR = struct.Struct("<BI")          # type, body length


def _send_frame(sock: _pysocket.socket, ftype: int, body: bytes) -> None:
    sock.sendall(_HDR.pack(ftype, len(body)) + body)


def _recv_exact(sock: _pysocket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: _pysocket.socket) -> Optional[Tuple[int, bytes]]:
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    ftype, length = _HDR.unpack(hdr)
    body = _recv_exact(sock, length) if length else b""
    if length and body is None:
        return None
    return ftype, body


class FabricNode:
    """Per-process fabric runtime: transfer server + control listener +
    the coordination-service registry."""

    _instance: Optional["FabricNode"] = None
    _lock = threading.Lock()

    # fablint guarded-state contract
    _GUARDED_BY = {
        "_xfer_conns": "_xfer_lock",
        "_next_uuid": "_uuid_lock",
    }

    def __init__(self):
        self.process_id = -1
        self.num_processes = 0
        self._kv = None
        self._xfer_server = None
        self._xfer_conns: Dict[int, object] = {}      # pid -> TransferConnection
        self._xfer_lock = _dbg.make_lock("FabricNode._xfer_lock")
        self._ctrl_listener: Optional[_pysocket.socket] = None
        self.ctrl_addr = ""
        self._uuid_lock = _dbg.make_lock("FabricNode._uuid_lock")
        self._next_uuid = 1
        self._peers: Dict[int, dict] = {}             # pid -> contact info
        self._accept_thread: Optional[threading.Thread] = None
        self._shutdown = False
        self._bulk_lib = None                         # native core handle
        self._bulk_listener = 0                       # fab listener handle
        self.bulk_addr = ""
        self.bulk_uds = ""
        self.host_ip = ""
        # same-host shm ring tier: probed at start (a denied /dev/shm
        # just leaves the capability un-advertised — clean degrade)
        self._shm_ok = False
        self._shm_lib = None

    # ---- lifecycle -----------------------------------------------------
    @classmethod
    def instance(cls) -> Optional["FabricNode"]:
        with cls._lock:
            return cls._instance

    @classmethod
    def initialize(cls, coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   host_ip: Optional[str] = None) -> "FabricNode":
        """Join the fabric.  Calls jax.distributed.initialize when the
        coordination service isn't up yet (the reference's equivalent is
        whatever launched the processes); then performs the handshake
        publication.  Idempotent per process.

        ``host_ip`` is the address PUBLISHED to peers; None (default)
        derives it from the route to the coordinator, so multi-host
        fabrics don't hand out 127.0.0.1 (ADVICE r2 finding)."""
        with cls._lock:
            if cls._instance is not None:
                return cls._instance
            node = FabricNode()
            node._start(coordinator_address, num_processes, process_id,
                        host_ip)
            cls._instance = node
            # deterministic pre-exit shutdown ordering: quiesce every
            # fabric reader thread (Python control readers AND native
            # bulk readers) before interpreter/static teardown can race
            # them — the exit-abort class of flake
            import atexit
            atexit.register(cls._atexit_quiesce)
            return node

    @classmethod
    def _atexit_quiesce(cls) -> None:
        with cls._lock:
            node = cls._instance
        if node is not None:
            try:
                node.quiesce()
            except Exception:
                pass

    def _start(self, coordinator_address, num_processes, process_id,
               host_ip) -> None:
        import jax
        from jax._src import distributed
        if distributed.global_state.client is None:
            jax.distributed.initialize(coordinator_address,
                                       num_processes=num_processes,
                                       process_id=process_id)
        self._kv = distributed.global_state.client
        self.process_id = distributed.global_state.process_id
        self.num_processes = distributed.global_state.num_processes
        if host_ip is None:
            host_ip = self._derive_host_ip(
                coordinator_address
                or getattr(distributed.global_state, "coordinator_address",
                           None))
        # data plane: transfer server (explicit TCP transport addresses —
        # the same-host "local" bulk transport is not usable in sandboxed
        # containers, and TCP is the portable choice; on real pods the
        # premapped DMA path takes over).  OPTIONAL: older jax builds
        # ship no jax.experimental.transfer at all — the fabric then
        # rides the native bulk plane for every payload (device refs
        # included), or inlines d2h bytes on the control channel when
        # that is missing too, and publishes no "xfer" contact.
        try:
            from jax.experimental import transfer
        except ImportError:
            transfer = None
        if transfer is not None:
            backend = jax.local_devices()[0].client
            self._xfer_server = transfer.start_transfer_server(
                backend, f"{host_ip}:0", [f"{host_ip}:0"])
        # control plane listener
        self._ctrl_listener = _pysocket.socket()
        self._ctrl_listener.setsockopt(_pysocket.SOL_SOCKET,
                                       _pysocket.SO_REUSEADDR, 1)
        self._ctrl_listener.bind((host_ip, 0))
        self._ctrl_listener.listen(64)
        self.ctrl_addr = "%s:%d" % self._ctrl_listener.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fabric_accept", daemon=True)
        self._accept_thread.start()
        # bulk data plane (native/fabric.cpp) — optional: peers fall back
        # to transfer-server pulls when either side lacks it
        self.host_ip = host_ip
        lib = _bulk_lib()
        if lib is not None:
            port_out = ctypes.c_int()
            uds_out = ctypes.create_string_buffer(108)
            lh = lib.brpc_tpu_fab_listen(host_ip.encode(),
                                         ctypes.byref(port_out),
                                         uds_out, 108)
            if lh:
                self._bulk_lib = lib
                self._bulk_listener = lh
                self.bulk_addr = f"{host_ip}:{port_out.value}"
                self.bulk_uds = uds_out.value.decode()
        # shm ring capability probe: can this process create, map, and
        # unlink a segment?  A sandbox that denies /dev/shm fails here
        # once and the capability simply is not advertised — peers then
        # keep the socket bulk tier, byte-for-byte the old behavior.
        if lib is not None and hasattr(lib, "brpc_tpu_shm_create") \
                and _flags.get_flag("ici_fabric_shm"):
            import os as _os
            probe = f"brpc_tpu_shm_probe.{_os.getpid()}"
            lib.brpc_tpu_shm_unlink(probe.encode())
            ph = lib.brpc_tpu_shm_create(probe.encode(), 64 * 1024)
            if ph:
                lib.brpc_tpu_shm_unlink(probe.encode())
                lib.brpc_tpu_shm_close(ph)
                self._shm_ok = True
                self._shm_lib = lib
        # the handshake publication (GID/QPN analogue)
        info = {
            "ctrl": self.ctrl_addr,
            "devices": [i for i, d in enumerate(jax.devices())
                        if d.process_index == self.process_id],
        }
        if self._xfer_server is not None:
            info["xfer"] = self._xfer_server.address()
        if self.bulk_addr:
            info["bulk"] = self.bulk_addr
            if self.bulk_uds:
                # same-host peers dial the abstract unix plane instead
                # (~3x loopback TCP bandwidth); "host" disambiguates
                # same-host from same-address-on-another-host
                info["bulk_uds"] = self.bulk_uds
                info["host"] = self.host_ip
        if self._shm_ok:
            # shm capability key: same-host peers (matching "host") may
            # hand us a segment name at HELLO; mixed-version or
            # shm-less peers never see an shm descriptor (we only bind
            # the ring when BOTH ends acked it)
            info["shm"] = 1
            info["host"] = self.host_ip
        if _flags.get_flag("ici_device_plane"):
            # device-plane capability advert (both ends must hold it:
            # one-sided entry into an SPMD program would hang forever).
            # Version 3 = sequenced AND traced kind-4 descriptors
            # (<IqQQ> src+seq+trace_id+parent_span_id, plus the
            # _F_DPLANE_SEQ assignment frame), advertised under a NEW
            # key so the treat-as-plane-less rule holds in BOTH
            # directions across every version pair: a v1/v2 peer checks
            # "dplane"/"dplane2" (absent here — it never sends its
            # narrower descriptors at us) and we check "dplane3"
            # (absent on v1/v2 — we never send <IqQQ> at it).
            info["dplane3"] = 3
        self._kv.key_value_set(_KV_PREFIX + str(self.process_id),
                               json.dumps(info))
        log.info("fabric: process %d/%d up ctrl=%s xfer=%s devices=%s",
                 self.process_id, self.num_processes, info["ctrl"],
                 info.get("xfer", "<unavailable>"), info["devices"])

    @staticmethod
    def _derive_host_ip(coordinator_address: Optional[str]) -> str:
        """The IP this host uses to reach the coordinator — the address
        peers can reach US on (every fabric member reaches the
        coordinator by construction).  A UDP connect never sends a
        packet; it just resolves the route."""
        if coordinator_address:
            host, sep, port = coordinator_address.rpartition(":")
            if not sep:                    # no port at all: 'hostname'
                host, port = coordinator_address, ""
            host = host.strip("[]")        # IPv6 '[::1]:1234' form
            s = _pysocket.socket(_pysocket.AF_INET, _pysocket.SOCK_DGRAM)
            try:
                # ValueError too: '[::]' or a port-less 'host:path' form
                # must fall back, not crash FabricNode.initialize
                s.connect((host, int(port) if port.isdigit() else 1))
                return s.getsockname()[0]
            except (OSError, ValueError):
                pass
            finally:
                s.close()
        return "127.0.0.1"

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            if self._ctrl_listener is not None:
                self._ctrl_listener.close()
        except Exception:
            pass
        if self._bulk_listener and self._bulk_lib is not None:
            self._bulk_lib.brpc_tpu_fab_listener_close(self._bulk_listener)
            self._bulk_listener = 0

    def quiesce(self) -> None:
        """Close the listeners, sever every live fabric socket's control
        conn and JOIN its reader, then close+join every native bulk
        conn/listener reader (brpc_tpu_fab_quiesce).  After this returns
        no fabric thread is running, so exit-time teardown (CPython
        finalization, C++ static destructors) has nothing to race."""
        self.shutdown()
        t = self._accept_thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(2.0)     # accept() returns once the listener closed
        try:
            from ..rpc.socket import list_sockets
            for s in list(list_sockets()):
                if isinstance(s, FabricSocket):
                    s.quiesce_reader()
        except Exception:
            pass
        lib = self._bulk_lib
        if lib is None:
            try:
                lib = _bulk_lib()
            except Exception:
                lib = None
        if lib is not None and hasattr(lib, "brpc_tpu_fab_quiesce"):
            try:
                lib.brpc_tpu_fab_quiesce()
            except Exception:
                pass

    # ---- registry ------------------------------------------------------
    def peer_info(self, pid: int, timeout_ms: int = 60000) -> dict:
        info = self._peers.get(pid)
        if info is None:
            raw = self._kv.blocking_key_value_get(_KV_PREFIX + str(pid),
                                                  timeout_ms)
            info = json.loads(raw)
            self._peers[pid] = info
        return info

    @staticmethod
    def device_owner(device_id: int) -> int:
        import jax
        return jax.devices()[device_id].process_index

    def xfer_connection(self, pid: int):
        # the dial happens OUTSIDE _xfer_lock: peer_info is a blocking
        # KV get (up to 60s on a slow-starting peer) and connect is a
        # network round trip — holding the lock across either would
        # stall every OTHER peer's transfer path behind one laggard
        # (fablint blocking-under-lock finding).  Two racing dialers
        # both connect; the loser's conn is dropped (same keep-first
        # contract as the device-plane program cache).
        with self._xfer_lock:
            conn = self._xfer_conns.get(pid)
        if conn is not None:
            return conn
        if self._xfer_server is None:
            raise ConnectionError(
                "transfer server unavailable in this jax build "
                "(jax.experimental.transfer missing)")
        conn = self._xfer_server.connect(self.peer_info(pid)["xfer"])
        with self._xfer_lock:
            kept = self._xfer_conns.setdefault(pid, conn)
        if kept is not conn:
            # lost the dial race: release OUR conn, it is a live
            # transfer-server resource, not a GC-able cache entry
            closer = getattr(conn, "close", None)
            if closer is not None:
                try:
                    closer()
                except Exception:
                    pass
        return kept

    def next_uuid(self) -> int:
        with self._uuid_lock:
            u = (self.process_id + 1) << 40 | self._next_uuid
            self._next_uuid += 1
            return u

    def stage(self, uuid: int, arrays: List) -> None:
        self._xfer_server.await_pull(uuid, arrays)

    # ---- server side ---------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                conn, _ = self._ctrl_listener.accept()
            except OSError:
                return
            # fablint: thread-quiesced(per-connection; exits when the handshake completes or refuses and the conn closes)
            threading.Thread(target=self._handshake_server, args=(conn,),
                             name="fabric_handshake", daemon=True).start()

    def _handshake_server(self, conn: _pysocket.socket) -> None:
        # every exit that does not hand `bulk_h` to a FabricSocket must
        # release the client's parked bulk connection — each failed
        # handshake (e.g. the retry-until-server-up startup race) would
        # otherwise leak one fd + reader thread in the native pending
        # map, under a key no one will ever claim (review finding)
        bulk_h = 0
        bulk_key = None
        shm_h = 0
        try:
            conn.setsockopt(_pysocket.IPPROTO_TCP, _pysocket.TCP_NODELAY, 1)
            fr = _recv_frame(conn)
            if fr is not None and fr[0] == _F_PING:
                # liveness probe: reply and close, no socket is created
                (target,) = struct.unpack("<I", fr[1])
                from .transport import _listeners, _listeners_lock
                with _listeners_lock:
                    up = target in _listeners
                _send_frame(conn, _F_PING_OK if up else _F_PING_ERR, b"")
                conn.close()
                return
            if fr is None or fr[0] != _F_HELLO:
                conn.close()
                return
            hello = json.loads(fr[1])
            bulk_key = hello.get("bulk_key")
            target = hello["target_dev"]
            plan = _fi.fabric_active()
            if plan is not None and plan.on_hello():
                _send_frame(conn, _F_HELLO_ERR, b"injected hello refusal")
                conn.close()
                self._reap_parked_bulk(bulk_key)
                return
            from .transport import _listeners, _listeners_lock
            with _listeners_lock:
                listener = _listeners.get(target)
            if listener is None:
                _send_frame(conn, _F_HELLO_ERR,
                            f"no server at ici://{target}".encode())
                conn.close()
                self._reap_parked_bulk(bulk_key)
                return
            # bulk plane binding: the client connected its bulk conn
            # BEFORE sending HELLO, so the claim usually returns at once.
            # A client that advertised a key it never connected must get
            # HELLO_ERR, not a silently bulk-less socket — it will send
            # bulk descriptors we could never resolve.
            if bulk_key:
                if self._bulk_listener and self._bulk_lib is not None:
                    bulk_h = self._bulk_lib.brpc_tpu_fab_accept(
                        self._bulk_listener, bulk_key.encode(),
                        15_000_000)
                if not bulk_h:
                    _send_frame(conn, _F_HELLO_ERR,
                                b"bulk plane binding failed")
                    conn.close()
                    return
            # shm ring tier: attach the segment the client created and
            # unlink it (the mapping outlives the name; a later crash
            # leaks nothing).  Attach failure is SOFT — the client only
            # binds its end on our explicit ack, so a missing ack
            # degrades the pair to the socket bulk tier cleanly.
            shm_name = hello.get("shm_seg")
            if shm_name and self._shm_ok and self._shm_lib is not None \
                    and _flags.get_flag("ici_fabric_shm"):
                refused = plan is not None and plan.on_shm_handshake()
                if not refused:
                    shm_h = self._shm_lib.brpc_tpu_shm_attach(
                        shm_name.encode())
                    if shm_h:
                        self._shm_lib.brpc_tpu_shm_unlink(
                            shm_name.encode())
            sock = FabricSocket(conn, local_dev=target,
                                remote_dev=hello["client_dev"],
                                peer_pid=hello["pid"], node=self)
            sock._attach_bulk(self._bulk_lib, bulk_h)
            bulk_h = 0                       # custody passed to the socket
            if shm_h:
                sock._attach_shm(self._shm_lib, shm_h)
                shm_h = 0
            sock.is_server_side = True
            # on_accept attaches the messenger BEFORE any frame can be
            # read — a reader that fires first would drain the input
            # event with no messenger and drop the first request
            listener.on_accept(sock)
            # clock-alignment piggyback (ici/clock.py): echo the
            # client's wall t0 with OUR wall stamp — the client bounds
            # our offset by its HELLO round trip.  Empty for old peers
            # unless the shm ack needs carrying.
            ok = {}
            if "wall_us" in hello:
                ok = {"t0": hello["wall_us"],
                      "wall_us": time.time_ns() // 1000}
            if sock.shm_bound():
                ok["shm"] = True
            ok_body = json.dumps(ok).encode() if ok else b""
            _send_frame(conn, _F_HELLO_OK, ok_body)
            sock.start_io()
        except Exception as e:
            log.error("fabric handshake failed: %s", e)
            try:
                conn.close()
            except Exception:
                pass
            if bulk_h and self._bulk_lib is not None:
                self._bulk_lib.brpc_tpu_fab_conn_close(bulk_h)
            else:
                self._reap_parked_bulk(bulk_key)
            if shm_h and self._shm_lib is not None:
                self._shm_lib.brpc_tpu_shm_close(shm_h)

    # A refused handshake's parked bulk conn is reaped with a short
    # NONZERO claim wait: the client dialed the bulk plane before sending
    # HELLO, but the acceptor thread may not have read the <klen><key>
    # binding header yet — a zero-timeout claim would miss that conn and
    # leak its fd + reader thread in Listener::pending forever (ADVICE r5).
    # 2 s comfortably covers the header race; the reap runs on the
    # per-handshake daemon thread, so the wait blocks no one else.
    _REAP_CLAIM_US = 2_000_000

    def _reap_parked_bulk(self, bulk_key: Optional[str]) -> None:
        """Claim-and-close a bulk conn the client parked for a handshake
        that is now being refused."""
        if not bulk_key or not self._bulk_listener \
                or self._bulk_lib is None:
            return
        h = self._bulk_lib.brpc_tpu_fab_accept(
            self._bulk_listener, bulk_key.encode(), self._REAP_CLAIM_US)
        if h:
            self._bulk_lib.brpc_tpu_fab_conn_close(h)

    # ---- client side ---------------------------------------------------
    def dial_bulk(self, peer_pid: int
                  ) -> Tuple[int, Optional[str], object, bool]:
        """Dial the peer's bulk listener and park a fresh conn under a
        unique key: (handle, key, lib, is_uds).  (0, None, lib, False)
        when either end lacks the native plane.  Shared by the initial
        connect and the degradation-recovery re-establishment path."""
        lib = _bulk_lib()
        bulk_h, bulk_key, is_uds = 0, None, False
        info = self.peer_info(peer_pid)
        if lib is not None and info.get("bulk"):
            bhost, _, bport = info["bulk"].rpartition(":")
            bulk_key = f"{self.process_id}:{self.next_uuid():x}"
            # same host -> abstract unix plane (measured ~3x loopback
            # TCP bandwidth); cross-host or failed -> TCP plane
            if info.get("bulk_uds") and info.get("host") == self.host_ip:
                bulk_h = lib.brpc_tpu_fab_connect_uds(
                    info["bulk_uds"].encode(), bulk_key.encode())
                is_uds = bool(bulk_h)
            if not bulk_h:
                bulk_h = lib.brpc_tpu_fab_connect(
                    bhost.encode(), int(bport), bulk_key.encode())
            if not bulk_h:
                bulk_key = None
        return bulk_h, bulk_key, lib, is_uds

    def shm_peer_ok(self, peer_pid: int) -> bool:
        """Both ends hold the shm capability AND share this host.  The
        flag is re-checked at CONNECT time (not just at the start-time
        probe) so a tool pinning the tier off after the node joined —
        rpc_press --bulk-plane uds, the bench's pinned legs — takes
        effect on every later socket."""
        if not self._shm_ok or not _flags.get_flag("ici_fabric_shm"):
            return False
        try:
            info = self.peer_info(peer_pid)
        except Exception:
            return False
        return bool(info.get("shm")) and info.get("host") == self.host_ip

    def create_shm_segment(self) -> Tuple[int, Optional[str], object]:
        """Create a fresh ring segment as the dialing side: (handle,
        name, lib); (0, None, None) when shm is unavailable.  The name
        rides the control channel (HELLO or the shm revival frame); the
        ATTACHING side unlinks after mapping, so the /dev/shm entry
        lives only for the handshake round trip."""
        if not self._shm_ok or self._shm_lib is None:
            return 0, None, None
        name = f"brpc_tpu_shm.{self.process_id}.{self.next_uuid():x}"
        stripes = _resolve_shm_stripes()
        if stripes > 1 and hasattr(self._shm_lib, "brpc_tpu_shm_create2"):
            # striped v2 segment (multi-core hosts): the attacher reads
            # the stripe count from the header, no hello change needed
            h = self._shm_lib.brpc_tpu_shm_create2(
                name.encode(),
                int(_flags.get_flag("ici_shm_ring_bytes")), stripes)
        else:
            h = self._shm_lib.brpc_tpu_shm_create(
                name.encode(), int(_flags.get_flag("ici_shm_ring_bytes")))
        if not h:
            return 0, None, None
        return h, name, self._shm_lib

    def drop_shm_segment(self, h: int, name: Optional[str]) -> None:
        """Abandon a created-but-never-acked segment: close the handle
        and remove the directory entry (the attach never happened, so
        nobody else unlinked it)."""
        if self._shm_lib is None:
            return
        if h:
            self._shm_lib.brpc_tpu_shm_close(h)
        if name:
            self._shm_lib.brpc_tpu_shm_unlink(name.encode())

    def ping(self, target_dev: int, timeout: float = 1.0) -> bool:
        """Probe whether ici://target_dev is served by its owner process,
        without creating a fabric socket — the health checker's
        reachability test for cross-process endpoints."""
        try:
            owner = self.device_owner(target_dev)
            info = self.peer_info(owner, timeout_ms=int(timeout * 1000))
            host, _, port = info["ctrl"].rpartition(":")
            with _pysocket.create_connection((host, int(port)),
                                             timeout=timeout) as conn:
                conn.settimeout(timeout)
                _send_frame(conn, _F_PING, struct.pack("<I", target_dev))
                fr = _recv_frame(conn)
                return fr is not None and fr[0] == _F_PING_OK
        except (OSError, ValueError, KeyError):
            return False

    def connect(self, target_dev: int, client_dev: int) -> "FabricSocket":
        owner = self.device_owner(target_dev)
        info = self.peer_info(owner)
        host, _, port = info["ctrl"].rpartition(":")
        conn = _pysocket.create_connection((host, int(port)), timeout=30)
        conn.setsockopt(_pysocket.IPPROTO_TCP, _pysocket.TCP_NODELAY, 1)
        # bulk plane: dial the peer's bulk listener FIRST so the key is
        # already parked when the control HELLO names it (both ends must
        # have the native core; either missing -> transfer-server path)
        bulk_h, bulk_key, lib, bulk_uds = self.dial_bulk(owner)
        # shm ring tier: create the segment BEFORE the HELLO that names
        # it; the server attaches during the handshake and unlinks, so
        # the /dev/shm entry lives only for this round trip.  Bound to
        # the socket only on an explicit ack — a refusing/older server
        # never sees an shm descriptor.
        shm_h, shm_name, shm_lib = (0, None, None)
        if self.shm_peer_ok(owner):
            shm_h, shm_name, shm_lib = self.create_shm_segment()
        hello = {"target_dev": target_dev, "client_dev": client_dev,
                 "pid": self.process_id,
                 # clock-alignment piggyback: our wall at HELLO send;
                 # the HELLO_OK echo + server wall bounds the peer
                 # offset by this round trip (±RTT/2, ici/clock.py)
                 "wall_us": time.time_ns() // 1000}
        if bulk_key:
            hello["bulk_key"] = bulk_key
        if shm_name:
            hello["shm_seg"] = shm_name
        t0_mono = time.monotonic_ns()
        try:
            _send_frame(conn, _F_HELLO, json.dumps(hello).encode())
            fr = _recv_frame(conn)
        except OSError:
            # a reset/timeout mid-handshake must not strand the already
            # -registered native bulk conn (fd + reader thread held by
            # the process-global registry — review finding) nor the
            # created-but-unattached shm segment
            conn.close()
            if bulk_h:
                lib.brpc_tpu_fab_conn_close(bulk_h)
            self.drop_shm_segment(shm_h, shm_name)
            raise
        if fr is None or fr[0] != _F_HELLO_OK:
            msg = fr[1].decode() if fr else "connection closed"
            conn.close()
            if bulk_h:
                lib.brpc_tpu_fab_conn_close(bulk_h)
            self.drop_shm_segment(shm_h, shm_name)
            raise ConnectionRefusedError(f"fabric: {msg}")
        echo = {}
        if fr[1]:
            try:
                echo = json.loads(fr[1])
            except ValueError:
                echo = {}
        if "wall_us" in echo:
            try:
                rtt_us = max(0, (time.monotonic_ns() - t0_mono) // 1000)
                # +1: a 0 bound would claim perfection no measurement
                # can prove
                from . import clock as _clock
                _clock.record(
                    owner,
                    echo["wall_us"] - (echo["t0"] + rtt_us / 2.0),
                    rtt_us / 2.0 + 1.0)
            except (ValueError, KeyError, TypeError):
                pass          # old peer / malformed echo: no estimate
        sock = FabricSocket(conn, local_dev=client_dev,
                            remote_dev=target_dev, peer_pid=owner, node=self)
        if bulk_h:
            sock._bulk_is_uds = bulk_uds
            sock._attach_bulk(lib, bulk_h)
        if shm_h:
            if echo.get("shm"):
                sock._attach_shm(shm_lib, shm_h)
            else:
                # server did not ack (older peer, refused, or attach
                # failed): the segment must not leak
                self.drop_shm_segment(shm_h, shm_name)
        sock.start_io()
        return sock


class CollectiveSequencer:
    """Direction-spanning total order for one socket pair's device-plane
    transfers — the pod-scale sequencer that closes the PR-3 open item
    (docs/PARITY.md: per-direction executors ordered each direction's
    collectives but let the two directions interleave differently on the
    two processes, a guaranteed SPMD ordering mismatch under
    bidirectional load).

    One sequencer replaces both per-direction executors, agreed over the
    serial control channel:

      * the socket's SERVER side is the order master: it assigns a dense
        sequence number to EVERY transfer, both directions — its own
        sends at encode time, the client's sends the moment their
        descriptor arrives on the control read loop (before anything
        executes);
      * a master-side send carries its seq inside the kind-4 descriptor;
        a client-side send goes out with seq -1 and receives its
        assignment via an ``_F_DPLANE_SEQ`` control frame;
      * each side runs ONE executor thread admitting transfers strictly
        in seq order, so both processes enter transfer k's collective
        only after both executed 0..k-1 — the total order is the
        master's assignment order, identical on both ends regardless of
        how the directions interleaved.

    Progress: at the lowest unexecuted seq, the sender half never waits
    on executor progress of the peer (a compiled collective parks inside
    the XLA runtime until the peer joins; the bulk-carried leg's send is
    a plain write), so the receiver half's wait always resolves —
    lockstep advance, no deadlock.

    The assignment stream is valid for exactly one socket incarnation
    (seqs restart at 0 with each fresh HELLO under a new socket id);
    ``epoch`` records the pod epoch at creation for observability —
    "epoch-ordered" means every incarnation's order is anchored to the
    membership epoch it was created under."""

    def __init__(self, sock: "FabricSocket", master: bool,
                 epoch: int = 0):
        import collections
        self.sock = sock
        self.master = master
        self.epoch = epoch
        self._cv = threading.Condition(
            _dbg.make_lock("CollectiveSequencer._lock"))
        self._next_assign = 0            # master's assignment counter
        self._next_exec = 0              # both sides' execution cursor
        self._ready: Dict[int, object] = {}        # seq -> transfer
        self._unassigned: Dict[int, object] = {}   # uuid -> parked send
        self._closed = False
        # uuids in execution order (bounded; the cross-process order-
        # equality assertions in tests/test_pod.py read this)
        self.executed = collections.deque(maxlen=4096)
        # fablint: thread-quiesced(close() sets _closed and notifies; the run loop fails leftovers and exits)
        self._thread = threading.Thread(
            target=self._run_loop,
            name=f"fabric_dplane_seq_{sock.remote_dev}", daemon=True)
        self._thread.start()

    def submit_local(self, t) -> Optional[int]:
        """Admit a transfer THIS side is sending.  Returns the seq to
        encode into the descriptor — the assignment (master) or -1
        (client, parked until the master's _F_DPLANE_SEQ) — or None when
        the sequencer is closed (the caller fails the transfer and falls
        back in-frame)."""
        with self._cv:
            if self._closed:
                return None
            if self.master:
                seq = self._next_assign
                self._next_assign += 1
                self._ready[seq] = t
                self._cv.notify_all()
            else:
                self._unassigned[t.uuid] = t
                seq = -1
        if seq >= 0:
            _dp.plane().annotate_transfer(t, f"seq assigned {seq}")
        else:
            _dp.plane().annotate_transfer(
                t, "seq parked (awaiting master assignment)")
        return seq

    def submit_remote(self, t, seq: int) -> None:
        """Admit a transfer the PEER is sending (its kind-4 descriptor
        just arrived on the control read loop).  The master assigns an
        unassigned (-1) descriptor NOW and tells the peer — control-read
        ordering makes the assignment deterministic."""
        assign = None
        with self._cv:
            if self._closed:
                _dp.plane().fail_transfer(
                    t, "sequencer closed before execution")
                return
            if seq < 0:
                if not self.master:
                    # protocol violation: only the master assigns
                    _dp.plane().fail_transfer(
                        t, "unassigned descriptor at non-master")
                    return
                seq = assign = self._next_assign
                self._next_assign += 1
            self._ready[seq] = t
            self._cv.notify_all()
        _dp.plane().annotate_transfer(t, f"seq assigned {seq}")
        if assign is not None:
            try:
                self.sock._ctrl_send(_F_DPLANE_SEQ,
                                     struct.pack("<Qq", t.uuid, assign))
            except OSError:
                pass     # control death tears the whole socket down

    def on_assignment(self, uuid: int, seq: int) -> None:
        """Client side: the master's _F_DPLANE_SEQ for one of our parked
        sends — the transfer becomes executable at ``seq``."""
        with self._cv:
            t = self._unassigned.pop(uuid, None)
            if t is None:
                return
            if self._closed:
                # close() already ran: the run loop's leftover sweep can
                # no longer see this transfer (we just popped it), so
                # fail it here or the source pin leaks forever
                _dp.plane().fail_transfer(
                    t, "sequencer closed before execution")
                return
            self._ready[seq] = t
            self._cv.notify_all()
        _dp.plane().annotate_transfer(t, f"seq assigned {seq} "
                                         "(master reply)")

    def _run_loop(self) -> None:
        leftovers: List = []
        while True:
            with self._cv:
                while not self._closed \
                        and self._next_exec not in self._ready:
                    self._cv.wait(0.5)
                if self._closed:
                    leftovers = (list(self._ready.values())
                                 + list(self._unassigned.values()))
                    self._ready.clear()
                    self._unassigned.clear()
                    break
                t = self._ready.pop(self._next_exec)
                self._next_exec += 1
            self._execute(t)
        for t in leftovers:
            # teardown: everything still queued/parked can never execute
            # — fail it so completions fire and source pins release
            _dp.plane().fail_transfer(
                t, "socket torn down before execution")

    def _execute(self, t) -> None:
        sock = self.sock
        if sock.failed or sock._peer_gone():
            _dp.plane().fail_transfer(t, "socket failed before execution")
            return
        _dp.plane().annotate_transfer(
            t, "seq admit queue_wait_us="
               f"{(time.monotonic_ns() - t.posted_ns) // 1000}")
        plan = _fi.fabric_active()
        if plan is not None:
            plan.on_plane_op(sock, "device")    # SLOW chaos injector
        try:
            if _dp.xproc_compiled_ok():
                _dp.plane().execute_remote(t)
            else:
                sock._dplane_execute_bulk(t)
            self.executed.append(t.uuid)
        except Exception as e:
            # the transfer is already failed (completion signaled with
            # an error — delivery/claim paths observe it); latch the
            # plane so later frames keep bulk/inline
            sock._device_plane_down(f"execution failed: {e}")

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def describe(self) -> dict:
        with self._cv:
            return {"master": self.master, "epoch": self.epoch,
                    "assigned": self._next_assign,
                    "executed": self._next_exec,
                    "queued": len(self._ready),
                    "awaiting_assignment": len(self._unassigned)}


class FabricSocket(CreditWindow, OrderedDelivery, Socket):
    """Cross-process ici socket: control TCP + transfer-server pulls,
    with the same credit window as the in-process IciSocket."""

    # fablint guarded-state contract: the bulk-plane handle swap
    # commutes under _bulk_lock (the PR-2 review-finding class),
    # staging under _staged_lock, inbox + credit batching under
    # _inbox_lock, device-plane executors under _dplane_lock.
    # The cumulative bulk byte counters are written by concurrent
    # writer threads (multiple streams share one socket) and so live
    # under _bulk_lock too.  Health/revival STATE (down flags, revival
    # wanted/running, the device re-probe latch) lives in the per-plane
    # PlaneHealth records (ici/plane_health.py, its own guard map) —
    # the bulk/shm records share _bulk_lock and the device record
    # shares _dplane_lock, so the old commute guarantees still hold.
    _GUARDED_BY = {
        "_bulk": "_bulk_lock",
        "_blib": "_bulk_lock",
        "_bulk_epoch": "_bulk_lock",
        "_reestab_pending": "_bulk_lock",
        "bulk_bytes_sent": "_bulk_lock",
        "bulk_bytes_claimed": "_bulk_lock",
        "_shm": "_bulk_lock",
        "_shm_dead": "_bulk_lock",
        "_shmlib": "_bulk_lock",
        "_shm_epoch": "_bulk_lock",
        "_shm_ring_bytes": "_bulk_lock",
        "_shm_stripes": "_bulk_lock",
        "_shm_dead_stripes": "_bulk_lock",
        "_shm_reestab_pending": "_bulk_lock",
        "shm_bytes_sent": "_bulk_lock",
        "shm_bytes_claimed": "_bulk_lock",
        "_staged": "_staged_lock",
        "_inbox": "_inbox_lock",
        "_consumed_unacked": "_inbox_lock",
        "_dplane_seq": "_dplane_lock",
        "_dplane_closed": "_dplane_lock",
    }

    def __init__(self, conn: _pysocket.socket, local_dev: int,
                 remote_dev: int, peer_pid: int, node: FabricNode,
                 window_bytes: Optional[int] = None):
        from .mesh import IciMesh
        mesh = IciMesh.default()
        super().__init__(remote_side=mesh.endpoint(remote_dev))
        self.local_side = mesh.endpoint(local_dev)
        self.local_dev = local_dev
        self.remote_dev = remote_dev
        self.peer_pid = peer_pid
        self.node = node
        self._conn = conn
        self._conn_wlock = _dbg.make_lock("FabricSocket._conn_wlock")
        self._inbox = IOBuf()
        self._inbox_lock = _dbg.make_lock("FabricSocket._inbox_lock")
        self.read_chunk_hint = 1 << 26    # _do_read cuts, never allocates
        # input events run the parse loop INLINE on the delivering thread
        # (the control read loop for host frames): a tasklet spawn +
        # park/wake per frame measured ~1/3 of the per-frame fixed cost
        # on the streaming tier.  Order-sensitive stream frames are
        # consumed inside the parse loop as always; full RPC messages
        # are queued to tasklets (queue_last_message) so user handlers
        # can never stall the control channel's CREDIT/PULLED processing.
        self.queue_last_message = True
        self._consumed_unacked = 0     # credits not yet returned (batched)
        self._peer_closed = False      # reader-visible EOF (ordered)
        self._conn_dead = False        # writer-visible death (immediate)
        self._fin_code = 0             # peer's close code (FIN body)
        self._init_window(window_bytes)
        self._init_delivery()
        self._staged: Dict[int, Tuple] = {}    # uuid -> (src_block, array)
        self._staged_lock = _dbg.make_lock("FabricSocket._staged_lock")
        self._reader: Optional[threading.Thread] = None
        self._bulk = 0                         # native bulk conn handle
        self._blib = None
        # bulk-plane self-healing state.  _bulk_lock guards the handle
        # swap (degrade/re-attach race writers and the read loop);
        # the cumulative counters survive re-attachment so tests can
        # assert threshold routing was actually restored.
        self._bulk_lock = _dbg.make_lock("FabricSocket._bulk_lock")
        self._bulk_epoch = 0                   # attachments so far
        self.bulk_bytes_sent = 0               # cumulative, across epochs
        self.bulk_bytes_claimed = 0
        self._reestab_pending: Optional[Tuple] = None   # (lib, handle)
        self._reestab_ok = False
        self._reestab_evt = threading.Event()
        # shm ring tier (same-host peers; bound only after BOTH ends
        # acked the segment at handshake).  Shares _bulk_lock: the two
        # bulk planes' handle swaps commute under one lock and every
        # writer already holds it on this path.
        self._shm = 0                          # native shm conn handle
        self._shm_dead = 0                     # retired ring, claim-only
        self._shmlib = None
        self._shm_epoch = 0                    # attachments so far
        self._shm_ring_bytes = 0               # per-direction capacity
        self._shm_stripes = 1                  # ring pairs in the segment
        self._shm_dead_stripes = 1             # stripes of the retired ring
        # round-robin stripe cursor for unary bulk frames (streams pin
        # a stripe by affinity instead); itertools.count is GIL-atomic
        self._shm_rr = itertools.count().__next__
        self.shm_bytes_sent = 0                # cumulative, across epochs
        self.shm_bytes_claimed = 0
        self._bulk_is_uds = False              # route-counter label only
        self._shm_peer = node.shm_peer_ok(peer_pid)
        self._shm_reestab_pending: Optional[Tuple] = None  # (lib, h, name)
        self._shm_reestab_ok = False
        self._shm_reestab_evt = threading.Event()
        # kind-1 transfer-server staging needs the module on BOTH ends:
        # ours to stage, the peer's to pull.  A peer whose jax build
        # lacks jax.experimental.transfer publishes no "xfer" contact —
        # staging to it would fail its first pull, so such pairs use the
        # inline d2h fallback instead (review finding)
        self._xfer_usable = (node._xfer_server is not None
                             and "xfer" in node.peer_info(peer_pid))
        # cross-process device plane (kind-4): sequenced transfers both
        # processes execute in ONE agreed total order (CollectiveSequencer
        # — compiled collectives on capable backends, bulk-carried
        # elsewhere).  Down-latched on failure with a timed re-probe.
        # Capability advert version 3 = sequenced + traced descriptors
        # (<IqQQ>) under the "dplane3" key; older peers' narrower wire
        # formats are not spoken anymore, and they never send at us
        # either (they key on "dplane"/"dplane2", which v3 no longer
        # publishes).
        self._dplane_peer = \
            node.peer_info(peer_pid).get("dplane3", 0) >= 3
        self._dplane_lock = _dbg.make_lock("FabricSocket._dplane_lock")
        self._dplane_seq: Optional[CollectiveSequencer] = None   # lazy
        self._dplane_closed = False
        self.dplane_bytes_sent = 0         # cumulative, for tests/builtin
        self.dplane_bytes_recv = 0
        self.dplane_fallbacks = 0
        # ---- plane-health records (ici/plane_health.py) ----------------
        # ONE shared engine owns every plane's UP/DOWN/REESTABLISHING
        # bookkeeping, revival policy, and the unified
        # rpc_fabric_plane_* counters; this socket keeps only the
        # MECHANICS (dial, handshake payloads, teardown, native alive
        # probes).  bulk/shm records share _bulk_lock with the handle
        # swap — the instant-death suppression needs health flags and
        # handles deciding under ONE lock hold — and the device record
        # shares _dplane_lock with the sequencer state.
        def _gone():
            return self.failed or self._peer_gone()

        self._plane_bulk = _ph.register_plane(
            "bulk", self._bulk_lock,
            probe=lambda n: bool(self._bulk_alive()),
            gate=lambda: not (self.is_server_side or _gone()),
            prober=self._bulk_revive_attempt,
            attached=lambda: bool(self._bulk),
            dead=_gone,
            thread_name="fabric_bulk_revive",
            seed=self.id ^ 0x5DEECE66D)
        self._plane_shm = _ph.register_plane(
            "shm", self._bulk_lock,
            probe=self.shm_route_usable,
            gate=lambda: not (self.is_server_side or _gone()
                              or not self._shm_peer),
            prober=self._shm_revive_attempt,
            attached=lambda: bool(self._shm),
            dead=_gone,
            thread_name="fabric_shm_revive",
            seed=self.id ^ 0x73686D)
        self._plane_device = _ph.register_plane(
            "device", self._dplane_lock,
            retry_s=lambda: _flags.get_flag("ici_device_plane_retry_s"),
            on_reprobe=lambda: log.info(
                "fabric %s: device plane re-probing", self.remote_side))
        self._plane_xfer = _ph.register_plane(
            "xfer", _dbg.make_lock("FabricSocket._xfer_plane_lock"),
            probe=lambda n: self._xfer_usable,
            retry_s=lambda: _flags.get_flag("ici_device_plane_retry_s"))
        self._planes = {"bulk": self._plane_bulk, "shm": self._plane_shm,
                        "device": self._plane_device,
                        "xfer": self._plane_xfer}

    def _attach_bulk(self, lib, handle: int) -> None:
        """Bind the native bulk data-plane connection (both ends hold one
        fab conn per fabric socket pair; 0 = transfer-server fallback).
        Re-attachment (bulk revival) closes any stale handle and bumps
        the epoch; chaos plans get to poison the fresh conn here."""
        old = 0
        with self._bulk_lock:
            old, self._bulk = self._bulk, handle
            self._blib = lib
            if handle:
                self._bulk_epoch += 1
        if old and lib is not None:
            lib.brpc_tpu_fab_conn_close(old)
        if handle:
            if hasattr(lib, "brpc_tpu_fab_set_peer"):
                # per-pair plane registry: the /ici page and pod
                # observability aggregate native planes by peer pid
                lib.brpc_tpu_fab_set_peer(handle, self.peer_pid)
            plan = _fi.fabric_active()
            if plan is not None:
                plan.on_bulk_attach(self, lib, handle)
            # an INITIAL attach finds the record UP and counts nothing;
            # a re-attach flips DOWN/REESTABLISHING back to UP and arms
            # the breaker's half-open ramp
            self._plane_bulk.revived()

    # ---- bulk-plane degradation + revival ------------------------------
    # Bulk death with a LIVE control channel no longer kills the socket:
    # the handle is dropped (writers route inline / via the transfer
    # server from the next frame on), the peer is told via the plane
    # down-notify frame, and the client side re-establishes in the
    # background.  The STATE machine — down/reestablishing flags,
    # exponential backoff + jitter, instant-death suppression, the
    # unified counters — lives in the shared PlaneHealth engine
    # (ici/plane_health.py); this socket supplies the MECHANICS: one
    # dial-and-handshake attempt (_bulk_revive_attempt) whose fresh
    # parked conn is bound through the revival handshake on the control
    # channel, whose serial ordering guarantees no descriptor can
    # reference the new conn before both ends attached it.

    def bulk_epoch(self) -> int:
        with self._bulk_lock:
            return self._bulk_epoch

    def _bulk_alive(self) -> int:
        """The bulk handle when usable, else 0.  A handle whose native
        conn died is degraded HERE — at a frame boundary, before any
        descriptor references it, which is what lets an in-progress
        stream fall back inline instead of stranding a descriptor whose
        bytes can never arrive."""
        with self._bulk_lock:
            h, lib = self._bulk, self._blib
        if not h:
            return 0
        if lib.brpc_tpu_fab_alive(h):
            return h
        self._bulk_plane_down("bulk conn dead at frame boundary")
        return 0

    def bulk_plane_failed(self) -> None:
        """Receiver-side hook (rpc/stream.py): a bulk claim failed.  The
        affected stream is failed by the caller; the SOCKET survives —
        only the bulk plane degrades and revival begins."""
        self._bulk_plane_down("bulk claim failed")

    def shm_plane_failed(self) -> None:
        """Receiver-side hook (rpc/stream.py): an shm claim failed —
        same socket-survives contract as bulk_plane_failed."""
        self._shm_plane_down("shm claim failed")

    def _bulk_plane_down(self, reason: str, notify: bool = True) -> None:
        with self._bulk_lock:
            h, self._bulk = self._bulk, 0
            lib = self._blib
        if not h:
            return                      # already degraded / never bound
        if lib is not None:
            lib.brpc_tpu_fab_conn_close(h)
        log.warning("fabric %s: bulk plane down (%s) — inline fallback "
                    "engaged", self.remote_side, reason)
        self._plane_bulk.mark_down(reason)
        if notify:
            self._plane_notify_down("bulk")
        # client side only (the engine's gate enforces it): ensure a
        # revival loop is running, at most one at a time
        self._plane_bulk.kick()

    def _plane_notify_down(self, which: str) -> None:
        """Tell the peer the plane died so it degrades too; the
        receiving side degrades with notify=False (no echo ping-pong)."""
        if self._peer_gone():
            return
        try:
            self._ctrl_send(
                _F_BULK_DOWN if which == "bulk" else _F_SHM_DOWN, b"")
        except OSError:
            pass

    def _bulk_revive_attempt(self) -> bool:
        """ONE re-dial + handshake attempt, run by the engine's backoff
        loop: dial a fresh conn, park it pending, and ask the server to
        claim it; the attach itself happens on the read loop
        (_on_bulk_reply) so descriptor ordering holds."""
        h, key, lib, is_uds = self.node.dial_bulk(self.peer_pid)
        if not h:
            return False
        self._bulk_is_uds = is_uds
        self._reestab_evt.clear()
        self._reestab_ok = False
        with self._bulk_lock:
            self._reestab_pending = (lib, h)
        try:
            self._ctrl_send(_F_BULK_REESTABLISH,
                            json.dumps({"bulk_key": key}).encode())
            ok = self._reestab_evt.wait(5.0) and self._reestab_ok
        except OSError:
            ok = False
        if ok:
            log.info("fabric %s: bulk plane re-established (epoch %d)",
                     self.remote_side, self.bulk_epoch())
            return True
        # timed out / refused: reclaim the pending handle unless the
        # read loop already attached it
        with self._bulk_lock:
            pending, self._reestab_pending = self._reestab_pending, None
        if pending is not None:
            lib.brpc_tpu_fab_conn_close(h)
        return False

    def _on_bulk_reestablish(self, req: dict) -> None:
        """Server side: claim the conn the client re-parked on our bulk
        listener and attach it; runs on the control read loop so the
        attach is ordered BEFORE any descriptor that will use it."""
        key = req.get("bulk_key")
        node = self.node
        ok = False
        plan = _fi.fabric_active()
        if plan is not None and plan.on_bulk_handshake(self):
            node._reap_parked_bulk(key)          # refuse deterministically
        elif key and node._bulk_listener and node._bulk_lib is not None:
            h = node._bulk_lib.brpc_tpu_fab_accept(
                node._bulk_listener, key.encode(), 2_000_000)
            if h:
                self._attach_bulk(node._bulk_lib, h)
                ok = True
        try:
            self._ctrl_send(_F_BULK_OK if ok else _F_BULK_ERR, b"")
        except OSError:
            pass

    def _on_bulk_reply(self, ok: bool) -> None:
        """Client side: _F_BULK_OK/_F_BULK_ERR from the server.  The
        attach happens HERE on the read loop — a descriptor following
        BULK_OK on the serial control channel then always finds the new
        handle bound."""
        with self._bulk_lock:
            pending, self._reestab_pending = self._reestab_pending, None
        if ok and pending is not None:
            self._attach_bulk(*pending)
        elif pending is not None:
            pending[0].brpc_tpu_fab_conn_close(pending[1])
            ok = False
        self._reestab_ok = ok and pending is not None
        self._reestab_evt.set()

    # ---- shm ring tier: attach / degrade / revive ----------------------
    # Mirrors the bulk-plane self-healing above: ring death with a live
    # control channel degrades to the socket bulk tier (route table),
    # the peer is told via the plane down-notify frame, and the CLIENT
    # side (the end that created the original segment) re-creates one
    # in the background — the same shared PlaneHealth engine drives the
    # state/backoff, this socket supplies one create-and-handshake
    # attempt (_shm_revive_attempt) whose serial control ordering
    # guarantees no kind-5/6 descriptor can reference the new ring
    # before both ends attached it.

    def _attach_shm(self, lib, handle: int) -> None:
        """Bind the shm ring pair (0 = no shm tier).  Re-attachment
        closes any stale handle and bumps the epoch; chaos plans get to
        poison the fresh ring here."""
        old = 0
        ring_bytes = 0
        stripes = 1
        if handle:
            st = (ctypes.c_uint64 * 6)()
            if lib.brpc_tpu_shm_stats(handle, st, 6) == 6:
                ring_bytes = int(st[5])
            if hasattr(lib, "brpc_tpu_shm_stripes"):
                stripes = int(lib.brpc_tpu_shm_stripes(handle)) or 1
        with self._bulk_lock:
            old, self._shm = self._shm, handle
            self._shmlib = lib
            if handle:
                self._shm_epoch += 1
                self._shm_ring_bytes = ring_bytes
                self._shm_stripes = stripes
        if old and lib is not None:
            lib.brpc_tpu_shm_close(old)
        if handle:
            plan = _fi.fabric_active()
            if plan is not None:
                plan.on_shm_attach(self, lib, handle)
            # initial attach: no-op (record UP); re-attach: revival
            self._plane_shm.revived()

    def shm_bound(self) -> bool:
        with self._bulk_lock:
            return bool(self._shm)

    def shm_epoch(self) -> int:
        with self._bulk_lock:
            return self._shm_epoch

    def _shm_alive(self) -> int:
        """The shm handle when usable, else 0 — death is detected HERE,
        at a frame boundary, before any descriptor references the ring
        (the same degradation discipline as _bulk_alive)."""
        with self._bulk_lock:
            h, lib = self._shm, self._shmlib
        if not h:
            return 0
        if lib.brpc_tpu_shm_alive(h):
            return h
        self._shm_plane_down("shm ring dead at frame boundary")
        return 0

    def shm_route_usable(self, nbytes: int) -> bool:
        """Route-table health/capability probe: a live ring the payload
        is GUARANTEED to fit (an oversize payload skips shm WITHOUT
        degrading it — the ring is healthy, just small).  The bound is
        half the ring: a frame over ring/2 can land at a wrap position
        where remainder + footprint exceeds the ring and never fits no
        matter how far the consumer drains (the native send returns -3
        there — kept as the belt under this screen)."""
        with self._bulk_lock:
            h, ring = self._shm, self._shm_ring_bytes
        if not h:
            return False
        if ring and nbytes + 48 > ring // 2:
            return False
        return bool(self._shm_alive())

    def _shm_plane_down(self, reason: str, notify: bool = True) -> None:
        with self._bulk_lock:
            h, self._shm = self._shm, 0
            lib = self._shmlib
            old_dead = 0
            if h:
                # the retired ring stays CLAIMABLE (marked dead, not
                # closed): descriptors already flushed — or batched and
                # about to flush — reference bytes that are PUBLISHED
                # and parked in it, and the serial control channel may
                # deliver them to us after the shm down-notify that
                # caused this call.  Closing here would strand those claims
                # (rc -2) and kill their streams even though every byte
                # is sitting in the mapping.  Bounded at one retired
                # ring: a second death closes the first.
                old_dead, self._shm_dead = self._shm_dead, h
                # the retired ring keeps ITS stripe geometry for claims
                self._shm_dead_stripes = self._shm_stripes
                self._shm_stripes = 1
        if not h:
            return                      # already degraded / never bound
        if lib is not None:
            lib.brpc_tpu_shm_mark_dead(h)
            if old_dead:
                lib.brpc_tpu_shm_close(old_dead)
        log.warning("fabric %s: shm ring down (%s) — socket bulk tier "
                    "engaged", self.remote_side, reason)
        self._plane_shm.mark_down(reason)
        if notify:
            self._plane_notify_down("shm")
        # client side only (the end that created the original segment;
        # the engine's gate enforces it): ensure one revival loop
        self._plane_shm.kick()

    def _shm_revive_attempt(self) -> bool:
        """ONE re-create + handshake attempt, run by the engine's
        backoff loop: create a fresh segment, park it pending, and ask
        the server to attach it; our own attach happens on the read
        loop (_on_shm_reply) so descriptor ordering holds."""
        h, name, lib = self.node.create_shm_segment()
        if not h:
            return False
        self._shm_reestab_evt.clear()
        self._shm_reestab_ok = False
        with self._bulk_lock:
            self._shm_reestab_pending = (lib, h, name)
        try:
            self._ctrl_send(_F_SHM_REESTABLISH,
                            json.dumps({"shm_seg": name}).encode())
            ok = self._shm_reestab_evt.wait(5.0) and self._shm_reestab_ok
        except OSError:
            ok = False
        if ok:
            log.info("fabric %s: shm ring re-established (epoch %d)",
                     self.remote_side, self.shm_epoch())
            return True
        with self._bulk_lock:
            pending, self._shm_reestab_pending = \
                self._shm_reestab_pending, None
        if pending is not None:
            self.node.drop_shm_segment(pending[1], pending[2])
        return False

    def _on_shm_reestablish(self, req: dict) -> None:
        """Server side: attach the fresh segment the client created;
        runs on the control read loop so the attach is ordered BEFORE
        any descriptor that will use it."""
        name = req.get("shm_seg")
        node = self.node
        ok = False
        plan = _fi.fabric_active()
        if plan is not None and plan.on_shm_handshake(self):
            pass                                 # refuse deterministically
        elif name and node._shm_ok and node._shm_lib is not None \
                and _flags.get_flag("ici_fabric_shm"):
            h = node._shm_lib.brpc_tpu_shm_attach(name.encode())
            if h:
                node._shm_lib.brpc_tpu_shm_unlink(name.encode())
                self._attach_shm(node._shm_lib, h)
                ok = True
        try:
            self._ctrl_send(_F_SHM_OK if ok else _F_SHM_ERR, b"")
        except OSError:
            pass

    def _on_shm_reply(self, ok: bool) -> None:
        """Client side: _F_SHM_OK/_F_SHM_ERR.  The attach happens HERE
        on the read loop (descriptor-ordering, same as _on_bulk_reply)."""
        with self._bulk_lock:
            pending, self._shm_reestab_pending = \
                self._shm_reestab_pending, None
        if ok and pending is not None:
            self._attach_shm(pending[0], pending[1])
        elif pending is not None:
            self.node.drop_shm_segment(pending[1], pending[2])
            ok = False
        self._shm_reestab_ok = ok and pending is not None
        self._shm_reestab_evt.set()

    def _on_plane_frame(self, which: str, op: int, body: bytes) -> None:
        """One read-loop dispatch row for both self-healing planes
        (_PLANE_FRAMES).  op 0: the peer observed the plane's death
        first — degrade without echoing (no notify ping-pong); the
        client side starts revival.  op 1: the client parked/created a
        fresh plane — the server attaches it HERE on the read loop, so
        the attach is ordered BEFORE any descriptor that will use it.
        op 2/3: the server's ok/err reply to our pending attempt."""
        if op == 0:
            if which == "bulk":
                self._bulk_plane_down(f"peer reported {which} death",
                                      notify=False)
            else:
                self._shm_plane_down(f"peer reported {which} death",
                                     notify=False)
        elif op == 1:
            req = json.loads(body)
            if which == "bulk":
                self._on_bulk_reestablish(req)
            else:
                self._on_shm_reestablish(req)
        else:
            if which == "bulk":
                self._on_bulk_reply(op == 2)
            else:
                self._on_shm_reply(op == 2)

    def _close_shm(self) -> None:
        """Socket-level teardown of the shm tier (no revival).  Claimed
        zero-copy views stay readable — the native side defers the unmap
        until the last release."""
        with self._bulk_lock:
            h, self._shm = self._shm, 0
            dead_h, self._shm_dead = self._shm_dead, 0
            pending, self._shm_reestab_pending = \
                self._shm_reestab_pending, None
            lib = self._shmlib
        if lib is not None:
            if h:
                lib.brpc_tpu_shm_close(h)
            if dead_h:
                lib.brpc_tpu_shm_close(dead_h)
        if pending is not None:
            self.node.drop_shm_segment(pending[1], pending[2])
        self._shm_reestab_evt.set()    # unblock a parked revival thread

    def describe_shm(self) -> Optional[dict]:
        """Ring-tier snapshot for the /ici builtin: byte totals, epoch,
        occupancy and doorbell waits from the native side."""
        with self._bulk_lock:
            h, lib = self._shm, self._shmlib
            stripes = self._shm_stripes
            out = {"epoch": self._shm_epoch,
                   "bytes_sent": self.shm_bytes_sent,
                   "bytes_claimed": self.shm_bytes_claimed,
                   "ring_bytes": self._shm_ring_bytes,
                   "stripes": stripes}
        if not h and not out["epoch"]:
            return None
        if h and lib is not None:
            st = (ctypes.c_uint64 * 6)()
            if lib.brpc_tpu_shm_stats(h, st, 6) == 6:
                out.update({"tx_occupancy": int(st[2]),
                            "rx_occupancy": int(st[3]),
                            "doorbell_waits": int(st[4])})
            if stripes > 1 and hasattr(lib, "brpc_tpu_shm_stripe_stats"):
                per = []
                for i in range(stripes):
                    if lib.brpc_tpu_shm_stripe_stats(h, i, st, 6) == 6:
                        per.append({"bytes_out": int(st[0]),
                                    "bytes_in": int(st[1]),
                                    "tx_occupancy": int(st[2]),
                                    "rx_occupancy": int(st[3]),
                                    "doorbell_waits": int(st[4])})
                out["stripe_stats"] = per
        return out

    # ---- device plane (kind-4 sequenced transfers) ---------------------
    def _dplane_usable(self, nbytes: int) -> bool:
        """Route this device payload through the sequenced cross-process
        device plane?  Needs the master+xproc flags, a peer that
        advertised the (v2, sequenced) capability, an eligible
        size/platform, a byte mover (the bulk plane, when this backend
        has no compiled multi-controller collectives), and a plane that
        is not down-latched (a lapsed latch re-probes)."""
        if not _flags.get_flag("ici_device_plane_xproc"):
            return False
        if not self._dplane_peer or not _dp.eligible(nbytes):
            return False
        if not _dp.xproc_compiled_ok() and not self._bulk_alive():
            return False       # bulk-carried leg needs a live bulk plane
        # the down-latch + lapsed-latch re-probe is the engine's
        # timer policy (the record shares _dplane_lock)
        return self._plane_device.usable(nbytes)

    def _dplane_sequencer(self) -> Optional["CollectiveSequencer"]:
        """The socket's (lazily created) collective sequencer; None after
        teardown.  Master role = server side, so exactly one end of the
        pair assigns."""
        with self._dplane_lock:
            if self._dplane_closed:
                return None
            seqr = self._dplane_seq
            if seqr is None:
                epoch = 0
                try:
                    from .pod import Pod
                    pod = Pod.current()
                    if pod is not None:
                        epoch = pod.epoch()
                except Exception:
                    pass
                seqr = self._dplane_seq = CollectiveSequencer(
                    self, master=self.is_server_side, epoch=epoch)
            return seqr

    def _device_plane_down(self, reason: str) -> None:
        """Degrade: device payloads ride the PR-2 bulk/inline machinery
        from the next frame until the re-probe deadline lapses (the
        engine's timer policy re-arms the deadline on repeat failures
        while counting/logging only the actual transition)."""
        first = self._plane_device.mark_down(reason)
        self.dplane_fallbacks += 1
        if first:
            log.warning("fabric %s: device plane down (%s) — bulk/inline "
                        "fallback engaged, re-probe in %.1fs",
                        self.remote_side, reason,
                        _flags.get_flag("ici_device_plane_retry_s"))

    def _dplane_execute_bulk(self, t) -> None:
        """The bulk-carried xproc leg: this backend has no compiled
        multi-controller collectives (the CPU jaxlib raises on them), so
        the payload's bytes cross on the native bulk plane under the
        SEQUENCED uuid — identical descriptors, total order, source
        pins, and CQ completions as the compiled leg; only the byte
        mover differs.  Runs on the sequencer's executor at this
        transfer's slot in the total order.  Failure fails the transfer
        (completion fires, pin releases) and re-raises so the plane
        latches down."""
        import numpy as np
        arr = t.source_array()
        try:
            if arr is not None:                    # sender half
                np_arr = np.asarray(arr)
                if not np_arr.flags["C_CONTIGUOUS"]:
                    np_arr = np.ascontiguousarray(np_arr)
                self._bulk_send(t.uuid, np_arr)
                _dp.plane().finish_remote(t, None)
            else:                                  # receiver half
                ca = self._claim_zero_copy(t.uuid, t.nbytes)
                with self._bulk_lock:
                    self.bulk_bytes_claimed += t.nbytes
                host = np.frombuffer(ca, dtype=np.uint8)
                if _flags.get_flag("ici_fabric_host_delivery"):
                    out = host                # zero-copy host delivery
                else:
                    import jax
                    owned = host.copy()
                    del host, ca              # owner releases the buffer
                    out = jax.device_put(
                        owned, _dp.plane().mesh().device(t.dst_dev))
                _dp.plane().finish_remote(t, out)
        except Exception as e:
            _dp.plane().fail_transfer(
                t, f"bulk-carried transfer failed: {e}")
            raise

    def _close_dplane(self) -> None:
        with self._dplane_lock:
            self._dplane_closed = True
            seqr = self._dplane_seq
        if seqr is not None:
            seqr.close()

    def describe_dplane_sequencer(self) -> Optional[dict]:
        """Locked snapshot of the sequencer state for the /ici builtin
        page (honors the _dplane_seq guarded-state contract)."""
        with self._dplane_lock:
            seqr = self._dplane_seq
        return None if seqr is None else seqr.describe()

    # ---- the route table's plane-health gate ---------------------------
    def plane_usable(self, plane: str, nbytes: int = 0) -> bool:
        """ONE health/capability gate for route.candidates(): engine
        state first (a down plane is skipped without probing; a lapsed
        timer latch re-probes), then the plane's own capability probe
        (ring fit, native alive check, xfer contact)."""
        rec = self._planes.get(plane)
        return rec is not None and rec.usable(nbytes)

    def _xfer_plane_down(self, reason: str) -> None:
        """Degrade the transfer-server route (today only chaos plans
        refusing a stage drive this): the xfer record rides the same
        timer-latch revival as the device plane, so a refused stage
        falls through in-frame and the route returns after the
        re-probe window."""
        if self._plane_xfer.mark_down(reason):
            log.warning("fabric %s: xfer plane down (%s) — inline "
                        "fallback engaged", self.remote_side, reason)

    def describe_planes(self) -> dict:
        """Per-plane health snapshots for the /ici builtin ``planes``
        block (state/reason/down_epoch/reprobe_in per plane)."""
        return {name: rec.snapshot()
                for name, rec in self._planes.items()}

    def start_io(self) -> None:
        self._reader = threading.Thread(target=self._read_loop,
                                        name="fabric_read", daemon=True)
        self._reader.start()

    def quiesce_reader(self, timeout: float = 2.0) -> None:
        """Deterministic teardown ordering: sever the control conn and
        JOIN the reader thread, so no fabric thread can race interpreter
        or C++ static teardown (the exit-abort class of flake).  Called
        from Server.stop after the socket failed, and from the process
        atexit quiesce."""
        try:
            self._conn.shutdown(_pysocket.SHUT_RDWR)
        except OSError:
            pass
        r = self._reader
        if r is not None and r.is_alive() \
                and r is not threading.current_thread():
            r.join(timeout)

    # ---- lame-duck (GOODBYE) -------------------------------------------
    def send_goodbye(self) -> None:
        """Server drain: tell the peer this endpoint is going lame-duck
        so it pulls it from LBs proactively instead of discovering the
        drain at the next health-check probe."""
        if self._peer_gone():
            return
        try:
            self._ctrl_send(_F_GOODBYE, b"")
        except OSError:
            pass

    def _on_goodbye(self) -> None:
        # runs on the control read loop of the RECEIVING side: stop
        # handing this socket out for new calls (SocketMap replaces
        # logoff sockets on next use) while in-flight responses and
        # stream frames keep flowing, and register the peer's drain
        self.logoff = True
        try:
            from ..rpc import lameduck
            lameduck.notify_peer_draining(self.remote_side)
        except Exception:
            pass

    def inflight_send_blocks(self) -> int:
        with self._staged_lock:
            return len(self._staged)

    def _peer_gone(self) -> bool:
        return self._peer_closed or self._conn_dead

    # ---- write path ----------------------------------------------------
    def _ctrl_send(self, ftype: int, body: bytes) -> None:
        """Every outbound control frame funnels through here: the one
        place the chaos harness can drop a frame (lossy link) or sever
        the control TCP (peer reset) deterministically."""
        plan = _fi.fabric_active()
        if plan is not None:
            action = plan.on_control_send(self)
            if action == _fi.DROP:
                return                   # bytes vanish
            if action == _fi.ERROR:
                # sever both directions: our read loop observes the
                # reset and runs the connection-over path, exactly as a
                # mid-conversation RST would
                try:
                    self._conn.shutdown(_pysocket.SHUT_RDWR)
                except OSError:
                    pass
                raise ConnectionError("fabric control channel: "
                                      "injected sever")
        if ftype in _PLANE_FRAMES and _rdump.dump_enabled():
            # A/B parity seam: the plane-healing handshake, as sent
            _rdump.maybe_dump_fabric_frame(self, "out", ftype, body)
        with self._conn_wlock:
            _send_frame(self._conn, ftype, body)

    def _do_write(self, data: IOBuf) -> int:
        n = self._consume_window(len(data))
        if n < 0:
            return -1
        frame = data.cut(n)
        body = self._encode_data(frame)
        try:
            self._ctrl_send(_F_DATA, body)
        except OSError as e:
            raise ConnectionError(f"fabric control channel: {e}")
        return n

    def _encode_data(self, frame: IOBuf) -> bytes:
        """Serialize a frame: host refs inline, DEVICE refs out-of-band.
        Byte-mover selection goes through the route table (ici/route.py
        — payload class × size × peer capability × plane health):
        same-host pairs prefer the shm ring (kind 5 device / kind 6
        host; one copy into shared memory, zero-copy claim), then the
        socket bulk conn (kind 2/3; synchronous-send custody), then
        transfer-server staging for device payloads (kind 1; pinned
        until the PULLED ack), then inline (kind 0).

        Degradation: every fast-plane use is health-gated and a failed
        send falls through to the NEXT route WITHIN the same frame —
        nothing is committed to the control stream until its bytes are
        already with a transport, so a dying plane can never strand a
        descriptor."""
        out = [b""]
        nchunks = 0
        pending_host: List[bytes] = []

        def flush_host():
            nonlocal nchunks
            if not pending_host:
                return
            blob = b"".join(pending_host)
            pending_host.clear()
            nchunks += 1
            for rt in _route.candidates(self, _route.HOST, len(blob)):
                if rt == _route.SHM:
                    uuid = self.shm_tag_uuid(self.node.next_uuid())
                    try:
                        self._shm_send(uuid, blob)
                    except _ShmOversize:
                        continue
                    except ConnectionError:
                        self._shm_plane_down("shm send failed mid-encode")
                        continue
                    out.append(struct.pack("<BQQ", 6, uuid, len(blob)))
                    _route.record(self, rt, len(blob))
                    return
                if rt == _route.BULK:
                    uuid = self.node.next_uuid()
                    try:
                        self._bulk_send(uuid, blob)
                    except ConnectionError:
                        self._bulk_plane_down(
                            "bulk send failed mid-encode")
                        continue
                    out.append(struct.pack("<BQQ", 3, uuid, len(blob)))
                    _route.record(self, rt, len(blob))
                    return
                break                              # INLINE
            out.append(struct.pack("<BI", 0, len(blob)))
            out.append(blob)
            _route.record(self, _route.INLINE, len(blob))

        for i in range(frame.backing_block_num()):
            r = frame.backing_block(i)
            if r.block.kind != DEVICE:
                pending_host.append(
                    bytes(r.block.host_view(r.offset, r.length)))
                continue
            arr = r.block.data
            if r.offset or r.length != len(arr):
                arr = arr[r.offset:r.offset + r.length]
            kind = 0
            # device plane first (kind 4): the payload crosses through
            # the sequenced xproc plane — a compiled XLA program both
            # processes enter in the agreed total order (or its
            # bulk-carried leg on backends without multi-controller
            # collectives).  A refused post degrades to the bulk/inline
            # machinery below WITHIN this same frame (the
            # descriptor-consistency rule: nothing is committed to the
            # control stream until its transport is decided).
            dplane_src = -1
            dplane_seq = -1
            dplane_trace = (0, 0)
            if (hasattr(arr, "devices")
                    and self._dplane_usable(r.length)):
                # the route's true source is wherever the array LIVES —
                # a process owns several devices and the receiver must
                # compile the identical (src, dst) submesh program, so
                # src rides the descriptor
                src_idx = _dp.mesh_index_of(arr)
                if src_idx >= 0 and src_idx != self.remote_dev:
                    try:
                        t = _dp.plane().post_send(
                            arr, src_idx, self.remote_dev,
                            socket=self, uuid=self.node.next_uuid(),
                            remote=True)
                        t.add_source_release(
                            getattr(r.block, "on_send_complete", None))
                        seqr = self._dplane_sequencer()
                        assigned = (seqr.submit_local(t)
                                    if seqr is not None else None)
                        if assigned is None:
                            # torn down between usable-check and submit:
                            # fail the posted WR (pin releases) and fall
                            # back in this same frame
                            _dp.plane().fail_transfer(
                                t, "sequencer closed before submit")
                            raise _dp.DevicePlaneError(
                                "device-plane sequencer closed")
                        dplane_seq = assigned
                        uuid = t.uuid
                        dplane_src = src_idx
                        dplane_trace = (t.trace_id, t.parent_span_id)
                        kind = 4
                        self.dplane_bytes_sent += r.length
                    except _dp.DevicePlaneError as e:
                        self._device_plane_down(str(e))
            if kind == 0:
                for rt in _route.candidates(self, _route.DEVICE,
                                            r.length):
                    if rt in (_route.SHM, _route.BULK):
                        # device -> host staging (on CPU backends a
                        # zero-copy view; on TPU the D2H leg of a
                        # host-staged fabric)
                        import numpy as np
                        np_arr = np.asarray(arr)
                        if not np_arr.flags["C_CONTIGUOUS"]:
                            np_arr = np.ascontiguousarray(np_arr)
                        uuid = self.node.next_uuid()
                        try:
                            if rt == _route.SHM:
                                uuid = self.shm_tag_uuid(uuid)
                                self._shm_send(uuid, np_arr)
                                kind = 5
                            else:
                                self._bulk_send(uuid, np_arr)
                                kind = 2
                        except _ShmOversize:
                            continue
                        except ConnectionError:
                            if rt == _route.SHM:
                                self._shm_plane_down(
                                    "shm send failed mid-encode")
                            else:
                                self._bulk_plane_down(
                                    "bulk send failed mid-encode")
                            continue
                        _route.record(self, rt, r.length)
                        # synchronous-send custody: the kernel/ring owns
                        # a copy, the source block is reusable now
                        cb = getattr(r.block, "on_send_complete", None)
                        if cb is not None:
                            try:
                                cb()
                            except Exception:
                                pass
                        break
                    if rt == _route.XFER:
                        plan = _fi.fabric_active()
                        if plan is not None and plan.on_xfer_stage(self):
                            # injected refusal: degrade the xfer record
                            # and fall through IN-FRAME (nothing is
                            # committed yet), like the planes above
                            self._xfer_plane_down("injected stage refusal")
                            continue
                        if not hasattr(arr, "devices"):
                            # forwarding a host-delivered numpy over an
                            # xfer-mode socket: the transfer server
                            # stages jax arrays only — detach into an
                            # owned copy (aliasing a ctypes-backed view
                            # is unsafe)
                            import jax
                            import numpy as np
                            arr = jax.device_put(
                                np.array(arr, copy=True),
                                jax.devices()[self.local_dev])
                        uuid = self.node.next_uuid()
                        self.node.stage(uuid, [arr])
                        with self._staged_lock:
                            self._staged[uuid] = (r.block, arr)
                        kind = 1
                        _route.record(self, rt, r.length)
                        break
                    break                          # INLINE
            if kind == 0:
                # neither fast plane: the device payload crosses as plain
                # host bytes on the control channel (d2h here, h2d on
                # first use at the peer — the same residency contract as
                # host delivery)
                pending_host.append(
                    bytes(r.block.host_view(r.offset, r.length)))
                continue
            flush_host()
            dt = str(arr.dtype).encode()
            shape = arr.shape
            out.append(struct.pack("<BQH", kind, uuid, len(dt)))
            out.append(dt)
            out.append(struct.pack("<B", len(shape)))
            out.append(struct.pack("<%dQ" % len(shape), *shape)
                       if shape else b"")
            out.append(struct.pack("<Q", r.length))
            if kind == 4:
                # src device + the sequencer's total-order slot (-1 when
                # this side is the client: the master assigns on receipt
                # and answers with _F_DPLANE_SEQ) + the trace context the
                # transfer belongs to (0,0 when the RPC wasn't sampled):
                # the RECEIVER parents its transfer span under the same
                # RPC span, so both halves land in one stitched trace
                out.append(struct.pack("<IqQQ", dplane_src, dplane_seq,
                                       dplane_trace[0], dplane_trace[1]))
            nchunks += 1
        flush_host()
        out[0] = struct.pack("<I", nchunks)
        return b"".join(out)

    def _bulk_send(self, uuid: int, data) -> None:
        """Blocking bulk-plane send (the GIL is dropped for the native
        write).  ``data``: bytes or a C-contiguous numpy array."""
        if isinstance(data, (bytes, bytearray)):
            ptr = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) \
                if isinstance(data, bytearray) else \
                ctypes.cast(data, _u8p)
            n = len(data)
        else:
            ptr = data.ctypes.data_as(_u8p)
            n = data.nbytes
        with self._bulk_lock:
            h, lib = self._bulk, self._blib
        rc = lib.brpc_tpu_fab_send(h, uuid, ptr, n) if h else -1
        if rc != 0:
            raise ConnectionError("fabric bulk channel closed")
        with self._bulk_lock:
            # concurrent writers (streams share the socket) race this
            # cumulative counter; unguarded += lost updates (fablint)
            self.bulk_bytes_sent += n

    def shm_tag_uuid(self, uuid: int,
                     affinity: Optional[int] = None) -> int:
        """Stamp the chosen stripe into the uuid's top byte — the
        descriptor carries it to the claimer, so no wire format
        changes.  ``affinity`` pins a stripe (streams pass their stream
        id: per-stream ordering is decided by ONE ring); unary bulk
        frames round-robin.  A 1-stripe segment leaves the uuid
        untouched — the PR-10 shape, byte-identical."""
        with self._bulk_lock:
            n = self._shm_stripes
        if n <= 1:
            return uuid
        stripe = (affinity if affinity is not None
                  else self._shm_rr()) % n
        return (uuid & ~(0xff << _SHM_STRIPE_SHIFT)) | \
            (stripe << _SHM_STRIPE_SHIFT)

    @staticmethod
    def _shm_stripe_of(uuid: int, nstripes: int) -> int:
        """Decode the stripe a tagged uuid names; clamped so a
        malformed tag can never index out of range."""
        if nstripes <= 1:
            return 0
        return min(uuid >> _SHM_STRIPE_SHIFT, nstripes - 1)

    def _shm_send(self, uuid: int, data) -> None:
        """Blocking shm ring send (the GIL is dropped for the native
        copy; a full ring parks on the futex doorbell).  ``data``:
        bytes or a C-contiguous numpy array.  Raises _ShmOversize when
        the frame can never fit the ring (route elsewhere; the ring is
        healthy) and ConnectionError on death/timeout (degrade).  The
        uuid's top byte names the stripe (shm_tag_uuid)."""
        plan = _fi.fabric_active()
        if plan is not None:
            plan.on_plane_op(self, "shm")      # SLOW chaos injector
        if isinstance(data, (bytes, bytearray)):
            ptr = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) \
                if isinstance(data, bytearray) else \
                ctypes.cast(data, _u8p)
            n = len(data)
        else:
            ptr = data.ctypes.data_as(_u8p)
            n = data.nbytes
        with self._bulk_lock:
            h, lib, stripes = self._shm, self._shmlib, self._shm_stripes
        timeout_us = int(
            _flags.get_flag("ici_shm_send_timeout_s") * 1e6)
        if not h:
            rc = -1
        elif stripes > 1:
            stripe = self._shm_stripe_of(uuid, stripes)
            rc = lib.brpc_tpu_shm_send2(h, stripe, uuid, ptr, n,
                                        timeout_us)
            if rc == 0:
                _route.record_shm_stripe(stripe, n)
        else:
            rc = lib.brpc_tpu_shm_send(h, uuid, ptr, n, timeout_us)
        if rc == -3:
            raise _ShmOversize()
        if rc != 0:
            raise ConnectionError("fabric shm ring closed")
        with self._bulk_lock:
            self.shm_bytes_sent += n

    # ---- stream fast plane ---------------------------------------------
    # Stream DATA frames above ici_stream_bulk_threshold post their
    # payload here (rpc/stream.py): bytes ride the dedicated bulk
    # connection under a reserved uuid, only a 16-byte descriptor rides
    # the control channel.  Custody is synchronous-send (the kernel owns
    # a copy when sendv returns) and delivery is zero-copy host-resident
    # (the claimed IOBuf wraps the native receive buffer) — the same
    # contract as the kind-2/3 attachment path above.

    def stream_fast_begin(self, nbytes: int,
                          affinity: Optional[int] = None
                          ) -> Tuple[int, Optional[str]]:
        """Route one stream DATA frame of ``nbytes``: (uuid, route) with
        route "shm"/"bulk", or (0, None) to keep the inline path.  The
        liveness check here is what lets a stream survive plane death: a
        dead plane is detected BEFORE the descriptor goes out, so the
        frame — and every later one until revival — rides the next tier
        instead.  ``affinity`` (the stream id) pins shm frames to one
        stripe so per-stream ordering is decided by a single ring."""
        for rt in _route.candidates(self, _route.STREAM, nbytes):
            if rt == _route.SHM:
                return self.shm_tag_uuid(self.node.next_uuid(),
                                         affinity), rt
            if rt == _route.BULK:
                return self.node.next_uuid(), rt
            break
        return 0, None

    def stream_bulk_begin(self) -> int:
        """Legacy single-plane reservation (bulk only); kept for callers
        that pin the socket bulk tier explicitly."""
        if not self._bulk_alive():
            return 0
        return self.node.next_uuid()

    def _gather_blocks(self, frame: IOBuf):
        """(ptrs, lens, n, total, keep) for a gather send — keep pins
        the block buffers until the native call returns."""
        import numpy as np
        nblocks = frame.backing_block_num()
        ptrs = (ctypes.c_void_p * nblocks)()
        lens = (ctypes.c_uint64 * nblocks)()
        keep = []                      # buffers must outlive the write
        n = 0
        total = 0
        for i in range(nblocks):
            r = frame.backing_block(i)
            if not r.length:
                continue
            a = np.frombuffer(r.block.host_view(r.offset, r.length),
                              dtype=np.uint8)
            keep.append(a)
            ptrs[n] = a.ctypes.data
            lens[n] = r.length
            total += r.length
            n += 1
        return ptrs, lens, n, total, keep

    def stream_fast_send(self, route: str, uuid: int,
                         frame: IOBuf) -> None:
        """Gather-send the frame's blocks as ONE uuid-tagged frame on
        the chosen plane, zero-copy hand-off (the native call drops the
        GIL; synchronous-send custody either way)."""
        if route == _route.SHM:
            ptrs, lens, n, total, keep = self._gather_blocks(frame)
            with self._bulk_lock:
                h, lib, stripes = self._shm, self._shmlib, \
                    self._shm_stripes
            timeout_us = int(
                _flags.get_flag("ici_shm_send_timeout_s") * 1e6)
            if not h:
                rc = -1
            elif stripes > 1:
                stripe = self._shm_stripe_of(uuid, stripes)
                rc = lib.brpc_tpu_shm_sendv2(h, stripe, uuid, ptrs,
                                             lens, n, timeout_us)
                if rc == 0:
                    _route.record_shm_stripe(stripe, total)
            else:
                rc = lib.brpc_tpu_shm_sendv(h, uuid, ptrs, lens, n,
                                            timeout_us)
            del keep
            if rc != 0:
                # descriptor already on the control channel: the peer's
                # claim fails and closes THAT stream; the socket only
                # degrades (rc -3 cannot happen: stream_fast_begin
                # screened the frame against the ring capacity)
                self._shm_plane_down("shm sendv failed")
                raise ConnectionError("fabric shm ring closed")
            with self._bulk_lock:
                self.shm_bytes_sent += total
            _route.record(self, _route.SHM, total)
            return
        self.stream_bulk_send(uuid, frame)
        _route.record(self, _route.BULK, len(frame))

    def stream_bulk_send(self, uuid: int, frame: IOBuf) -> None:
        """Gather-send the frame's blocks as ONE uuid-tagged bulk frame,
        zero-copy: block buffers are handed to writev as-is (fab_sendv
        drops the GIL; synchronous-send custody)."""
        ptrs, lens, n, total, keep = self._gather_blocks(frame)
        with self._bulk_lock:
            h, lib = self._bulk, self._blib
        rc = lib.brpc_tpu_fab_sendv(h, uuid, ptrs, lens, n) if h else -1
        del keep
        if rc != 0:
            # the descriptor for this frame is already on the control
            # channel: the peer's claim will fail and close THAT stream
            # (descriptor-consistency rule); this socket only degrades
            self._bulk_plane_down("bulk sendv failed")
            raise ConnectionError("fabric bulk channel closed")
        with self._bulk_lock:
            self.bulk_bytes_sent += total

    def stream_fast_abort(self, route: Optional[str]) -> None:
        """Sever the plane a descriptor went out on whose payload never
        will (sender-side Python failure): the peer's pending claim must
        fail promptly, not sit out the full claim timeout.  The failed
        claim closes the affected STREAM on the peer; the socket
        survives and the plane re-establishes in the background."""
        if route == _route.SHM:
            self._shm_plane_down("stream shm abort")
        else:
            self._bulk_plane_down("stream bulk abort")

    def stream_bulk_abort(self) -> None:
        self.stream_fast_abort(_route.BULK)

    def stream_bulk_claim(self, uuid: int, length: int) -> IOBuf:
        """Claim a stream DATA frame's bulk bytes as a zero-copy IOBuf:
        the USER block wraps the native receive buffer, released back to
        the conn's pool when the last ref dies (_NativeBufOwner)."""
        buf = IOBuf()
        buf.append_user_data(memoryview(self._claim_zero_copy(uuid, length)))
        with self._bulk_lock:
            self.bulk_bytes_claimed += length
        return buf

    def stream_shm_claim(self, uuid: int, length: int) -> IOBuf:
        """Claim a stream DATA frame's shm bytes as a zero-copy IOBuf:
        the USER block wraps the ring slot itself — released (ring
        credit returned) when the last ref dies (_ShmBufOwner)."""
        buf = IOBuf()
        buf.append_user_data(
            memoryview(self._shm_claim_zero_copy(uuid, length)))
        return buf

    def _claim_zero_copy(self, uuid: int, expect_len: int):
        """Claim a bulk frame of exactly ``expect_len`` bytes as a ctypes
        array WRAPPING the native receive buffer, with the exactly-once
        release chained through ``._owner`` — the one custody-critical
        sequence shared by stream claims and kind-2 host delivery."""
        ptr, n, h, lib = self._bulk_claim(uuid)
        if n != expect_len:
            lib.brpc_tpu_fab_buf_release(h, ptr, n)
            raise ConnectionError(
                f"bulk frame {uuid:#x}: {n} bytes, descriptor "
                f"said {expect_len}")
        ca = (ctypes.c_uint8 * n).from_address(
            ctypes.addressof(ptr.contents))
        # the owner pins the HANDLE the claim was served from: after a
        # degrade/re-attach, releasing against a closed handle falls
        # back to free() in the native layer — never a leak
        ca._owner = _NativeBufOwner(lib.brpc_tpu_fab_buf_release, h, ptr, n)
        return ca

    # ---- read path -----------------------------------------------------
    def _read_loop(self) -> None:
        try:
            while not self.failed:
                fr = _recv_frame(self._conn)
                if fr is None:
                    break
                plan = _fi.fabric_active()
                if plan is not None:
                    plan.on_control_recv(self)    # peer-crash chaos hook
                ftype, body = fr
                if ftype == _F_DATA:
                    self._on_data(body)
                elif ftype == _F_CREDIT:
                    self._on_credits(struct.unpack("<Q", body)[0])
                elif ftype == _F_PULLED:
                    self._on_pulled(struct.unpack("<Q", body)[0])
                elif ftype in _PLANE_FRAMES:
                    if _rdump.dump_enabled():
                        _rdump.maybe_dump_fabric_frame(
                            self, "in", ftype, body)
                    which, op = _PLANE_FRAMES[ftype]
                    self._on_plane_frame(which, op, body)
                elif ftype == _F_GOODBYE:
                    self._on_goodbye()
                elif ftype == _F_DPLANE_SEQ:
                    u, s = struct.unpack("<Qq", body)
                    seqr = self._dplane_sequencer()
                    if seqr is not None:
                        seqr.on_assignment(u, s)
                elif ftype == _F_COLL_CALL:
                    from ..channels import collective_fanout as _cf
                    _cf.on_remote_announce(self, json.loads(body))
                elif ftype == _F_COLL_OK:
                    from ..channels import collective_fanout as _cf
                    _cf.on_remote_reply(self, json.loads(body), ok=True)
                elif ftype == _F_COLL_ERR:
                    from ..channels import collective_fanout as _cf
                    _cf.on_remote_reply(self, json.loads(body), ok=False)
                elif ftype == _F_COLL_GO:
                    from ..channels import collective_fanout as _cf
                    _cf.on_remote_go(self, json.loads(body))
                elif ftype == _F_FIN:
                    if len(body) >= 4:
                        # the peer closed with an explicit code (lame-duck
                        # ELOGOFF): fail in-flight calls with IT, not the
                        # generic socket-death code
                        self._fin_code = struct.unpack("<I", body[:4])[0]
                    break
        except OSError:
            pass
        except Exception as e:
            # a malformed frame or failed pull must not strand the socket
            # with a silently-dead reader — surface it as a failure
            log.error("fabric read loop died on %s: %s", self.remote_side, e)
        self._on_connection_over()

    def _on_connection_over(self) -> None:
        """Connection teardown.  EOF must ride the ORDERED delivery
        queue: a graceful FIN can arrive while an earlier device-bearing
        frame is still awaiting its transfer-server pull — committing
        EOF first would make the reader see end-of-stream and drop the
        tail (ADVICE r2 finding; the reference's teardown completes in
        CQ order, rdma_endpoint.cpp:926).  Writers and pinned send
        blocks are released immediately — their acks can never arrive."""
        self._conn_dead = True
        self._wake_window()
        self._flush_staged()
        self._close_bulk()
        self._close_shm()
        self._close_dplane()

        def commit_eof():
            with self._inbox_lock:
                self._peer_closed = True
            if self._fin_code:
                # ordered behind every delivered frame: fail in-flight
                # calls with the peer's explicit close code (lame-duck
                # ELOGOFF) instead of the generic EOF
                self.set_failed(self._fin_code,
                                "peer server logged off (lame duck)")
                return
            self.start_input_event()

        self._enqueue_delivery([], commit_eof)

    def _flush_staged(self) -> None:
        with self._staged_lock:
            staged, self._staged = self._staged, {}
        for blk, _arr in staged.values():
            cb = getattr(blk, "on_send_complete", None)
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass

    def _on_data(self, body: bytes) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding
        (nchunks,) = struct.unpack_from("<I", body, 0)
        off = 4
        # parts assemble into the delivered IOBuf at commit time: kind-4
        # outputs (device-plane transfers) do not exist until their
        # compiled program has run on the executor, so the buffer cannot
        # be built inline the way pure claim/pull kinds could
        parts: List = []
        pulled_uuids: List[int] = []
        waits: List = []
        local_device = jax.devices()[self.local_dev]
        for _ in range(nchunks):
            kind, = struct.unpack_from("<B", body, off)
            off += 1
            if kind == 0:
                (blen,) = struct.unpack_from("<I", body, off)
                off += 4
                parts.append(body[off:off + blen])
                off += blen
            elif kind == 3:
                uuid, blen = struct.unpack_from("<QQ", body, off)
                off += 16
                parts.append(self._bulk_claim_bytes(uuid, blen))
            elif kind == 6:
                uuid, blen = struct.unpack_from("<QQ", body, off)
                off += 16
                parts.append(self._shm_claim_bytes(uuid, blen))
            else:
                uuid, dtlen = struct.unpack_from("<QH", body, off)
                off += 10
                dt = body[off:off + dtlen].decode()
                off += dtlen
                (ndim,) = struct.unpack_from("<B", body, off)
                off += 1
                shape = struct.unpack_from("<%dQ" % ndim, body, off) \
                    if ndim else ()
                off += 8 * ndim
                (length,) = struct.unpack_from("<Q", body, off)
                off += 8
                if kind == 4:
                    src_dev, dseq, d_tid, d_psid = struct.unpack_from(
                        "<IqQQ", body, off)
                    off += 28
                    # device-plane descriptor: enqueue the matching recv
                    # at its slot in the total order (the rendezvous);
                    # when we are the master and the peer sent -1, the
                    # sequencer assigns here — on the control read loop,
                    # so assignment order is deterministic — and answers
                    # with _F_DPLANE_SEQ
                    t = _dp.plane().post_recv_remote(
                        uuid, length, src_dev=src_dev,
                        dst_dev=self.local_dev, socket=self,
                        trace_id=d_tid, parent_span_id=d_psid)
                    seqr = self._dplane_sequencer()
                    if seqr is None:
                        _dp.plane().fail_transfer(
                            t, "socket torn down before execution")
                    else:
                        seqr.submit_remote(t, dseq)
                    parts.append(t)
                    waits.append(t)
                    continue
                if kind in (2, 5):
                    claim = self._bulk_claim_array if kind == 2 \
                        else self._shm_claim_array
                    arr = claim(uuid, dt, shape, length, local_device)
                    # host-delivered numpy is resident by construction —
                    # only genuine device arrays gate ordered delivery
                    # on the device waiter
                    if hasattr(arr, "is_ready"):
                        waits.append(arr)
                else:
                    sds = jax.ShapeDtypeStruct(
                        shape, jnp.dtype(dt),
                        sharding=SingleDeviceSharding(local_device))
                    arr = self.node.xfer_connection(self.peer_pid).pull(
                        uuid, [sds])[0]
                    pulled_uuids.append(uuid)
                    waits.append(arr)
                parts.append(("dev", arr))

        def commit():
            from . import device_plane as _dpl
            buf = IOBuf()
            for p in parts:
                if isinstance(p, _dpl.DeviceTransfer):
                    if p.out is None or p.state == _dpl.FAILED:
                        # the payload can never be delivered and the
                        # control byte stream cannot be repaired — same
                        # terminal rule as a failed kind-2 claim
                        self.set_failed(
                            errors.EFAILEDSOCKET,
                            f"device-plane transfer {p.uuid:#x} failed: "
                            f"{p.error}")
                        return
                    self.dplane_bytes_recv += p.nbytes
                    buf.append_device_array(p.out)
                elif isinstance(p, tuple):
                    buf.append_device_array(p[1])
                else:
                    buf.append(p)
            # the PULLED ack (CQ completion): data is resident locally,
            # sender may reuse its source blocks
            for u in pulled_uuids:
                try:
                    self._ctrl_send(_F_PULLED, struct.pack("<Q", u))
                except OSError:
                    pass
            with self._inbox_lock:
                self._inbox.append(buf)
            self.start_input_event(inline=True)

        # ordered per-socket commit — a host-only frame must not jump
        # ahead of an earlier device-bearing frame still in flight
        self._enqueue_delivery(waits, commit)

    def _bulk_claim(self, uuid: int):
        # Bulk frames can trail their control descriptor (separate TCP
        # connections have no cross-ordering); the claim tolerates
        # ici_bulk_claim_timeout_s of skew before declaring the bytes
        # lost.  A frame parked BEFORE the conn died is still claimable
        # after it; a missing frame on a dead conn fails fast (-2).
        # Returns (ptr, len, handle, lib): callers MUST release against
        # the returned handle — their own snapshot could postdate a
        # degrade/re-attach and name a different conn than the one the
        # claim was served from (the buffer would then be free()d
        # instead of recycled into the owning conn's pool).
        with self._bulk_lock:
            h, lib = self._bulk, self._blib
        out, olen = _u8p(), ctypes.c_uint64()
        timeout_us = int(
            _flags.get_flag("ici_bulk_claim_timeout_s") * 1e6)
        rc = lib.brpc_tpu_fab_recv(
            h, uuid, timeout_us,
            ctypes.byref(out), ctypes.byref(olen)) if h else -2
        if rc != 0:
            # attachment frames surface this in _read_loop's catch-all ->
            # socket failure (the control byte stream cannot be repaired);
            # stream frames catch it in on_stream_frame and fail only the
            # stream (descriptor-consistency rule)
            raise ConnectionError(
                f"fabric bulk frame {uuid:#x} unclaimable (rc {rc})")
        return out, olen.value, h, lib

    def _bulk_claim_bytes(self, uuid: int, expect_len: int) -> bytes:
        ptr, n, h, lib = self._bulk_claim(uuid)
        try:
            if n != expect_len:
                raise ConnectionError(
                    f"bulk frame {uuid:#x}: {n} bytes, descriptor "
                    f"said {expect_len}")
            with self._bulk_lock:
                self.bulk_bytes_claimed += n
            return ctypes.string_at(ptr, n)
        finally:
            lib.brpc_tpu_fab_buf_release(h, ptr, n)

    def _bulk_claim_array(self, uuid: int, dt: str, shape, length: int,
                          local_device):
        """Claim a kind-2 frame and deliver it as an array.

        Host-delivery mode (default): ZERO-COPY — the numpy array wraps
        the native receive buffer directly, with an owner chained through
        numpy's base so the buffer is freed exactly when the last view
        dies.  This is the reference's RDMA delivery contract (bytes in
        registered host memory); first device use pays the H2D move.

        Eager mode: one owned numpy copy off the native buffer, then
        device_put.  The copy is NOT optional — device_put zero-copy
        ALIASES ctypes-backed donor views WITHOUT retaining them (proved
        by corrupted bounced payloads in the 2-process stress test, and
        by /tmp-scale repro: jax re-reads the donor after
        block_until_ready), so the native buffer may only be freed
        manually when device_put consumed an array it cannot alias
        unsafely (an owned copy)."""
        import numpy as np
        ca = self._claim_zero_copy(uuid, length)
        host = np.frombuffer(ca, dtype=np.uint8).view(
            np.dtype(dt)).reshape(shape)
        if _flags.get_flag("ici_fabric_host_delivery"):
            return host
        import jax
        np_arr = host.copy()          # the owned copy device_put may alias
        del host, ca                  # last refs: owner releases the buffer
        return jax.device_put(np_arr, local_device)

    # ---- shm ring claims (kinds 5/6 + FRAME_DATA_SHM) -------------------
    def _shm_claim(self, uuid: int):
        """(ptr, len, handle, lib) for one shm frame — the zero-copy
        twin of _bulk_claim, same skew-tolerant timeout, same release-
        against-the-served-handle custody rule.

        The RETIRED ring (if a degrade left one behind) is consulted
        FIRST: descriptors flushed around a plane death reference bytes
        published there, and asking a dead ring is instantaneous either
        way — parked frames return at once, missing ones fail -2
        without a wait.  Only then does the live ring get the full
        skew-tolerant timeout."""
        with self._bulk_lock:
            h, dead_h, lib = self._shm, self._shm_dead, self._shmlib
            stripes, dead_stripes = self._shm_stripes, \
                self._shm_dead_stripes
        out, olen = _u8p(), ctypes.c_uint64()
        if dead_h:
            if dead_stripes > 1:
                rc = lib.brpc_tpu_shm_recv2(
                    dead_h, self._shm_stripe_of(uuid, dead_stripes),
                    uuid, 0, ctypes.byref(out), ctypes.byref(olen))
            else:
                rc = lib.brpc_tpu_shm_recv(
                    dead_h, uuid, 0, ctypes.byref(out),
                    ctypes.byref(olen))
            if rc == 0:
                return out, olen.value, dead_h, lib
        timeout_us = int(
            _flags.get_flag("ici_bulk_claim_timeout_s") * 1e6)
        if not h:
            rc = -2
        elif stripes > 1:
            rc = lib.brpc_tpu_shm_recv2(
                h, self._shm_stripe_of(uuid, stripes), uuid, timeout_us,
                ctypes.byref(out), ctypes.byref(olen))
        else:
            rc = lib.brpc_tpu_shm_recv(
                h, uuid, timeout_us,
                ctypes.byref(out), ctypes.byref(olen))
        if rc != 0:
            raise ConnectionError(
                f"fabric shm frame {uuid:#x} unclaimable (rc {rc})")
        return out, olen.value, h, lib

    def _shm_claim_zero_copy(self, uuid: int, expect_len: int):
        """Claim an shm frame as a ctypes array WRAPPING the ring slot
        — zero receiver copies; the slot is retired (ring credit
        returned) when the last view dies (_ShmBufOwner)."""
        ptr, n, h, lib = self._shm_claim(uuid)
        if n != expect_len:
            lib.brpc_tpu_shm_release(h, ptr, n)
            raise ConnectionError(
                f"shm frame {uuid:#x}: {n} bytes, descriptor "
                f"said {expect_len}")
        ca = (ctypes.c_uint8 * n).from_address(
            ctypes.addressof(ptr.contents))
        ca._owner = _ShmBufOwner(lib, h, ptr, n)
        with self._bulk_lock:
            self.shm_bytes_claimed += n
        return ca

    def _shm_claim_bytes(self, uuid: int, expect_len: int) -> bytes:
        """Kind-6 host blobs: one owned copy off the ring (the blob is
        protocol bytes the parser consumes), slot retired immediately."""
        ptr, n, h, lib = self._shm_claim(uuid)
        try:
            if n != expect_len:
                raise ConnectionError(
                    f"shm frame {uuid:#x}: {n} bytes, descriptor "
                    f"said {expect_len}")
            with self._bulk_lock:
                self.shm_bytes_claimed += n
            return ctypes.string_at(ptr, n)
        finally:
            lib.brpc_tpu_shm_release(h, ptr, n)

    def _shm_claim_array(self, uuid: int, dt: str, shape, length: int,
                         local_device):
        """Kind-5 device payload: same delivery semantics as the kind-2
        bulk claim (_bulk_claim_array), zero-copy host-resident by
        default with the release chained through numpy's base."""
        import numpy as np
        ca = self._shm_claim_zero_copy(uuid, length)
        host = np.frombuffer(ca, dtype=np.uint8).view(
            np.dtype(dt)).reshape(shape)
        if _flags.get_flag("ici_fabric_host_delivery"):
            return host
        import jax
        np_arr = host.copy()          # the owned copy device_put may alias
        del host, ca                  # last refs: owner releases the slot
        return jax.device_put(np_arr, local_device)

    def _on_pulled(self, uuid: int) -> None:
        with self._staged_lock:
            entry = self._staged.pop(uuid, None)
        if entry is not None:
            blk = entry[0]
            cb = getattr(blk, "on_send_complete", None)
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass

    def _do_read(self, portal: IOPortal, max_count: int) -> int:
        with self._inbox_lock:
            avail = len(self._inbox)
            if avail == 0:
                return 0 if self._peer_closed else -1
            n = min(avail, max_count)
            self._inbox.cutn(portal, n)
        # batched credit return (the reference piggybacks acks on
        # completions rather than acking every read): parsers consume the
        # inbox in many small cuts, and a CREDIT frame per cut measured
        # ~66 tiny control sends per bulk chunk.  Deferring the return
        # until window/8 keeps the sender pumping (7/8 of the window is
        # still credited) at 1/66th the control traffic.
        flush = 0
        with self._inbox_lock:
            self._consumed_unacked += n
            if (self._consumed_unacked >= self.window_bytes // 8
                    or self._peer_closed):
                flush = self._consumed_unacked
                self._consumed_unacked = 0
        if flush:
            try:
                self._ctrl_send(_F_CREDIT, struct.pack("<Q", flush))
            except OSError:
                pass
        return n

    def set_failed(self, error_code: int = errors.EFAILEDSOCKET,
                   reason: str = "") -> bool:
        """Socket death is no longer the end of the endpoint: the first
        transport-level failure hands the remote endpoint to the health
        checker, which probes with exponential backoff + jitter until a
        reconnect (fresh HELLO/bulk handshake, NEW versioned socket id —
        this id was already revoked by the base set_failed, so stale
        writes fail cleanly) can succeed; Channel retry / backup-request
        then recovers RPCs issued during the outage, and the endpoint's
        circuit breaker is reset on revival (ramp-up gating)."""
        first = super().set_failed(error_code, reason)
        if (first and not self.is_server_side
                and error_code != errors.ECLOSE
                and _flags.get_flag("ici_fabric_health_check")):
            try:
                from ..rpc.health_check import start_health_check
                start_health_check(self.remote_side)
            except Exception:
                pass
        return first

    def _transport_close(self) -> None:
        try:
            # FIN carries the closer's error code (empty body = old
            # peers / clean close): a lame-duck hard stop propagates
            # ELOGOFF so the peer's in-flight calls fail over without
            # burning their connection-failure backoff budget
            body = struct.pack("<I", self.failed_error) \
                if self.failed_error == errors.ELOGOFF else b""
            self._ctrl_send(_F_FIN, body)
        except OSError:
            pass
        try:
            self._conn.close()
        except OSError:
            pass
        self._wake_window()
        self._flush_staged()
        self._close_bulk()
        self._close_shm()
        self._close_dplane()

    def _close_bulk(self) -> None:
        """Tear down the bulk conn WITHOUT starting revival (socket-level
        teardown).  Safe while writers race: fab_send on a closed handle
        fails cleanly (shared-ptr registry), and the serial read loop has
        already claimed every pending frame by the time teardown runs."""
        with self._bulk_lock:
            h, self._bulk = self._bulk, 0
            pending, self._reestab_pending = self._reestab_pending, None
            lib = self._blib
        if h and lib is not None:
            lib.brpc_tpu_fab_conn_close(h)
        if pending is not None:
            pending[0].brpc_tpu_fab_conn_close(pending[1])
        self._reestab_evt.set()        # unblock a parked revival thread


def pair_plane_stats() -> Dict[int, dict]:
    """Live native bulk planes grouped by peer pid (the per-pair plane
    registry, native/fabric.cpp): {peer_pid: {conns, bytes_in,
    bytes_out}}.  Empty when the native core is absent."""
    try:
        from ..butil import native as _native
        lib = _native.load()
    except Exception:
        lib = None
    if lib is None or not hasattr(lib, "brpc_tpu_fab_peer_list"):
        return {}
    # a FULL buffer means the native list may have been truncated (the
    # C call returns min(count, cap) with no overflow signal): grow and
    # retry so a >64-process pod's /ici page never silently drops pairs
    cap = 64
    while True:
        peers = (ctypes.c_int32 * cap)()
        n = lib.brpc_tpu_fab_peer_list(peers, cap)
        if n < cap or cap >= (1 << 16):
            if n >= cap:
                log.warning("pair_plane_stats: peer list truncated "
                            "at %d entries", cap)
            break
        cap *= 2
    out: Dict[int, dict] = {}
    for i in range(n):
        conns = ctypes.c_uint64()
        bi = ctypes.c_uint64()
        bo = ctypes.c_uint64()
        lib.brpc_tpu_fab_pair_stats(peers[i], ctypes.byref(conns),
                                    ctypes.byref(bi), ctypes.byref(bo))
        out[int(peers[i])] = {"conns": int(conns.value),
                              "bytes_in": int(bi.value),
                              "bytes_out": int(bo.value)}
    return out


def connect_any(ep, local_dev: Optional[int] = None):
    """Route an ici:// connect: in-process targets use the zero-copy
    IciSocket path; remote ones the fabric.  This is what makes
    Channel("ici://k") work identically single- and multi-controller."""
    from .transport import ici_connect
    node = FabricNode.instance()
    target = ep.device_id
    if node is None:
        return ici_connect(ep, local_dev)
    if local_dev is None:
        # default client residence must be a device THIS process owns —
        # ici_connect's neighbor default can land on another controller's
        # device, which this process cannot address
        import jax
        me = node.process_id
        owned = [i for i, d in enumerate(jax.devices())
                 if d.process_index == me]
        local_dev = next((i for i in owned if i != target), owned[0])
    if FabricNode.device_owner(target) == node.process_id:
        return ici_connect(ep, local_dev)
    return node.connect(target, local_dev)
