"""Multi-controller ici://: cross-process handshake + device data plane.

Reference analogue (SURVEY.md §3.5, src/brpc/rdma/rdma_endpoint.h:37-108):
RdmaEndpoint forms a connection with an out-of-band TCP handshake that
exchanges GID/QPN, then moves payloads over verbs with an explicit-ACK
window, freeing send buffers only on CQ completion.  The TPU translation:

  * **Out-of-band channel** — the JAX coordination service
    (jax.distributed): each process publishes its fabric contact info
    (control TCP address, transfer-server address, owned device ids) under
    a well-known KV key; peers resolve it with a blocking get.  This is
    the GID/QPN exchange.
  * **Control plane** — a plain TCP connection per socket pair carries
    protocol bytes (frames, meta — small) plus the window bookkeeping
    (CREDIT) and transfer completions (PULLED — the CQ-completion
    analogue).
  * **Data plane** — DEVICE payloads never ride the control TCP: the
    sender stages arrays on its jax.experimental.transfer server under a
    uuid (``await_pull``) and ships only a descriptor; the receiver pulls
    straight into its local device memory (on TPU pods this is a
    DMA-style fetch, the RDMA-READ model).  Source blocks stay pinned
    until the peer's PULLED ack — the rdma_endpoint.cpp:926 discipline.
  * **Flow control** — same credit window as the in-process IciSocket
    (rdma_endpoint.cpp:771): at most ``ici_socket_window_bytes``
    unconsumed bytes per socket; CREDIT frames replenish on consume.

Addressing: ``ici://k`` is position k in the GLOBAL jax.devices() list
(identical in every process); ownership is ``devices[k].process_index``.
``connect_any(ep)`` routes in-process targets through the zero-copy
IciSocket and remote ones through a FabricSocket transparently, so
Server/Channel code is identical single- or multi-controller.
"""
from __future__ import annotations

import json
import socket as _pysocket
import struct
import threading
from typing import Dict, List, Optional, Tuple

from ..butil import logging as log
from ..butil.iobuf import IOBuf, IOPortal, DEVICE
from ..rpc import errors
from ..rpc.socket import Socket
from .transport import CreditWindow, OrderedDelivery

_KV_PREFIX = "brpc_tpu/fabric/"

# control-channel frame types
_F_HELLO = 1       # json: {target_dev, client_dev, pid}
_F_HELLO_OK = 2
_F_HELLO_ERR = 3
_F_DATA = 4        # chunk list: host bytes + device descriptors
_F_CREDIT = 5      # u64 consumed bytes
_F_PULLED = 6      # u64 uuid — receiver finished pulling (CQ completion)
_F_FIN = 7

_HDR = struct.Struct("<BI")          # type, body length


def _send_frame(sock: _pysocket.socket, ftype: int, body: bytes) -> None:
    sock.sendall(_HDR.pack(ftype, len(body)) + body)


def _recv_exact(sock: _pysocket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: _pysocket.socket) -> Optional[Tuple[int, bytes]]:
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    ftype, length = _HDR.unpack(hdr)
    body = _recv_exact(sock, length) if length else b""
    if length and body is None:
        return None
    return ftype, body


class FabricNode:
    """Per-process fabric runtime: transfer server + control listener +
    the coordination-service registry."""

    _instance: Optional["FabricNode"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.process_id = -1
        self.num_processes = 0
        self._kv = None
        self._xfer_server = None
        self._xfer_conns: Dict[int, object] = {}      # pid -> TransferConnection
        self._xfer_lock = threading.Lock()
        self._ctrl_listener: Optional[_pysocket.socket] = None
        self.ctrl_addr = ""
        self._uuid_lock = threading.Lock()
        self._next_uuid = 1
        self._peers: Dict[int, dict] = {}             # pid -> contact info
        self._accept_thread: Optional[threading.Thread] = None
        self._shutdown = False

    # ---- lifecycle -----------------------------------------------------
    @classmethod
    def instance(cls) -> Optional["FabricNode"]:
        with cls._lock:
            return cls._instance

    @classmethod
    def initialize(cls, coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   host_ip: Optional[str] = None) -> "FabricNode":
        """Join the fabric.  Calls jax.distributed.initialize when the
        coordination service isn't up yet (the reference's equivalent is
        whatever launched the processes); then performs the handshake
        publication.  Idempotent per process.

        ``host_ip`` is the address PUBLISHED to peers; None (default)
        derives it from the route to the coordinator, so multi-host
        fabrics don't hand out 127.0.0.1 (ADVICE r2 finding)."""
        with cls._lock:
            if cls._instance is not None:
                return cls._instance
            node = FabricNode()
            node._start(coordinator_address, num_processes, process_id,
                        host_ip)
            cls._instance = node
            return node

    def _start(self, coordinator_address, num_processes, process_id,
               host_ip) -> None:
        import jax
        from jax._src import distributed
        if distributed.global_state.client is None:
            jax.distributed.initialize(coordinator_address,
                                       num_processes=num_processes,
                                       process_id=process_id)
        self._kv = distributed.global_state.client
        self.process_id = distributed.global_state.process_id
        self.num_processes = distributed.global_state.num_processes
        if host_ip is None:
            host_ip = self._derive_host_ip(
                coordinator_address
                or getattr(distributed.global_state, "coordinator_address",
                           None))
        # data plane: transfer server (explicit TCP transport addresses —
        # the same-host "local" bulk transport is not usable in sandboxed
        # containers, and TCP is the portable choice; on real pods the
        # premapped DMA path takes over)
        from jax.experimental import transfer
        backend = jax.local_devices()[0].client
        self._xfer_server = transfer.start_transfer_server(
            backend, f"{host_ip}:0", [f"{host_ip}:0"])
        # control plane listener
        self._ctrl_listener = _pysocket.socket()
        self._ctrl_listener.setsockopt(_pysocket.SOL_SOCKET,
                                       _pysocket.SO_REUSEADDR, 1)
        self._ctrl_listener.bind((host_ip, 0))
        self._ctrl_listener.listen(64)
        self.ctrl_addr = "%s:%d" % self._ctrl_listener.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fabric_accept", daemon=True)
        self._accept_thread.start()
        # the handshake publication (GID/QPN analogue)
        info = {
            "ctrl": self.ctrl_addr,
            "xfer": self._xfer_server.address(),
            "devices": [i for i, d in enumerate(jax.devices())
                        if d.process_index == self.process_id],
        }
        self._kv.key_value_set(_KV_PREFIX + str(self.process_id),
                               json.dumps(info))
        log.info("fabric: process %d/%d up ctrl=%s xfer=%s devices=%s",
                 self.process_id, self.num_processes, info["ctrl"],
                 info["xfer"], info["devices"])

    @staticmethod
    def _derive_host_ip(coordinator_address: Optional[str]) -> str:
        """The IP this host uses to reach the coordinator — the address
        peers can reach US on (every fabric member reaches the
        coordinator by construction).  A UDP connect never sends a
        packet; it just resolves the route."""
        if coordinator_address:
            host, sep, port = coordinator_address.rpartition(":")
            if not sep:                    # no port at all: 'hostname'
                host, port = coordinator_address, ""
            host = host.strip("[]")        # IPv6 '[::1]:1234' form
            s = _pysocket.socket(_pysocket.AF_INET, _pysocket.SOCK_DGRAM)
            try:
                # ValueError too: '[::]' or a port-less 'host:path' form
                # must fall back, not crash FabricNode.initialize
                s.connect((host, int(port) if port.isdigit() else 1))
                return s.getsockname()[0]
            except (OSError, ValueError):
                pass
            finally:
                s.close()
        return "127.0.0.1"

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            if self._ctrl_listener is not None:
                self._ctrl_listener.close()
        except Exception:
            pass

    # ---- registry ------------------------------------------------------
    def peer_info(self, pid: int, timeout_ms: int = 60000) -> dict:
        info = self._peers.get(pid)
        if info is None:
            raw = self._kv.blocking_key_value_get(_KV_PREFIX + str(pid),
                                                  timeout_ms)
            info = json.loads(raw)
            self._peers[pid] = info
        return info

    @staticmethod
    def device_owner(device_id: int) -> int:
        import jax
        return jax.devices()[device_id].process_index

    def xfer_connection(self, pid: int):
        with self._xfer_lock:
            conn = self._xfer_conns.get(pid)
            if conn is None:
                conn = self._xfer_server.connect(self.peer_info(pid)["xfer"])
                self._xfer_conns[pid] = conn
            return conn

    def next_uuid(self) -> int:
        with self._uuid_lock:
            u = (self.process_id + 1) << 40 | self._next_uuid
            self._next_uuid += 1
            return u

    def stage(self, uuid: int, arrays: List) -> None:
        self._xfer_server.await_pull(uuid, arrays)

    # ---- server side ---------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                conn, _ = self._ctrl_listener.accept()
            except OSError:
                return
            threading.Thread(target=self._handshake_server, args=(conn,),
                             name="fabric_handshake", daemon=True).start()

    def _handshake_server(self, conn: _pysocket.socket) -> None:
        try:
            conn.setsockopt(_pysocket.IPPROTO_TCP, _pysocket.TCP_NODELAY, 1)
            fr = _recv_frame(conn)
            if fr is None or fr[0] != _F_HELLO:
                conn.close()
                return
            hello = json.loads(fr[1])
            target = hello["target_dev"]
            from .transport import _listeners, _listeners_lock
            with _listeners_lock:
                listener = _listeners.get(target)
            if listener is None:
                _send_frame(conn, _F_HELLO_ERR,
                            f"no server at ici://{target}".encode())
                conn.close()
                return
            sock = FabricSocket(conn, local_dev=target,
                                remote_dev=hello["client_dev"],
                                peer_pid=hello["pid"], node=self)
            sock.is_server_side = True
            # on_accept attaches the messenger BEFORE any frame can be
            # read — a reader that fires first would drain the input
            # event with no messenger and drop the first request
            listener.on_accept(sock)
            _send_frame(conn, _F_HELLO_OK, b"")
            sock.start_io()
        except Exception as e:
            log.error("fabric handshake failed: %s", e)
            try:
                conn.close()
            except Exception:
                pass

    # ---- client side ---------------------------------------------------
    def connect(self, target_dev: int, client_dev: int) -> "FabricSocket":
        owner = self.device_owner(target_dev)
        info = self.peer_info(owner)
        host, _, port = info["ctrl"].rpartition(":")
        conn = _pysocket.create_connection((host, int(port)), timeout=30)
        conn.setsockopt(_pysocket.IPPROTO_TCP, _pysocket.TCP_NODELAY, 1)
        _send_frame(conn, _F_HELLO, json.dumps({
            "target_dev": target_dev, "client_dev": client_dev,
            "pid": self.process_id}).encode())
        fr = _recv_frame(conn)
        if fr is None or fr[0] != _F_HELLO_OK:
            msg = fr[1].decode() if fr else "connection closed"
            conn.close()
            raise ConnectionRefusedError(f"fabric: {msg}")
        sock = FabricSocket(conn, local_dev=client_dev,
                            remote_dev=target_dev, peer_pid=owner, node=self)
        sock.start_io()
        return sock


class FabricSocket(CreditWindow, OrderedDelivery, Socket):
    """Cross-process ici socket: control TCP + transfer-server pulls,
    with the same credit window as the in-process IciSocket."""

    def __init__(self, conn: _pysocket.socket, local_dev: int,
                 remote_dev: int, peer_pid: int, node: FabricNode,
                 window_bytes: Optional[int] = None):
        from .mesh import IciMesh
        mesh = IciMesh.default()
        super().__init__(remote_side=mesh.endpoint(remote_dev))
        self.local_side = mesh.endpoint(local_dev)
        self.local_dev = local_dev
        self.remote_dev = remote_dev
        self.peer_pid = peer_pid
        self.node = node
        self._conn = conn
        self._conn_wlock = threading.Lock()
        self._inbox = IOBuf()
        self._inbox_lock = threading.Lock()
        self._peer_closed = False      # reader-visible EOF (ordered)
        self._conn_dead = False        # writer-visible death (immediate)
        self._init_window(window_bytes)
        self._init_delivery()
        self._staged: Dict[int, Tuple] = {}    # uuid -> (src_block, array)
        self._staged_lock = threading.Lock()
        self._reader: Optional[threading.Thread] = None

    def start_io(self) -> None:
        self._reader = threading.Thread(target=self._read_loop,
                                        name="fabric_read", daemon=True)
        self._reader.start()

    def inflight_send_blocks(self) -> int:
        with self._staged_lock:
            return len(self._staged)

    def _peer_gone(self) -> bool:
        return self._peer_closed or self._conn_dead

    # ---- write path ----------------------------------------------------
    def _do_write(self, data: IOBuf) -> int:
        n = self._consume_window(len(data))
        if n < 0:
            return -1
        frame = data.cut(n)
        body = self._encode_data(frame)
        try:
            with self._conn_wlock:
                _send_frame(self._conn, _F_DATA, body)
        except OSError as e:
            raise ConnectionError(f"fabric control channel: {e}")
        return n

    def _encode_data(self, frame: IOBuf) -> bytes:
        """Serialize a frame: host refs inline, DEVICE refs staged on the
        transfer server and shipped as (uuid, dtype, shape, length)."""
        out = [b""]
        nchunks = 0
        pending_host: List[bytes] = []

        def flush_host():
            nonlocal nchunks
            if pending_host:
                blob = b"".join(pending_host)
                out.append(struct.pack("<BI", 0, len(blob)))
                out.append(blob)
                pending_host.clear()
                nchunks += 1

        for i in range(frame.backing_block_num()):
            r = frame.backing_block(i)
            if r.block.kind == DEVICE:
                flush_host()
                arr = r.block.data
                if r.offset or r.length != len(arr):
                    arr = arr[r.offset:r.offset + r.length]
                uuid = self.node.next_uuid()
                self.node.stage(uuid, [arr])
                with self._staged_lock:
                    self._staged[uuid] = (r.block, arr)
                dt = str(arr.dtype).encode()
                shape = arr.shape
                out.append(struct.pack("<BQH", 1, uuid, len(dt)))
                out.append(dt)
                out.append(struct.pack("<B", len(shape)))
                out.append(struct.pack("<%dQ" % len(shape), *shape)
                           if shape else b"")
                out.append(struct.pack("<Q", r.length))
                nchunks += 1
            else:
                pending_host.append(
                    bytes(r.block.host_view(r.offset, r.length)))
        flush_host()
        out[0] = struct.pack("<I", nchunks)
        return b"".join(out)

    # ---- read path -----------------------------------------------------
    def _read_loop(self) -> None:
        try:
            while not self.failed:
                fr = _recv_frame(self._conn)
                if fr is None:
                    break
                ftype, body = fr
                if ftype == _F_DATA:
                    self._on_data(body)
                elif ftype == _F_CREDIT:
                    self._on_credits(struct.unpack("<Q", body)[0])
                elif ftype == _F_PULLED:
                    self._on_pulled(struct.unpack("<Q", body)[0])
                elif ftype == _F_FIN:
                    break
        except OSError:
            pass
        except Exception as e:
            # a malformed frame or failed pull must not strand the socket
            # with a silently-dead reader — surface it as a failure
            log.error("fabric read loop died on %s: %s", self.remote_side, e)
        self._on_connection_over()

    def _on_connection_over(self) -> None:
        """Connection teardown.  EOF must ride the ORDERED delivery
        queue: a graceful FIN can arrive while an earlier device-bearing
        frame is still awaiting its transfer-server pull — committing
        EOF first would make the reader see end-of-stream and drop the
        tail (ADVICE r2 finding; the reference's teardown completes in
        CQ order, rdma_endpoint.cpp:926).  Writers and pinned send
        blocks are released immediately — their acks can never arrive."""
        self._conn_dead = True
        self._wake_window()
        self._flush_staged()

        def commit_eof():
            with self._inbox_lock:
                self._peer_closed = True
            self.start_input_event()

        self._enqueue_delivery([], commit_eof)

    def _flush_staged(self) -> None:
        with self._staged_lock:
            staged, self._staged = self._staged, {}
        for blk, _arr in staged.values():
            cb = getattr(blk, "on_send_complete", None)
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass

    def _on_data(self, body: bytes) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding
        (nchunks,) = struct.unpack_from("<I", body, 0)
        off = 4
        buf = IOBuf()
        pulled_uuids: List[int] = []
        device_arrays: List = []
        local_device = jax.devices()[self.local_dev]
        for _ in range(nchunks):
            kind, = struct.unpack_from("<B", body, off)
            off += 1
            if kind == 0:
                (blen,) = struct.unpack_from("<I", body, off)
                off += 4
                buf.append(body[off:off + blen])
                off += blen
            else:
                uuid, dtlen = struct.unpack_from("<QH", body, off)
                off += 10
                dt = body[off:off + dtlen].decode()
                off += dtlen
                (ndim,) = struct.unpack_from("<B", body, off)
                off += 1
                shape = struct.unpack_from("<%dQ" % ndim, body, off) \
                    if ndim else ()
                off += 8 * ndim
                (length,) = struct.unpack_from("<Q", body, off)
                off += 8
                sds = jax.ShapeDtypeStruct(
                    shape, jnp.dtype(dt),
                    sharding=SingleDeviceSharding(local_device))
                arr = self.node.xfer_connection(self.peer_pid).pull(
                    uuid, [sds])[0]
                buf.append_device_array(arr)
                device_arrays.append(arr)
                pulled_uuids.append(uuid)

        def commit():
            # the PULLED ack (CQ completion): data is resident locally,
            # sender may reuse its source blocks
            for u in pulled_uuids:
                try:
                    with self._conn_wlock:
                        _send_frame(self._conn, _F_PULLED,
                                    struct.pack("<Q", u))
                except OSError:
                    pass
            with self._inbox_lock:
                self._inbox.append(buf)
            self.start_input_event()

        # ordered per-socket commit — a host-only frame must not jump
        # ahead of an earlier device-bearing frame still in flight
        self._enqueue_delivery(device_arrays, commit)

    def _on_pulled(self, uuid: int) -> None:
        with self._staged_lock:
            entry = self._staged.pop(uuid, None)
        if entry is not None:
            blk = entry[0]
            cb = getattr(blk, "on_send_complete", None)
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass

    def _do_read(self, portal: IOPortal, max_count: int) -> int:
        with self._inbox_lock:
            avail = len(self._inbox)
            if avail == 0:
                return 0 if self._peer_closed else -1
            n = min(avail, max_count)
            self._inbox.cutn(portal, n)
        try:
            with self._conn_wlock:
                _send_frame(self._conn, _F_CREDIT, struct.pack("<Q", n))
        except OSError:
            pass
        return n

    def _transport_close(self) -> None:
        try:
            with self._conn_wlock:
                _send_frame(self._conn, _F_FIN, b"")
        except OSError:
            pass
        try:
            self._conn.close()
        except OSError:
            pass
        self._wake_window()
        self._flush_staged()


def connect_any(ep, local_dev: Optional[int] = None):
    """Route an ici:// connect: in-process targets use the zero-copy
    IciSocket path; remote ones the fabric.  This is what makes
    Channel("ici://k") work identically single- and multi-controller."""
    from .transport import ici_connect
    node = FabricNode.instance()
    target = ep.device_id
    if node is None:
        return ici_connect(ep, local_dev)
    if local_dev is None:
        # default client residence must be a device THIS process owns —
        # ici_connect's neighbor default can land on another controller's
        # device, which this process cannot address
        import jax
        me = node.process_id
        owned = [i for i, d in enumerate(jax.devices())
                 if d.process_index == me]
        local_dev = next((i for i in owned if i != target), owned[0])
    if FabricNode.device_owner(target) == node.process_id:
        return ici_connect(ep, local_dev)
    return node.connect(target, local_dev)
