"""Device data plane: payloads cross the mesh through compiled XLA programs.

This is the analogue of the reference's RDMA datapath proper
(src/brpc/rdma/rdma_endpoint.cpp:771 ``ibv_post_send`` posting registered
IOBuf blocks straight on the NIC, :926 freeing send buffers on CQ
completion): instead of staging device payloads through host memory
(``jax.device_put`` in-process, the native bulk TCP plane cross-process),
a DEVICE-block payload is moved chip-to-chip by a **compiled XLA
point-to-point transfer program** — shard_map + ``jax.lax.ppermute`` over
a 2-device submesh (XLA-scheduled; on TPU this lowers to a
collective-permute over the ICI links), or a Pallas
``make_async_remote_copy`` kernel (hand-scheduled remote DMA, the literal
``ibv_post_send``) where ``pltpu`` is available.  No NIC — and no host —
in the datapath.

QP semantics (rdma_endpoint.h:37-108):

  * ``post_send(arr, src, dst)`` posts a work request and returns a
    :class:`DeviceTransfer` (the WR handle).  Nothing moves yet — like a
    posted SGE, the source array is pinned by the plane until completion.
  * a 16-byte descriptor ``(uuid, nbytes)`` (+ dtype/shape on the fabric
    wire) rides the transport's existing control/delivery channel;
  * the receiver ``post_recv(uuid)``s the matching recv — the rendezvous:
    both sides join the SAME compiled program (in-process: one runtime
    enters it once; multi-controller: each process enters with its local
    shard, the SPMD contract).
  * completion is a :class:`bthread.device_waiter.DeviceCompletion` (the
    CQ entry), signaled from the per-device completion poller — waiters
    yield their M:N worker instead of blocking it, and source pins
    release exactly at completion (the :926 discipline).

Program cache: one compiled executable per (nbytes, src, dst, kernel,
mesh generation), exactly like the collectives cache — steady workloads
repost the same shapes and pay compilation once (cache hits/misses are
counters).

Failure semantics: a refused/failed post raises :class:`DevicePlaneError`
BEFORE any descriptor exists, so the caller degrades to its previous
path — ``device_put`` in-process, the PR-2 bulk/inline fallback machinery
on the fabric — within the same frame (counted in
``ici_device_plane_fallbacks``).  An IN-PROCESS posted send whose recv
never arrives is reaped after ``ici_device_plane_match_timeout_s`` and
fails only that transfer.  Cross-process (fabric) transfers are owned by
their socket's per-direction executors instead: a transfer still queued
when the socket dies is failed by the executor (``fail_transfer`` —
completion fires, pins release), while one already INSIDE a collective
is uninterruptible from the host and relies on the backend's distributed
error propagation — the same contract every multi-controller XLA program
lives under.  The chaos harness forces the degrade paths
deterministically (``FabricFaultPlan.device_plane_fail_posts``).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import bvar
from ..butil import flags as _flags
from ..butil import debug_sync as _dbg
from ..butil import logging as log
from ..butil import custody_ledger as _ledger
from ..bthread.device_waiter import DeviceCompletion, device_on_ready
from .mesh import IciMesh

_flags.define_flag("ici_device_plane", True,
                   "move DEVICE payloads through compiled XLA transfer "
                   "programs (the no-host datapath) where eligible")
_flags.define_flag("ici_device_plane_threshold", 64 * 1024,
                   "min DEVICE payload bytes routed through the device "
                   "plane (smaller payloads keep the lower-fixed-cost "
                   "host paths)", _flags.positive_integer)
# On a host-memory mesh (the 8-virtual-device CPU platform) a compiled
# transfer program measured ~1.4 GB/s at 4 MB vs ~5.5 GB/s for a plain
# device_put memcpy — the program pays XLA dispatch plus a (2, n) output
# materialization for what is physically one host memcpy.  On TPU the
# program IS the ICI datapath and device_put cannot cross processes at
# all, so the plane engages there by default; host meshes must opt in
# (tests, bench, and the dryrun do — the code path is identical).
_flags.define_flag("ici_device_plane_host_mesh", False,
                   "engage the device plane on non-TPU (host-memory) "
                   "meshes too; slower than device_put there, real code "
                   "path for CI")
_flags.define_flag("ici_device_plane_kernel", "ppermute",
                   "transfer kernel: 'ppermute' (XLA-scheduled "
                   "shard_map + lax.ppermute) or 'pallas' "
                   "(make_async_remote_copy remote DMA; interpret mode "
                   "off-TPU)")
_flags.define_flag("ici_device_plane_match_timeout_s", 30.0,
                   "seconds a posted send waits for its matching recv "
                   "before failing (peer died post-descriptor)")
# Cross-process execution backend for the sequenced (xproc) plane.
# "auto": enter the compiled multi-controller collective on backends
# that have one (TPU pods), and carry the payload on the native bulk
# plane under the SAME epoch-ordered sequencer everywhere else (this
# container's CPU jaxlib raises "Multiprocess computations aren't
# implemented on the CPU backend" — the sequencer, descriptors, pins,
# and completions are identical either way, only the byte mover
# differs).  "on"/"off" force one leg, for tests and TPU bring-up.
_flags.define_flag("ici_device_plane_xproc_compiled", "auto",
                   "xproc transfer execution: 'auto' (compiled "
                   "collectives on TPU, bulk-carried elsewhere), 'on', "
                   "or 'off'")

_g_bytes_sent = bvar.Adder("ici_device_plane_bytes_sent")
_g_bytes_recv = bvar.Adder("ici_device_plane_bytes_recv")
_g_transfers = bvar.Adder("ici_device_plane_transfers")
_g_fallbacks = bvar.Adder("ici_device_plane_fallbacks")
_g_cache_hits = bvar.Adder("ici_device_plane_program_cache_hits")
_g_cache_misses = bvar.Adder("ici_device_plane_program_cache_misses")
_g_match_timeouts = bvar.Adder("ici_device_plane_match_timeouts")


class DevicePlaneError(ConnectionError):
    """A post was refused or failed before any descriptor went out; the
    caller must route the payload over its fallback path."""


# transfer states (WR lifecycle)
POSTED = "posted"          # send posted, awaiting the matching recv
MATCHED = "matched"        # rendezvous done, compiled program dispatched
COMPLETE = "complete"      # payload resident at dst; source released
FAILED = "failed"


class DeviceTransfer:
    """One posted work request: uuid-correlated, completion-signaled.

    ``out`` is the dst-resident flat uint8 array once MATCHED (an XLA
    future — physically resident at COMPLETE, which is when the source
    pin releases).  ``wait``/``poll``/``add_done_callback`` are the CQ
    interface (see DeviceCompletion)."""

    __slots__ = ("uuid", "src_dev", "dst_dev", "nbytes", "state", "error",
                 "out", "completion", "posted_ns", "matched_ns",
                 "complete_ns", "_src_arr", "_releases", "_lock",
                 "trace_id", "parent_span_id", "span")

    def __init__(self, uuid: int, src_dev: int, dst_dev: int, nbytes: int,
                 src_arr=None, trace_id: int = 0, parent_span_id: int = 0):
        self.uuid = uuid
        self.src_dev = src_dev
        self.dst_dev = dst_dev
        self.nbytes = nbytes
        self.state = POSTED
        self.error = ""
        self.out = None
        self.completion = DeviceCompletion()
        self.posted_ns = time.monotonic_ns()
        self.matched_ns = 0
        self.complete_ns = 0
        self._src_arr = src_arr        # the pin (rdma_endpoint.cpp:926)
        self._releases: List[Callable[[], None]] = []
        self._lock = _dbg.make_lock("DeviceTransfer._lock")
        # trace context: the RPC span this transfer belongs to, captured
        # at post time (sender) or carried in the kind-4 descriptor
        # (receiver), so the transfer's lifecycle lands in the SAME
        # trace on both processes.  With a context and sampling on, the
        # transfer owns its own SpanDB entry (a "transfer" span parented
        # under the RPC span); without one, annotations degrade to the
        # bthread-local current span, the pre-pod behavior.
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self.span = None
        if trace_id:
            from ..rpc import span as _span
            if _span.rpcz_enabled():
                self.span = _span.start_transfer_span(
                    f"device_plane ici://{src_dev}->{dst_dev} "
                    f"{'send' if src_arr is not None else 'recv'} "
                    f"{nbytes}B", trace_id, parent_span_id)

    # -- source pin ------------------------------------------------------
    def add_source_release(self, cb: Optional[Callable[[], None]]) -> None:
        """Called exactly once when the source block may be reused/donated
        (completion OR failure — either way the transfer holds no more
        references)."""
        if cb is None:
            return
        with self._lock:
            if self.state not in (COMPLETE, FAILED):
                self._releases.append(cb)
                return
        cb()

    def source_array(self):
        return self._src_arr

    def _release_source(self) -> None:
        with self._lock:
            cbs, self._releases = self._releases, []
            self._src_arr = None
        for cb in cbs:
            try:
                cb()
            except Exception:
                pass

    # -- CQ interface ----------------------------------------------------
    def poll(self) -> bool:
        return self.completion.poll()

    def wait(self, timeout: Optional[float] = None) -> int:
        return self.completion.wait(timeout)

    def add_done_callback(self, cb: Callable[[int], None]) -> None:
        self.completion.add_done_callback(cb)

    def describe(self) -> dict:
        return {
            "uuid": f"{self.uuid:#x}",
            "route": f"ici://{self.src_dev} -> ici://{self.dst_dev}",
            "nbytes": self.nbytes,
            "state": self.state,
            "error": self.error,
            "posted_to_matched_us": ((self.matched_ns - self.posted_ns)
                                     // 1000 if self.matched_ns else -1),
            "matched_to_complete_us": ((self.complete_ns - self.matched_ns)
                                       // 1000 if self.complete_ns else -1),
        }


def mesh_index_of(arr, mesh: Optional[IciMesh] = None) -> int:
    """Logical mesh id of a (single-device) array's residence; -1 when
    off-mesh or host-resident."""
    mesh = mesh or IciMesh.default()
    try:
        idx = mesh.device_index(arr.device)
        if idx >= 0:
            return idx
    except Exception:
        pass
    try:
        for d in arr.devices():
            i = mesh.device_index(d)
            if i >= 0:
                return i
    except Exception:
        pass
    return -1


def _platform() -> str:
    import jax
    return jax.devices()[0].platform


def platform_allows() -> bool:
    """The plane engages on TPU by default; host-memory meshes opt in
    (see the ici_device_plane_host_mesh flag rationale)."""
    try:
        return (_platform() == "tpu"
                or bool(_flags.get_flag("ici_device_plane_host_mesh")))
    except Exception:
        return False


def eligible(nbytes: int) -> bool:
    """Route this payload device-plane?  Flag + threshold + platform."""
    return (bool(_flags.get_flag("ici_device_plane"))
            and nbytes >= _flags.get_flag("ici_device_plane_threshold")
            and platform_allows())


def xproc_compiled_ok() -> bool:
    """Does the cross-process plane enter COMPILED multi-controller
    collectives, or carry bytes on the bulk plane under the same
    sequencer?  See the ici_device_plane_xproc_compiled flag."""
    mode = _flags.get_flag("ici_device_plane_xproc_compiled")
    if mode == "on":
        return True
    if mode == "off":
        return False
    try:
        return _platform() == "tpu"
    except Exception:
        return False


class DevicePlane:
    """Per-process device plane: program cache + posted-WR table."""

    _instance: Optional["DevicePlane"] = None
    _ilock = threading.Lock()

    # fablint guarded-state contract: cache/WR-table structure AND the
    # running stats counters — post_send/post_recv/execute_remote run
    # on arbitrary caller + executor + poller threads, so unguarded
    # `+= 1` counter updates were lost under contention (fablint
    # finding; the per-direction executors alone make two writers)
    _GUARDED_BY = {
        "_programs": "_lock",
        "_zeros": "_lock",
        "_pending": "_lock",
        "_active": "_lock",
        "_next_uuid": "_lock",
        "transfers": "_lock",
        "bytes_sent": "_lock",
        "bytes_recv": "_lock",
        "fallbacks": "_lock",
        "cache_hits": "_lock",
        "cache_misses": "_lock",
        "match_timeouts": "_lock",
    }

    # fablint custody contract (ISSUE 20): every tracked transfer (its
    # source HBM pin rides the _active entry) must untrack — completion,
    # failure, and the lame-duck fail_pending sweep are the exits.  The
    # post_* sites carry custody-moved markers because the release fires
    # asynchronously from the CQ callback, not on the posting path.
    _CUSTODY = {"_track": ("_untrack",)}

    # cache bounds: steady workloads repost a handful of (size, route)
    # shapes, but arbitrary attachment sizes would otherwise compile and
    # pin one executable + one device-resident zeros row PER DISTINCT
    # byte count, forever — LRU-bound both
    MAX_PROGRAMS = 64
    MAX_ZEROS = 64

    def __init__(self, mesh: Optional[IciMesh] = None):
        self._mesh = mesh
        self._lock = _dbg.make_lock("DevicePlane._lock")
        self._programs: "collections.OrderedDict" = collections.OrderedDict()
        self._zeros: "collections.OrderedDict" = collections.OrderedDict()
        self._pending: Dict[int, DeviceTransfer] = {}   # posted sends
        self._active: set = set()      # posted-but-incomplete (drain gate)
        self._next_uuid = 1
        self._recent: collections.deque = collections.deque(maxlen=64)
        # local running totals (the bvar Adders are process-global and
        # shared with other planes a test may construct)
        self.transfers = 0
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.fallbacks = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.match_timeouts = 0

    @classmethod
    def instance(cls) -> "DevicePlane":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = DevicePlane()
            return cls._instance

    def mesh(self) -> IciMesh:
        return self._mesh or IciMesh.default()

    # ---- program cache -------------------------------------------------
    def _program(self, nbytes: int, src_dev: int, dst_dev: int):
        """Compile-or-fetch the (src → dst, nbytes) transfer program.
        Returns (fn, input_sharding, mesh2, src_device, dst_device)."""
        kernel = _flags.get_flag("ici_device_plane_kernel")
        gen = IciMesh.generation
        key = (nbytes, src_dev, dst_dev, kernel, gen)
        with self._lock:
            hit = self._programs.get(key)
            if hit is not None:
                self._programs.move_to_end(key)
                self.cache_hits += 1
        if hit is not None:
            _g_cache_hits << 1
            return hit
        built = self._build(nbytes, src_dev, dst_dev, kernel)
        with self._lock:
            # a racing builder may have won; keep the first (identical)
            entry = self._programs.setdefault(key, built)
            self._programs.move_to_end(key)
            while len(self._programs) > self.MAX_PROGRAMS:
                self._programs.popitem(last=False)
            self.cache_misses += 1
        _g_cache_misses << 1
        return entry

    def _build(self, nbytes: int, src_dev: int, dst_dev: int, kernel: str):
        import jax
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from ..butil.jax_compat import shard_map
        mesh = self.mesh()
        src, dst = mesh.device(src_dev), mesh.device(dst_dev)
        mesh2 = Mesh(np.array([src, dst]), ("p2p",))
        sharding = NamedSharding(mesh2, P("p2p"))
        if kernel == "pallas":
            per_device = self._pallas_body(nbytes)
        else:
            def per_device(x_local):          # (1, nbytes) local row
                return jax.lax.ppermute(x_local, "p2p", [(0, 1)])
        fn = jax.jit(shard_map(per_device, mesh=mesh2, in_specs=P("p2p"),
                               out_specs=P("p2p"), check_vma=False))
        return (fn, sharding, mesh2, src, dst)

    @staticmethod
    def _pallas_body(nbytes: int):
        """The hand-scheduled variant: one remote-DMA hop
        (pltpu.make_async_remote_copy = ibv_post_send over ICI; see
        pallas_ring.py for the ring-shaped sibling).  Symmetric shift —
        both submesh members post toward the other (ICI links are
        bidirectional, so the unused reverse hop is free on hardware);
        only the dst row of the output is consumed.  Interpret mode
        off-TPU so CI runs the exact kernel control flow."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        import jax.experimental.pallas as pl
        import jax.experimental.pallas.tpu as pltpu
        from ..butil.jax_compat import tpu_compiler_params
        interpret = _platform() != "tpu"

        def kern(local_ref, out_ref, comm_buf, send_sem, recv_sem):
            my_id = lax.axis_index("p2p")
            other = 1 - my_id
            comm_buf[0] = local_ref[:]
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_buf.at[0],
                dst_ref=comm_buf.at[1],
                send_sem=send_sem.at[0],
                recv_sem=recv_sem.at[1],
                device_id=other,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait()
            out_ref[:] = comm_buf[1]

        def per_device(x_local):              # (1, nbytes)
            out = pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct((nbytes,), jnp.uint8),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec(memory_space=pl.ANY),
                scratch_shapes=[
                    pltpu.VMEM((2, nbytes), jnp.uint8),
                    pltpu.SemaphoreType.DMA((2,)),
                    pltpu.SemaphoreType.DMA((2,)),
                ],
                compiler_params=tpu_compiler_params(has_side_effects=True,
                                                    collective_id=2),
                interpret=interpret,
            )(x_local[0])
            return out[None]

        return per_device

    def _zeros_row(self, dst_dev: int, nbytes: int):
        """The dst-side input row (ppermute delivers INTO the program, so
        dst contributes a dummy shard).  Cached per (dst, size): steady
        workloads pay this device_put once, not per transfer."""
        import jax
        import jax.numpy as jnp
        gen = IciMesh.generation
        key = (dst_dev, nbytes, gen)
        with self._lock:
            z = self._zeros.get(key)
            if z is not None:
                self._zeros.move_to_end(key)
        if z is None:
            z = jax.device_put(jnp.zeros((1, nbytes), jnp.uint8),
                               self.mesh().device(dst_dev))
            with self._lock:
                z = self._zeros.setdefault(key, z)
                self._zeros.move_to_end(key)
                while len(self._zeros) > self.MAX_ZEROS:
                    self._zeros.popitem(last=False)
        return z

    # ---- QP interface --------------------------------------------------
    def next_uuid(self) -> int:
        with self._lock:
            u = self._next_uuid
            self._next_uuid += 1
            return u

    def post_send(self, arr, src_dev: int, dst_dev: int, socket=None,
                  uuid: Optional[int] = None,
                  remote: bool = False) -> DeviceTransfer:
        """Post one send WR.  ``arr``: flat uint8 jax array resident on
        mesh device ``src_dev``.  Raises DevicePlaneError (before any
        descriptor exists) when refused — chaos injection, or a plane
        that cannot serve the route — so the caller can fall back in the
        same frame."""
        from ..rpc import fault_injection as _fi
        plan = _fi.fabric_active()
        if plan is not None and plan.on_device_post(socket):
            with self._lock:
                self.fallbacks += 1
            _g_fallbacks << 1
            raise DevicePlaneError("injected device-plane post refusal")
        if src_dev == dst_dev:
            raise DevicePlaneError("device plane is point-to-point; "
                                   "same-device payloads are ref passes")
        nbytes = int(arr.shape[0])
        # trace context at post time: the server span being served, or
        # the ACTIVE client span (channel write path) — the context the
        # kind-4 descriptor carries to the receiver
        from ..rpc import span as _span
        tid, psid = _span.current_trace_context()
        t = DeviceTransfer(uuid if uuid is not None else self.next_uuid(),
                           src_dev, dst_dev, nbytes, src_arr=arr,
                           trace_id=tid, parent_span_id=psid)
        # compile (or fetch) NOW: a compilation error must surface before
        # the descriptor is committed to any wire
        try:
            self._program(nbytes, src_dev, dst_dev)
        except Exception as e:
            with self._lock:
                self.fallbacks += 1
            _g_fallbacks << 1
            raise DevicePlaneError(f"transfer program build failed: {e}")
        if not remote:
            with self._lock:
                self._pending[t.uuid] = t
        self._track(t)  # fablint: custody-moved(completion-path) the CQ done()/_fail callback untracks when the transfer completes or dies; fail_pending sweeps the orphans
        self._recent.append(t)
        self._annotate(t, "posted")
        self._sweep_stale()
        return t

    def post_recv(self, uuid: int) -> DeviceTransfer:
        """In-process rendezvous: match the posted send and join the
        compiled program.  Raises KeyError when no matching send is
        pending (already reaped by the match timeout, or never posted).
        On a program execution failure the transfer degrades internally
        to a plain device_put of the still-pinned source — the payload is
        in this process either way, so delivery must not fail."""
        with self._lock:
            t = self._pending.pop(uuid, None)
        if t is None:
            raise KeyError(f"device plane: no posted send {uuid:#x}")
        arr = t.source_array()
        try:
            out = self._run(t, {t.src_dev: arr.reshape(1, t.nbytes),
                                t.dst_dev: None})
        except Exception as e:
            # in-process degrade: device_put the pinned source (counted);
            # the compiled path failed but the bytes must still arrive
            import jax
            log.warning("device plane %s: compiled transfer failed (%s) — "
                        "device_put fallback", t.describe()["route"], e)
            with self._lock:
                self.fallbacks += 1
            _g_fallbacks << 1
            out = jax.device_put(arr, self.mesh().device(t.dst_dev))
        self._matched(t, out)
        return t

    # ---- fabric (multi-controller) halves ------------------------------
    def post_recv_remote(self, uuid: int, nbytes: int, src_dev: int,
                         dst_dev: int, socket=None, trace_id: int = 0,
                         parent_span_id: int = 0) -> DeviceTransfer:
        """Receiver half of a cross-process transfer: the descriptor
        arrived on the control channel; register the recv WR.  The
        collective itself runs on the fabric socket's executor (control
        order = execution order on both sides, the SPMD ordering
        contract).  ``trace_id``/``parent_span_id`` come off the kind-4
        descriptor, so the receiver's half of the transfer joins the
        sender's trace."""
        t = DeviceTransfer(uuid, src_dev, dst_dev, nbytes,
                           trace_id=trace_id,
                           parent_span_id=parent_span_id)
        self._track(t)  # fablint: custody-moved(completion-path) finish_remote/execute_remote completion or failure untracks; fail_pending sweeps the orphans
        self._recent.append(t)
        self._annotate(t, "recv enqueued")
        return t

    def execute_remote(self, t: DeviceTransfer) -> None:
        """Enter the compiled program with THIS process's shard (payload
        row when we own src, dummy row when we own dst).  Called on the
        fabric executor thread; blocks until the peer joins.  Failure
        fails the transfer (completion signaled with an error) and
        re-raises so the socket degrades its plane."""
        shards = {t.src_dev: None, t.dst_dev: None}
        arr = t.source_array()
        if arr is not None:                    # we are the sender
            shards[t.src_dev] = arr.reshape(1, t.nbytes)
        try:
            out = self._run(t, shards, local_only=True)
        except Exception as e:
            self._fail(t, f"remote execution failed: {e}")
            raise
        self._matched(t, out)

    # ---- execution -----------------------------------------------------
    def _run(self, t: DeviceTransfer, rows: Dict[int, Any],
             local_only: bool = False):
        """Build the global (2, n) input and run the cached program.
        ``rows[dev]``: the (1, n) shard for that mesh device, None for a
        dummy/other-process shard.  Returns the dst-resident flat array
        (None when dst is not addressable from this process)."""
        import jax
        fn, sharding, mesh2, src, dst = self._program(
            t.nbytes, t.src_dev, t.dst_dev)
        shards = []
        for dev_id, device in ((t.src_dev, src), (t.dst_dev, dst)):
            row = rows.get(dev_id)
            if row is None:
                if local_only and not _is_local(device):
                    continue               # the peer process's shard
                row = self._zeros_row(dev_id, t.nbytes)
            shards.append(row)
        ga = jax.make_array_from_single_device_arrays(
            (2, t.nbytes), sharding, shards)
        out_global = fn(ga)
        out = None
        for s in out_global.addressable_shards:
            if s.device == dst:
                out = s.data.reshape(t.nbytes)
                break
        return out

    def _matched(self, t: DeviceTransfer, out) -> None:
        t.state = MATCHED
        t.matched_ns = time.monotonic_ns()
        t.out = out
        self._annotate(t, "matched")
        # bytes_sent is a SENDER-side counter: a pure receiver (fabric
        # recv half, no source pinned) must not inflate it — in-process
        # transfers are both roles and count both directions
        sender = t.source_array() is not None
        with self._lock:
            self.transfers += 1
            if sender:
                self.bytes_sent += t.nbytes
        _g_transfers << 1
        if sender:
            _g_bytes_sent << t.nbytes

        def done() -> None:
            t.state = COMPLETE
            t.complete_ns = time.monotonic_ns()
            if out is not None:
                with self._lock:
                    self.bytes_recv += t.nbytes
                _g_bytes_recv << t.nbytes
            t._release_source()
            self._untrack(t)
            # pin hold-time: posted→complete is exactly how long the
            # source HBM block stayed pinned (the :926 discipline)
            self._annotate(
                t, "complete pin_held_us="
                   f"{(t.complete_ns - t.posted_ns) // 1000}")
            self._close_span(t, 0)
            t.completion.signal(0)

        if out is not None:
            # the device stream is the CQ: completion fires when the
            # transfer's output is physically resident at dst
            device_on_ready([out], done)
        else:
            done()           # sender-only half: participation is complete

    def _fail(self, t: DeviceTransfer, reason: str) -> None:
        t.state = FAILED
        t.error = reason
        t._release_source()
        self._untrack(t)
        self._annotate(t, f"failed: {reason}")
        self._close_span(t, 1)
        t.completion.signal(1)

    def fail_transfer(self, t: DeviceTransfer, reason: str) -> None:
        """Fail a transfer that can never execute (its socket died while
        it sat in an executor queue): completion fires with an error and
        the source pin releases."""
        self._fail(t, reason)

    def finish_remote(self, t: DeviceTransfer, out) -> None:
        """Complete a cross-process transfer whose bytes were moved by
        the transport itself (the bulk-carried xproc leg): same CQ
        semantics as the compiled path — completion signals when ``out``
        is resident at dst (sender half passes None), and the source pin
        releases exactly then."""
        self._matched(t, out)

    # ---- drain barrier (lame-duck server stop) -------------------------
    def _track(self, t: DeviceTransfer) -> None:
        _ledger.acquire("dev.transfer", (id(self), t.uuid))
        with self._lock:
            self._active.add(t)

    def _untrack(self, t: DeviceTransfer) -> None:
        # non-strict: discard is idempotent (a fail_pending sweep can
        # race the CQ callback), so a second untrack must stay a no-op
        _ledger.release("dev.transfer", (id(self), t.uuid))
        with self._lock:
            self._active.discard(t)

    def active_transfers(self) -> int:
        """Posted-but-incomplete transfers — the server drain gate waits
        for this to reach zero inside the grace window (completion fires,
        pins release — never a leaked HBM pin)."""
        with self._lock:
            return len(self._active)

    def fail_pending(self, reason: str,
                     posted_before_ns: Optional[int] = None) -> None:
        """Fail posted sends whose rendezvous never came (lame-duck
        grace expired): completions fire with an error and the source
        pins release NOW instead of at the 30s match-timeout sweep.
        ``posted_before_ns`` scopes the reap to sends already posted at
        that instant — the plane is process-global, and a transfer some
        OTHER server/channel posted mid-drain (healthy traffic matches
        in microseconds) must not be collateral."""
        stale = []
        with self._lock:
            for uuid, t in list(self._pending.items()):
                if posted_before_ns is None \
                        or t.posted_ns < posted_before_ns:
                    stale.append(self._pending.pop(uuid))
        for t in stale:
            self._fail(t, reason)

    def _sweep_stale(self) -> None:
        """Reap posted sends whose recv never matched (peer died between
        descriptor and rendezvous): fail ONLY those transfers, releasing
        their source pins."""
        timeout = _flags.get_flag("ici_device_plane_match_timeout_s")
        cutoff = time.monotonic_ns() - int(timeout * 1e9)
        stale = []
        with self._lock:
            for uuid, t in list(self._pending.items()):
                if t.posted_ns < cutoff:
                    stale.append(self._pending.pop(uuid))
        for t in stale:
            with self._lock:
                self.match_timeouts += 1
            _g_match_timeouts << 1
            self._fail(t, "no matching recv within "
                          f"{timeout}s (match timeout)")

    # ---- observability -------------------------------------------------
    def _annotate(self, t: DeviceTransfer, what: str) -> None:
        from ..rpc import span as _span
        text = (f"device_plane {what} uuid={t.uuid:#x} "
                f"ici://{t.src_dev}->{t.dst_dev} {t.nbytes}B")
        if t.span is not None:
            # the transfer owns a span in the RPC's trace: its lifecycle
            # lands there on BOTH processes (the receiver's context rode
            # the descriptor) instead of on whatever span happens to be
            # bthread-local on one side
            t.span.annotate(text)
        else:
            _span.annotate_current(text)

    def annotate_transfer(self, t: DeviceTransfer, what: str) -> None:
        """Public hook for transfer-lifecycle events raised OUTSIDE the
        plane (the CollectiveSequencer's assignment/queue-wait/admit)."""
        self._annotate(t, what)

    @staticmethod
    def _close_span(t: DeviceTransfer, error_code: int) -> None:
        from ..rpc import span as _span
        span, t.span = t.span, None
        if span is not None:
            _span.end_span(span, error_code)

    def pending_sends(self) -> int:
        with self._lock:
            return len(self._pending)

    def recent_transfers(self) -> List[dict]:
        return [t.describe() for t in list(self._recent)]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = {
                "transfers": self.transfers,
                "bytes_sent": self.bytes_sent,
                "bytes_recv": self.bytes_recv,
                "fallbacks": self.fallbacks,
                "program_cache_hits": self.cache_hits,
                "program_cache_misses": self.cache_misses,
                "match_timeouts": self.match_timeouts,
            }
        out["pending_sends"] = self.pending_sends()
        return out

    # ---- one-call convenience (in-process transports) ------------------
    def transfer_local(self, arr, src_dev: int, dst_dev: int, socket=None):
        """post_send + immediate rendezvous: the in-process fast path
        used by the native plane's relocation upcall.  Returns the
        dst-resident array (an XLA future; the transfer's completion
        releases the source pin).  Raises DevicePlaneError on refusal."""
        t = self.post_send(arr, src_dev, dst_dev, socket=socket)
        return self.post_recv(t.uuid)


def _is_local(device) -> bool:
    try:
        import jax
        return device.process_index == jax.process_index()
    except Exception:
        return True


def plane() -> DevicePlane:
    return DevicePlane.instance()
