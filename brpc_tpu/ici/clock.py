"""Per-peer clock alignment for cross-process span stitching.

Span clocks are per-process: ``monotonic_ns`` timelines from two
processes cannot be compared, and wall clocks on two hosts drift.  Each
fabric socket pair therefore estimates its peer's wall-clock offset with
one NTP-style exchange piggybacked ON THE HELLO/HELLO_OK HANDSHAKE
itself (``FabricNode.connect`` stamps its wall ``t0`` into the HELLO
json; ``_handshake_server`` echoes it with the server's wall in the
HELLO_OK body) — deliberately NO control frame of its own, so the chaos
suite's deterministic frame counting and the read loop never see it:

    t0 = local wall at HELLO send        (monotonic stamp kept alongside)
    pw = peer wall stamped into HELLO_OK (echoing t0)
    t1 = local monotonic at HELLO_OK receipt

    rtt        = t1 - t0 (monotonic)
    offset_us  = pw - (t0 + rtt/2)       # peer_wall - local_wall estimate
    bound_us   = rtt/2                   # the estimate's error bound

The bound is exact in the NTP sense: the peer stamped ``pw`` somewhere
inside our [t0, t1] window, so the true offset lies within ±rtt/2 of the
estimate — cross-process span ordering derived from it is *explicit and
bounded*, never assumed.  The table keeps the MINIMUM-bound sample per
peer (the tightest window wins; a re-probe on a later socket can only
improve it), which is also how the reference's rpcz treats client/server
skew: order is trusted only past the bound.

Consumers: the pod-scope ``/rpcz`` stitcher maps a remote span's wall
anchor into local time as ``local_est = remote_wall - offset_us`` and
reports ``bound_us`` with every aligned timestamp.
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from ..butil import debug_sync as _dbg

_lock = _dbg.make_lock("ici.clock._lock")
# pid -> (offset_us, bound_us, recorded_monotonic)
_peers: Dict[int, Tuple[float, float, float]] = {}

# fablint guarded-state contract: samples arrive from every fabric
# socket's control read loop concurrently
_GUARDED_BY_GLOBALS = {
    "_peers": "_lock",
}

# a sample this old is replaced even by a looser-bound fresh one (drift
# over hours would otherwise hide behind one lucky tight probe)
_STALE_S = 600.0

# Samples are only taken at HELLO time, so on a long-lived socket pair
# the estimate AGES with no re-probe; the reported bound widens by an
# age-proportional drift allowance so it stays honest — ~20 ppm covers
# typical unsynced crystal drift (NTP-disciplined hosts drift far
# less).  Reconnects/re-dials (and every pod-scope query's fan-out
# channels) refresh the sample and re-tighten the bound.
_DRIFT_US_PER_S = 20.0


def record(pid: int, offset_us: float, bound_us: float) -> None:
    """Record one offset sample for ``pid``; keeps the tightest-bound
    non-stale sample."""
    now = time.monotonic()
    with _lock:
        prev = _peers.get(pid)
        if prev is not None and now - prev[2] < _STALE_S \
                and prev[1] + (now - prev[2]) * _DRIFT_US_PER_S \
                <= bound_us:
            # the previous sample, drift-aged, is still tighter
            return
        _peers[pid] = (float(offset_us), float(bound_us), now)


def offset(pid: int) -> Optional[Tuple[float, float]]:
    """(offset_us, bound_us) for ``pid`` — peer_wall minus local_wall —
    or None when no fabric exchange has sampled that peer yet.  The
    bound includes the age-proportional drift allowance, so an estimate
    sampled hours ago honestly reports a wide bound."""
    with _lock:
        entry = _peers.get(pid)
    if entry is None:
        return None
    age_s = max(0.0, time.monotonic() - entry[2])
    return entry[0], entry[1] + age_s * _DRIFT_US_PER_S


def to_local_wall_us(pid: int, remote_wall_us: float) -> Tuple[float, float]:
    """Map a remote process's wall timestamp onto the local wall axis:
    (aligned_us, bound_us).  Unknown peers pass through with bound -1
    (same-host NTP wall clocks are the unrefined fallback)."""
    entry = offset(pid)
    if entry is None:
        return float(remote_wall_us), -1.0
    return float(remote_wall_us) - entry[0], entry[1]


def describe() -> Dict[str, dict]:
    with _lock:
        snap = dict(_peers)
    now = time.monotonic()
    return {str(pid): {"offset_us": round(off, 1),
                       "bound_us": round(bound, 1),
                       "age_s": round(now - at, 1)}
            for pid, (off, bound, at) in snap.items()}


def reset_for_test() -> None:
    with _lock:
        _peers.clear()
