"""ici — the device-fabric transport and collectives layer (the rdma/
analogue of SURVEY.md §2.4, rebuilt on XLA over the ICI mesh)."""
from .mesh import IciMesh
from .transport import (IciSocket, ici_listen, ici_unlisten, ici_connect,
                        ici_transport_stats)
from .collective import Collectives, default_collectives
from .ring import ring_all_reduce, RingStream
from . import device_plane
from .device_plane import DevicePlane, DeviceTransfer, DevicePlaneError
from . import pallas_ring
from . import ring_attention
from .pod import Pod, PodMember
