"""IciMesh: the device mesh underlying the ici:// transport.

The reference's "cluster" is whatever naming services return; the TPU
fabric's first-class cluster is the accelerator mesh itself
(jax.sharding.Mesh).  This module owns the process-global mesh: logical
device ids (the ``ici://k`` endpoints), the collective axis, and neighbor
topology for ring pipelines.

On test hosts the mesh is the 8-device virtual CPU platform from conftest;
on hardware it is the real TPU slice.  Everything above (transport,
collectives, combo-channel lowering) is written against this abstraction so
the same code compiles for both.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence, Tuple

from ..butil.endpoint import EndPoint, SCHEME_ICI

AXIS = "ici"


class IciMesh:
    _default: Optional["IciMesh"] = None
    _lock = threading.Lock()
    # bumped whenever the default mesh is (re)bound: consumers caching
    # mesh-relative facts (e.g. native_plane's array->logical-id cache)
    # key their entries on this and recompute after a swap — a stale
    # logical id would silently skip relocation for a wrongly-"resident"
    # array (review finding r5)
    generation: int = 0

    def __init__(self, devices: Optional[Sequence] = None,
                 axis_name: str = AXIS):
        import jax
        import numpy as np
        from jax.sharding import Mesh
        self.devices = list(devices if devices is not None else jax.devices())
        self.axis_name = axis_name
        self.mesh = Mesh(np.array(self.devices), (axis_name,))
        self.size = len(self.devices)
        # O(1) device→logical-id lookup for the transport hot path
        self._dev_index = {d: i for i, d in enumerate(self.devices)}

    def device_index(self, device) -> int:
        """Logical id of a jax device in this mesh (-1 if absent)."""
        return self._dev_index.get(device, -1)

    @classmethod
    def default(cls) -> "IciMesh":
        with cls._lock:
            if cls._default is None:
                cls._default = IciMesh()
            return cls._default

    @classmethod
    def set_default(cls, mesh: "IciMesh") -> None:
        with cls._lock:
            cls._default = mesh
            cls.generation += 1

    # ---- endpoints -----------------------------------------------------
    def endpoint(self, device_id: int) -> EndPoint:
        return EndPoint(scheme=SCHEME_ICI, coords=(device_id,))

    def endpoints(self) -> List[EndPoint]:
        return [self.endpoint(i) for i in range(self.size)]

    def device(self, device_id: int):
        return self.devices[device_id % self.size]

    # ---- topology ------------------------------------------------------
    def ring_perm(self, shift: int = 1) -> List[Tuple[int, int]]:
        """Source→dest pairs rotating the ring by ``shift`` hops."""
        n = self.size
        return [(i, (i + shift) % n) for i in range(n)]

    def neighbors(self, device_id: int) -> List[int]:
        n = self.size
        if n == 1:
            return [0]
        return sorted({(device_id - 1) % n, (device_id + 1) % n})

    def sharding(self, spec=None):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh,
                             spec if spec is not None else PartitionSpec())

    def shard_along_axis(self):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh, PartitionSpec(self.axis_name))
