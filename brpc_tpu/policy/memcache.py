"""Memcached binary protocol (client side, pipelined).

Reference: src/brpc/policy/memcache_binary_protocol.cpp + memcache.{h,cpp}
— client-only, requests pipeline on one connection, responses correlate by
order (opaque is also carried for defense).  24-byte binary header per the
memcached binary spec.
"""
from __future__ import annotations

import struct
from typing import Any, List

from ..butil.iobuf import IOBuf
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import Protocol, ParseResult, register_protocol

MAGIC_REQUEST = 0x80
MAGIC_RESPONSE = 0x81

OP_GET = 0x00
OP_SET = 0x01
OP_ADD = 0x02
OP_REPLACE = 0x03
OP_DELETE = 0x04
OP_INCREMENT = 0x05
OP_DECREMENT = 0x06
OP_FLUSH = 0x08
OP_NOOP = 0x0A
OP_VERSION = 0x0B
OP_TOUCH = 0x1C

STATUS_OK = 0x0000
STATUS_KEY_NOT_FOUND = 0x0001
STATUS_KEY_EXISTS = 0x0002

_HDR = struct.Struct(">BBHBBHIIQ")     # magic op keylen extras dt status/vb bodylen opaque cas


class MemcacheRequest:
    def __init__(self):
        self._ops: List[bytes] = []

    def _add(self, opcode: int, key: bytes = b"", value: bytes = b"",
             extras: bytes = b"") -> None:
        body_len = len(extras) + len(key) + len(value)
        opaque = len(self._ops)
        hdr = _HDR.pack(MAGIC_REQUEST, opcode, len(key), len(extras), 0, 0,
                        body_len, opaque, 0)
        self._ops.append(hdr + extras + key + value)

    def get(self, key) -> None:
        self._add(OP_GET, _b(key))

    def set(self, key, value, flags: int = 0, exptime: int = 0) -> None:
        self._add(OP_SET, _b(key), _b(value),
                  struct.pack(">II", flags, exptime))

    def add(self, key, value, flags: int = 0, exptime: int = 0) -> None:
        self._add(OP_ADD, _b(key), _b(value),
                  struct.pack(">II", flags, exptime))

    def replace(self, key, value, flags: int = 0, exptime: int = 0) -> None:
        self._add(OP_REPLACE, _b(key), _b(value),
                  struct.pack(">II", flags, exptime))

    def delete(self, key) -> None:
        self._add(OP_DELETE, _b(key))

    def incr(self, key, delta: int = 1, initial: int = 0) -> None:
        self._add(OP_INCREMENT, _b(key),
                  extras=struct.pack(">QQI", delta, initial, 0))

    def decr(self, key, delta: int = 1, initial: int = 0) -> None:
        self._add(OP_DECREMENT, _b(key),
                  extras=struct.pack(">QQI", delta, initial, 0))

    def version(self) -> None:
        self._add(OP_VERSION)

    def op_count(self) -> int:
        return len(self._ops)

    def serialize(self) -> bytes:
        return b"".join(self._ops)


def _b(v) -> bytes:
    return v.encode() if isinstance(v, str) else bytes(v)


class MemcacheOpResponse:
    __slots__ = ("opcode", "status", "value", "cas", "flags")

    def __init__(self, opcode: int, status: int, value: bytes, cas: int,
                 flags: int):
        self.opcode = opcode
        self.status = status
        self.value = value
        self.cas = cas
        self.flags = flags

    def ok(self) -> bool:
        return self.status == STATUS_OK


class MemcacheResponse:
    def __init__(self):
        self.ops: List[MemcacheOpResponse] = []

    def op(self, i: int = 0) -> MemcacheOpResponse:
        return self.ops[i]


# ---- protocol callbacks ----------------------------------------------

def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    """Bundle every complete response frame into ONE message: pipelined
    responses must be consumed strictly in order (see redis.parse)."""
    head = source.fetch(1)
    if head is None:
        return ParseResult.not_enough_data()
    if head[0] not in (MAGIC_RESPONSE, MAGIC_REQUEST):
        return ParseResult.try_others()
    # 0x80/0x81 collide with small binary frames of other protocols (mongo
    # lengths 128/129); memcache is client-only (reference parity) — only
    # claim when a memcache call is outstanding on this socket
    if getattr(arg, "server", None) is not None or \
            not getattr(socket, "pipelined_contexts", None):
        return ParseResult.try_others()
    data = source.fetch(len(source))
    ops: List[MemcacheOpResponse] = []
    pos = 0
    while pos + 24 <= len(data):
        (magic, opcode, keylen, extraslen, _dt, status, bodylen, opaque,
         cas) = _HDR.unpack(data[pos:pos + 24])
        if pos + 24 + bodylen > len(data):
            break
        body = data[pos + 24:pos + 24 + bodylen]
        extras = body[:extraslen]
        value = body[extraslen + keylen:]
        flags = struct.unpack(">I", extras[:4])[0] if len(extras) >= 4 else 0
        ops.append(MemcacheOpResponse(opcode, status, value, cas, flags))
        pos += 24 + bodylen
    if not ops:
        return ParseResult.not_enough_data()
    source.pop_front(pos)
    return ParseResult.ok(ops)


def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    if not isinstance(request, MemcacheRequest):
        raise TypeError("memcache request must be a MemcacheRequest")
    cntl._memcache_expected = request.op_count()
    return IOBuf(request.serialize())


OP_SASL_AUTH = 0x21


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    out = IOBuf()
    # CouchbaseAuthenticator (policy/couchbase_authenticator.cpp): SASL
    # PLAIN auth precedes the first op on each connection; its response
    # is consumed via ctx.auth_skip
    sock = getattr(cntl, "_pack_socket", None)
    cntl._memcache_auth_skip = 0
    if cntl.auth_token and sock is not None and \
            not getattr(sock, "_memcache_authed", False):
        sock._memcache_authed = True
        mech = b"PLAIN"
        user, _, password = cntl.auth_token.partition(":")
        value = b"\x00" + user.encode() + b"\x00" + password.encode()
        out.append(_HDR.pack(MAGIC_REQUEST, OP_SASL_AUTH, len(mech), 0, 0,
                             0, len(mech) + len(value), 0, 0)
                   + mech + value)
        cntl._memcache_auth_skip = 1
    out.append(payload)
    return out


class _Ctx:
    __slots__ = ("cid", "expected", "ops", "auth_skip")

    def __init__(self, cid, expected):
        self.cid = cid
        self.expected = expected
        self.ops: List[MemcacheOpResponse] = []
        self.auth_skip = 0


def _make_pipeline_ctx(cid: int, cntl: Controller) -> _Ctx:
    skip = getattr(cntl, "_memcache_auth_skip", 0)
    ctx = _Ctx(cid, getattr(cntl, "_memcache_expected", 1) + skip)
    ctx.auth_skip = skip
    return ctx


def process_response(bundle: List[MemcacheOpResponse], socket) -> None:
    from ..bthread import id as bthread_id
    for msg in bundle:
        with socket._pipeline_lock:
            ctx = (socket.pipelined_contexts[0]
                   if socket.pipelined_contexts else None)
        if ctx is None:
            return
        ctx.ops.append(msg)
        if len(ctx.ops) < ctx.expected:
            continue
        with socket._pipeline_lock:
            if socket.pipelined_contexts and socket.pipelined_contexts[0] is ctx:
                socket.pipelined_contexts.pop(0)
        rc, cntl = bthread_id.lock(ctx.cid)
        if rc != 0 or cntl is None:
            continue
        auth_ops, user_ops = (ctx.ops[:ctx.auth_skip],
                              ctx.ops[ctx.auth_skip:])
        if any(not op.ok() for op in auth_ops):
            socket._memcache_authed = False
            cntl.set_failed(errors.ERPCAUTH, "memcache SASL auth failed")
        resp = MemcacheResponse()
        resp.ops = user_ops
        cntl.response = resp
        cntl.remote_side = socket.remote_side
        cntl.finish_parsed_response(ctx.cid)


PROTOCOL = Protocol(
    name="memcache",
    parse=parse,
    process_response=process_response,
    serialize_request=serialize_request,
    pack_request=pack_request,
    support_server=False,
    pipelined=True,
    make_pipeline_ctx=_make_pipeline_ctx,
)


def _register() -> None:
    from ..rpc.protocol import find_protocol
    if find_protocol("memcache") is None:
        register_protocol(PROTOCOL)


_register()
