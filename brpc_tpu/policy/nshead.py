"""nshead: 36-byte-head framed protocol + extensible service adaptors.

Reference behavior: src/brpc/nshead.h (the head layout + magic
0xfb709394), src/brpc/policy/nshead_protocol.cpp (parse: magic check at
offset 24, then head+body cut; client correlation is stored per-connection
because the wire carries no correlation id, hence pooled/short connections
only), src/brpc/nshead_service.h (raw service contract) and
src/brpc/nshead_pb_service_adaptor.h (meta-parse → pb-dispatch →
serialize-back adaptor).

The nshead frame is the substrate for a whole legacy family (nova_pbrpc,
public_pbrpc, ubrpc): those register as client-side *variants* whose
responses are cut by this protocol and completed through the per-call
pipeline context (the analogue of the reference stashing the correlation id
on the Socket).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..butil.iobuf import IOBuf
from ..butil import logging as log
from ..bthread import id as bthread_id
from ..proto import legacy_meta_pb2 as legacy_pb
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import (CONNECTION_TYPE_POOLED, CONNECTION_TYPE_SHORT,
                            Protocol, ParseResult, register_protocol)

NSHEAD_MAGIC = 0xFB709394
_HEAD = struct.Struct("<HHI16sIII")    # id ver log_id provider magic rsvd blen
HEAD_SIZE = _HEAD.size                 # 36
_MAGIC_OFF = 24                        # offsetof(nshead_t, magic_num)

NsheadMeta = legacy_pb.NsheadMeta


@dataclass
class NsheadHead:
    id: int = 0
    version: int = 0
    log_id: int = 0
    provider: bytes = b""
    magic_num: int = NSHEAD_MAGIC
    reserved: int = 0
    body_len: int = 0

    def pack(self) -> bytes:
        return _HEAD.pack(self.id & 0xFFFF, self.version & 0xFFFF,
                          self.log_id & 0xFFFFFFFF,
                          self.provider[:16], self.magic_num,
                          self.reserved & 0xFFFFFFFF, self.body_len)

    @staticmethod
    def unpack(raw: bytes) -> "NsheadHead":
        i, v, lid, prov, magic, rsvd, blen = _HEAD.unpack(raw[:HEAD_SIZE])
        return NsheadHead(i, v, lid, prov.rstrip(b"\x00"), magic, rsvd, blen)


class NsheadMessage:
    """head + raw body; both the request and response type of NsheadService."""
    __slots__ = ("head", "body")

    def __init__(self, head: Optional[NsheadHead] = None,
                 body: Optional[IOBuf] = None):
        self.head = head or NsheadHead()
        self.body = body if body is not None else IOBuf()

    def pack(self) -> IOBuf:
        self.head.body_len = len(self.body)
        out = IOBuf()
        out.append(self.head.pack())
        out.append(self.body)
        return out


class NsheadService:
    """Raw nshead server: subclass and override process_nshead_request.

    Call done() exactly once after filling `response` (async is fine —
    the reference's NsheadClosure works the same way)."""

    SERVICE_NAME = "nshead"

    def process_nshead_request(self, server, controller: Controller,
                               request: NsheadMessage,
                               response: NsheadMessage,
                               done: Callable[[], None]) -> None:
        raise NotImplementedError


class NsheadPbServiceAdaptor(NsheadService):
    """Bridge nshead frames onto protobuf services registered on the same
    server: parse dispatch meta from the raw request, run the pb method,
    serialize the pb response back into an nshead body."""

    def parse_nshead_meta(self, server, request: NsheadMessage,
                          controller: Controller,
                          meta: NsheadMeta) -> None:
        raise NotImplementedError

    def parse_request_from_iobuf(self, meta: NsheadMeta,
                                 request: NsheadMessage,
                                 controller: Controller, pb_req: Any) -> None:
        raise NotImplementedError

    def serialize_response_to_iobuf(self, meta: NsheadMeta,
                                    controller: Controller,
                                    pb_res: Any,
                                    response: NsheadMessage) -> None:
        raise NotImplementedError

    # the template method (reference: NsheadPbServiceAdaptor::
    # ProcessNsheadRequest in nshead_pb_service_adaptor.cpp)
    def process_nshead_request(self, server, controller, request, response,
                               done) -> None:
        meta = NsheadMeta()

        def fail_out() -> None:
            # the reference contract: SerializeResponseToIOBuf is called
            # with pb_res=NULL on failure so the adaptor can put error
            # information into the wire response (nshead itself has no
            # error channel; public_pbrpc etc. do)
            try:
                self.serialize_response_to_iobuf(meta, controller, None,
                                                 response)
            except Exception:
                pass
            done()

        # adaptor hooks run under exception guards: a raise must become a
        # protocol-level error response, not an empty-body reply
        try:
            self.parse_nshead_meta(server, request, controller, meta)
        except Exception as e:
            controller.set_failed(errors.EREQUEST,
                                  f"{type(e).__name__}: {e}")
        if controller.failed():
            fail_out()
            return
        md = server.find_method(meta.full_method_name)
        if md is None:
            controller.set_failed(errors.ENOMETHOD,
                                  f"no method {meta.full_method_name}")
            fail_out()
            return
        pb_req = md.request_cls()
        try:
            self.parse_request_from_iobuf(meta, request, controller, pb_req)
        except Exception as e:
            controller.set_failed(errors.EREQUEST,
                                  f"{type(e).__name__}: {e}")
        if controller.failed():
            fail_out()
            return
        pb_res = md.response_cls()
        fired = [False]

        def pb_done() -> None:
            if fired[0]:
                return
            fired[0] = True
            self.serialize_response_to_iobuf(meta, controller, pb_res,
                                             response)
            done()

        try:
            md.invoke(controller, pb_req, pb_res, pb_done)
        except Exception as e:
            log.error("nshead pb method %s raised: %s",
                      meta.full_method_name, e, exc_info=True)
            if not fired[0]:
                controller.set_failed(errors.EINTERNAL,
                                      f"{type(e).__name__}: {e}")
                pb_done()


# ---- client-variant plumbing -----------------------------------------
# The wire has no correlation id: each call pushes a context carrying the
# cid and a completion callback; responses pop contexts in order (pooled
# connections carry one call at a time, so order is trivially correct).

class NsheadCallCtx:
    __slots__ = ("cid", "complete", "proto_name", "extra")

    def __init__(self, cid: int, complete: Callable, proto_name: str,
                 extra: Any = None):
        self.cid = cid
        self.complete = complete
        self.proto_name = proto_name
        self.extra = extra


def _client_expects_nshead(socket) -> bool:
    ctxs = getattr(socket, "pipelined_contexts", None)
    return bool(ctxs) and isinstance(ctxs[0], NsheadCallCtx)


# ---- parse ------------------------------------------------------------

def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    """Identify by the magic at offset 24 (nshead_protocol.cpp pattern)."""
    server = getattr(arg, "server", None)
    if server is not None:
        if getattr(server, "_nshead_service", None) is None:
            return ParseResult.try_others()
    elif not _client_expects_nshead(socket):
        return ParseResult.try_others()
    probe = source.fetch(min(len(source), _MAGIC_OFF + 4))
    if probe is None or len(probe) < _MAGIC_OFF + 4:
        return ParseResult.not_enough_data()
    magic = int.from_bytes(probe[_MAGIC_OFF:_MAGIC_OFF + 4], "little")
    if magic != NSHEAD_MAGIC:
        return ParseResult.try_others()
    head_raw = source.fetch(HEAD_SIZE)
    if head_raw is None:
        return ParseResult.not_enough_data()
    head = NsheadHead.unpack(head_raw)
    if head.body_len > (1 << 31):
        return ParseResult.parse_error("absurd nshead body_len")
    if len(source) < HEAD_SIZE + head.body_len:
        return ParseResult.not_enough_data()
    source.pop_front(HEAD_SIZE)
    body = source.cut(head.body_len)
    return ParseResult.ok(NsheadMessage(head, body))


# ---- server side ------------------------------------------------------

def process_request(msg: NsheadMessage, socket, server) -> None:
    svc = getattr(server, "_nshead_service", None)
    if svc is None:
        socket.set_failed(errors.ENOSERVICE, "no nshead service")
        return
    cntl = Controller()
    cntl.server = server
    cntl.log_id = msg.head.log_id
    cntl.remote_side = socket.remote_side
    response = NsheadMessage()
    # response head defaults mirror the request envelope
    response.head = NsheadHead(id=msg.head.id, version=msg.head.version,
                               log_id=msg.head.log_id,
                               provider=msg.head.provider,
                               reserved=msg.head.reserved)
    fired = [False]

    def done() -> None:
        if fired[0]:
            return
        fired[0] = True
        socket.write(response.pack())
        if server_counted[0]:
            server.on_request_out()

    server_counted = [False]
    if not server.on_request_in():
        cntl.set_failed(errors.ELIMIT, "server max_concurrency reached")
        done()
        return
    server_counted[0] = True
    try:
        svc.process_nshead_request(server, cntl, msg, response, done)
    except Exception as e:
        log.error("nshead service raised: %s", e, exc_info=True)
        if not fired[0]:
            done()


# ---- client side (raw nshead calls) -----------------------------------

def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    if isinstance(request, NsheadMessage):
        cntl._nshead_head = request.head
        buf = IOBuf()
        buf.append(request.body)
        return buf
    if isinstance(request, (bytes, bytearray)):
        cntl._nshead_head = NsheadHead()
        return IOBuf(bytes(request))
    raise TypeError("nshead request must be NsheadMessage or bytes")


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    head: NsheadHead = getattr(cntl, "_nshead_head", None) or NsheadHead()
    head.log_id = head.log_id or cntl.log_id
    head.body_len = len(payload)
    out = IOBuf()
    out.append(head.pack())
    out.append(payload)
    return out


def _complete_raw(msg: NsheadMessage, socket, ctx: NsheadCallCtx) -> None:
    rc, cntl = bthread_id.lock(ctx.cid)
    if rc != 0 or cntl is None:
        return
    cntl.remote_side = socket.remote_side
    cntl.response = msg
    cntl.finish_parsed_response(ctx.cid)


def make_pipeline_ctx(cid: int, cntl: Controller) -> NsheadCallCtx:
    return NsheadCallCtx(cid, _complete_raw, "nshead")


def process_response(msg: NsheadMessage, socket) -> None:
    ctx = socket.pop_pipelined_context()
    if ctx is None or not isinstance(ctx, NsheadCallCtx):
        log.warning("nshead response with no outstanding call; dropped")
        return
    ctx.complete(msg, socket, ctx)


PROTOCOL = Protocol(
    name="nshead",
    parse=parse,
    process_request=process_request,
    process_response=process_response,
    serialize_request=serialize_request,
    pack_request=pack_request,
    supported_connection_type=CONNECTION_TYPE_POOLED | CONNECTION_TYPE_SHORT,
    pipelined=True,
    make_pipeline_ctx=make_pipeline_ctx,
)


def _register() -> None:
    from ..rpc.protocol import find_protocol
    if find_protocol("nshead") is None:
        register_protocol(PROTOCOL)


_register()
