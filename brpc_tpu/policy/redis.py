"""Redis protocol: RESP client + server.

Reference: src/brpc/policy/redis_protocol.cpp + redis.{h,cpp},
redis_command.cpp, redis_reply.cpp — the client speaks RESP with command
pipelining (multiple commands per RedisRequest, responses correlated by
arrival order, socket.h:256-262 pipelined_count); the server side
(RedisService) lets a brpc server answer redis-cli directly, dispatching on
the command name.

Usage, client:
    ch.init(target, options=ChannelOptions(protocol="redis"))
    req = RedisRequest(); req.add_command("SET", "k", "v")
    resp = ch.call_method("redis", cntl, req, RedisResponse)

Usage, server:
    class MyRedis(RedisService):
        def __init__(self):
            super().__init__()
            self.add_handler("GET", lambda args: self.data.get(args[0]))
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import Protocol, ParseResult, register_protocol

# ---- RESP codec -------------------------------------------------------

REPLY_STATUS = "status"
REPLY_ERROR = "error"
REPLY_INTEGER = "integer"
REPLY_BULK = "bulk"
REPLY_ARRAY = "array"
REPLY_NIL = "nil"


class RedisReply:
    __slots__ = ("type", "value")

    def __init__(self, type_: str, value: Any = None):
        self.type = type_
        self.value = value

    def is_error(self) -> bool:
        return self.type == REPLY_ERROR

    def __repr__(self) -> str:
        return f"RedisReply({self.type}, {self.value!r})"

    def __eq__(self, other) -> bool:
        if isinstance(other, RedisReply):
            return (self.type, self.value) == (other.type, other.value)
        return self.value == other


def encode_command(*args) -> bytes:
    """RESP array-of-bulk-strings encoding."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, str):
            a = a.encode()
        elif isinstance(a, (int, float)):
            a = str(a).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(a), a))
    return b"".join(out)


def encode_reply(value: Any) -> bytes:
    """Python value → RESP reply bytes."""
    if isinstance(value, RedisReply):
        if value.type == REPLY_STATUS:
            return b"+%s\r\n" % str(value.value).encode()
        if value.type == REPLY_ERROR:
            return b"-%s\r\n" % str(value.value).encode()
        value = value.value
    if value is None:
        return b"$-1\r\n"
    if isinstance(value, bool):
        return b":%d\r\n" % int(value)
    if isinstance(value, int):
        return b":%d\r\n" % value
    if isinstance(value, str):
        value = value.encode()
    if isinstance(value, (bytes, bytearray)):
        return b"$%d\r\n%s\r\n" % (len(value), bytes(value))
    if isinstance(value, (list, tuple)):
        return b"*%d\r\n" % len(value) + b"".join(
            encode_reply(v) for v in value)
    raise TypeError(f"cannot encode {type(value)} as RESP")


def _parse_one(data: bytes, pos: int) -> Optional[Tuple[RedisReply, int]]:
    """Parse one reply at pos; None if incomplete."""
    if pos >= len(data):
        return None
    line_end = data.find(b"\r\n", pos)
    if line_end < 0:
        return None
    marker = data[pos:pos + 1]
    line = data[pos + 1:line_end]
    nxt = line_end + 2
    if marker == b"+":
        return RedisReply(REPLY_STATUS, line.decode()), nxt
    if marker == b"-":
        return RedisReply(REPLY_ERROR, line.decode()), nxt
    if marker == b":":
        return RedisReply(REPLY_INTEGER, int(line)), nxt
    if marker == b"$":
        n = int(line)
        if n < 0:
            return RedisReply(REPLY_NIL), nxt
        if len(data) < nxt + n + 2:
            return None
        return RedisReply(REPLY_BULK, data[nxt:nxt + n]), nxt + n + 2
    if marker == b"*":
        n = int(line)
        if n < 0:
            return RedisReply(REPLY_NIL), nxt
        items = []
        for _ in range(n):
            r = _parse_one(data, nxt)
            if r is None:
                return None
            item, nxt = r
            items.append(item)
        return RedisReply(REPLY_ARRAY, items), nxt
    raise ValueError(f"bad RESP marker {marker!r}")


# ---- request/response objects ----------------------------------------

class RedisRequest:
    def __init__(self):
        self._commands: List[bytes] = []
        self.command_names: List[str] = []

    def add_command(self, *args) -> None:
        self._commands.append(encode_command(*args))
        self.command_names.append(str(args[0]).upper())

    def command_count(self) -> int:
        return len(self._commands)

    def serialize(self) -> bytes:
        return b"".join(self._commands)


class RedisResponse:
    def __init__(self):
        self.replies: List[RedisReply] = []

    def reply(self, i: int = 0) -> RedisReply:
        return self.replies[i]

    def reply_count(self) -> int:
        return len(self.replies)


# ---- client side ------------------------------------------------------

class _PipelinedRedisCtx:
    __slots__ = ("cid", "expected", "replies", "auth_skip")

    def __init__(self, cid: int, expected: int):
        self.auth_skip = 0
        self.cid = cid
        self.expected = expected
        self.replies: List[RedisReply] = []


def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    """Cut ALL complete RESP units into ONE bundle.  Unlike tpu_std frames
    (independent, processed concurrently), pipelined redis commands must be
    handled strictly in order — bundling keeps the batch on one processor
    (the reference's redis server consumes command batches serially too)."""
    data = source.fetch(len(source))
    if not data:
        return ParseResult.not_enough_data()
    if data[:1] not in b"+-:$*":
        return ParseResult.try_others()
    # RESP's markers are single bytes that collide with binary frames (e.g.
    # '$' = 0x24 is a plausible little-endian mongo length); only claim the
    # stream when redis is actually in play here — server side: a
    # RedisService is registered; client side: a redis call is outstanding
    # (the reference gates server protocols on enabled services too)
    server = getattr(arg, "server", None)
    if server is not None:
        if getattr(server, "redis_service", None) is None:
            return ParseResult.try_others()
    elif not getattr(socket, "pipelined_contexts", None):
        return ParseResult.try_others()
    units: List[RedisReply] = []
    pos = 0
    try:
        while pos < len(data):
            r = _parse_one(data, pos)
            if r is None:
                break
            reply, pos = r
            units.append(reply)
    except (ValueError, IndexError) as e:
        return ParseResult.parse_error(str(e))
    if not units:
        return ParseResult.not_enough_data()
    source.pop_front(pos)
    return ParseResult.ok(units)


def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    buf = IOBuf()
    if isinstance(request, RedisRequest):
        buf.append(request.serialize())
        cntl._redis_expected = request.command_count()
    elif isinstance(request, (list, tuple)):
        buf.append(encode_command(*request))
        cntl._redis_expected = 1
    else:
        raise TypeError("redis request must be RedisRequest or arg tuple")
    return buf


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    out = IOBuf()
    # RedisAuthenticator (policy/redis_authenticator.cpp): AUTH precedes
    # the first command on each connection; its +OK is consumed by the
    # response path via ctx.auth_skip, never surfaced to the user
    sock = getattr(cntl, "_pack_socket", None)
    cntl._redis_auth_skip = 0
    if cntl.auth_token and sock is not None and \
            not getattr(sock, "_redis_authed", False):
        sock._redis_authed = True
        out.append(encode_command("AUTH", *cntl.auth_token.split("\x00")))
        cntl._redis_auth_skip = 1
    out.append(payload)
    return out


def process_response(bundle: List[RedisReply], socket) -> None:
    """Replies correlate by arrival order; one ctx may span several."""
    from ..bthread import id as bthread_id
    for msg in bundle:
        with socket._pipeline_lock:
            ctx = (socket.pipelined_contexts[0]
                   if socket.pipelined_contexts else None)
        if ctx is None:
            return
        ctx.replies.append(msg)
        if len(ctx.replies) < ctx.expected:
            continue
        with socket._pipeline_lock:
            if socket.pipelined_contexts and socket.pipelined_contexts[0] is ctx:
                socket.pipelined_contexts.pop(0)
        rc, cntl = bthread_id.lock(ctx.cid)
        if rc != 0 or cntl is None:
            continue
        auth_replies, user_replies = (ctx.replies[:ctx.auth_skip],
                                      ctx.replies[ctx.auth_skip:])
        if any(r.is_error() for r in auth_replies):
            socket._redis_authed = False
            cntl.set_failed(errors.ERPCAUTH,
                            f"redis AUTH failed: {auth_replies[0].value}")
        resp = RedisResponse()
        resp.replies = user_replies
        cntl.response = resp
        cntl.remote_side = socket.remote_side
        cntl.finish_parsed_response(ctx.cid)


# ---- server side ------------------------------------------------------

class RedisService:
    """Server-side redis dispatcher (reference RedisService): register
    command handlers; unknown commands get -ERR."""

    def __init__(self):
        self._handlers: Dict[str, Callable[[List[bytes]], Any]] = {}
        self.add_handler("PING", lambda args: RedisReply(REPLY_STATUS, "PONG"))
        self.add_handler("COMMAND", lambda args: [])

    def add_handler(self, command: str,
                    fn: Callable[[List[bytes]], Any]) -> None:
        self._handlers[command.upper()] = fn

    def dispatch(self, command: List[RedisReply]) -> bytes:
        if not command:
            return encode_reply(RedisReply(REPLY_ERROR, "ERR empty command"))
        parts = [c.value if isinstance(c.value, (bytes, bytearray))
                 else str(c.value).encode() for c in command]
        name = parts[0].decode().upper()
        fn = self._handlers.get(name)
        if fn is None:
            return encode_reply(RedisReply(
                REPLY_ERROR, f"ERR unknown command '{name}'"))
        try:
            return encode_reply(fn(parts[1:]))
        except Exception as e:
            return encode_reply(RedisReply(REPLY_ERROR, f"ERR {e}"))


def process_request(bundle: List[RedisReply], socket, server) -> None:
    svc = getattr(server, "redis_service", None)
    if svc is None:
        socket.write(IOBuf(encode_reply(RedisReply(
            REPLY_ERROR, "ERR this server has no RedisService"))))
        return
    out = []
    for msg in bundle:          # strict order within the pipeline batch
        if msg.type == REPLY_ARRAY:
            out.append(svc.dispatch(msg.value))
        else:                   # inline command
            parts = [RedisReply(REPLY_BULK, p) for p in bytes(
                msg.value if isinstance(msg.value, (bytes, bytearray))
                else str(msg.value).encode()).split()]
            out.append(svc.dispatch(parts))
    socket.write(IOBuf(b"".join(out)))


def _make_pipeline_ctx(cid: int, cntl: Controller) -> _PipelinedRedisCtx:
    skip = getattr(cntl, "_redis_auth_skip", 0)
    ctx = _PipelinedRedisCtx(cid,
                             getattr(cntl, "_redis_expected", 1) + skip)
    ctx.auth_skip = skip
    return ctx


PROTOCOL = Protocol(
    name="redis",
    parse=parse,
    process_request=process_request,
    process_response=process_response,
    serialize_request=serialize_request,
    pack_request=pack_request,
    pipelined=True,
    make_pipeline_ctx=_make_pipeline_ctx,
)


def _register() -> None:
    from ..rpc.protocol import find_protocol
    if find_protocol("redis") is None:
        register_protocol(PROTOCOL)


_register()
