"""ubrpc: nshead frames whose body is an mcpack envelope (UB ecosystem).

Reference behavior: src/brpc/policy/ubrpc2pb_protocol.cpp — the request
body is one mcpack object {content: [{service_name, method, id, params:
{...}}]}; `params` with a single field means that field's value is the
user request (idl wrapper convention), otherwise params itself is.  The
response is {content: [{id, result?, result_params: {...}}]} on success
or {content: [{id, error: {code, message}}]} on failure.  The reference
registers two variants differing only in serialization format
(compack / mcpack_v2); our peers speak mcpack_v2, and `ubrpc_compack` is
registered as an alias of the same wire so reference-shaped call sites
keep working (compack itself is a Baidu-internal sibling format with no
public speakers).

Server side is an NsheadPbServiceAdaptor (UbrpcAdaptor); client rides
the shared nshead cutter through per-call pipeline contexts, verifying
the echoed `id`.
"""
from __future__ import annotations

from typing import Any

from ..butil.iobuf import IOBuf
from ..bthread import id as bthread_id
from ..codec.mcpack import (mcpack_encode, mcpack_decode, pb_to_dict,
                            dict_to_pb)
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import (CONNECTION_TYPE_POOLED, CONNECTION_TYPE_SHORT,
                            Protocol, ParseResult, register_protocol,
                            find_protocol)
from .nshead import NsheadCallCtx, NsheadHead, NsheadMessage, \
    NsheadPbServiceAdaptor


def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    # stash the pb; the envelope needs the method identity at pack time
    cntl._ubrpc_request = request
    return IOBuf()


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str, _compack: bool = False) -> IOBuf:
    service, _, method_name = method_full_name.rpartition(".")
    request = getattr(cntl, "_ubrpc_request", None)
    params = pb_to_dict(request) if request is not None else {}
    envelope = {
        "content": [{
            "service_name": service,
            "method": method_name,
            "id": cid,
            # single-field params: the value is the user request (the
            # reference's idl-wrapper convention)
            "params": {"req": params},
        }],
    }
    data = mcpack_encode(envelope, compack=_compack)
    head = NsheadHead(log_id=cntl.log_id, body_len=len(data))
    out = IOBuf()
    out.append(head.pack())
    out.append(data)
    return out


def _complete(msg: NsheadMessage, socket, ctx: NsheadCallCtx) -> None:
    rc, cntl = bthread_id.lock(ctx.cid)
    if rc != 0 or cntl is None:
        return
    cntl.remote_side = socket.remote_side
    # EVERYTHING between lock and finish runs under one exception guard:
    # an uncaught raise here would leave the correlation id locked and the
    # caller blocked forever (the messenger only logs handler exceptions)
    try:
        envelope = mcpack_decode(msg.body.to_bytes())
        content = envelope.get("content") or []
        item = content[0] if content else {}
        if not isinstance(item, dict):
            raise ValueError("content[0] is not an object")
        got_id = item.get("id")
        err = item.get("error")
        if isinstance(err, dict):
            cntl.set_failed(int(err.get("code") or errors.EINTERNAL),
                            str(err.get("message") or "ubrpc error"))
        elif got_id is not None and got_id != ctx.cid:
            cntl.set_failed(errors.ERESPONSE,
                            f"response id {got_id} != call id {ctx.cid}")
        else:
            if "result" in item:
                cntl.idl_result = item["result"]
            rp = item.get("result_params") or {}
            # single-field wrapper unwraps to the response object
            if isinstance(rp, dict) and len(rp) == 1:
                (only,) = rp.values()
                if isinstance(only, dict):
                    rp = only
            if cntl._response_cls is not None:
                cntl.response = dict_to_pb(rp, cntl._response_cls())
            else:
                cntl.response = rp
    except Exception as e:
        cntl.set_failed(errors.ERESPONSE, f"bad ubrpc response: {e}")
    cntl.finish_parsed_response(ctx.cid)


def make_pipeline_ctx(cid: int, cntl: Controller) -> NsheadCallCtx:
    return NsheadCallCtx(cid, _complete, "ubrpc")


class UbrpcAdaptor(NsheadPbServiceAdaptor):
    """Server half: unwrap the mcpack envelope, dispatch by
    service_name.method, wrap the pb reply (or the error) back."""

    def parse_nshead_meta(self, server, request, controller, meta) -> None:
        try:
            envelope = mcpack_decode(request.body.to_bytes())
        except Exception as e:
            controller.set_failed(errors.EREQUEST,
                                  f"request is not mcpack: {e}")
            return
        content = envelope.get("content")
        if not isinstance(content, list) or not content \
                or not isinstance(content[0], dict):
            controller.set_failed(errors.EREQUEST,
                                  "fail to find request.content")
            return
        item = content[0]
        # record the envelope identity FIRST: failure responses must still
        # echo the caller's correlation id
        if isinstance(item.get("id"), int):
            meta.correlation_id = item["id"]
        meta.log_id = request.head.log_id
        service_name = item.get("service_name")
        method_name = item.get("method")
        if not isinstance(service_name, str) or \
                not isinstance(method_name, str) or \
                not service_name or not method_name:
            controller.set_failed(
                errors.EREQUEST, "missing content[0].service_name/method")
            return
        if "params" not in item:
            controller.set_failed(errors.EREQUEST,
                                  "fail to find content[0].params")
            return
        params = item["params"]
        if not isinstance(params, dict) or not params:
            controller.set_failed(errors.EREQUEST,
                                  "content[0].params must be a non-empty "
                                  "object")
            return
        if len(params) == 1:
            (only,) = params.values()
            if isinstance(only, dict):
                params = only
        controller._ubrpc_params = params
        meta.full_method_name = f"{service_name}.{method_name}"

    def parse_request_from_iobuf(self, meta, request, controller,
                                 pb_req) -> None:
        try:
            dict_to_pb(getattr(controller, "_ubrpc_params", {}), pb_req)
        except Exception as e:
            controller.set_failed(errors.EREQUEST,
                                  f"fail to map params: {e}")

    def serialize_response_to_iobuf(self, meta, controller, pb_res,
                                    response) -> None:
        item: dict = {"id": meta.correlation_id}
        if controller.failed() or pb_res is None:
            item["error"] = {"code": controller.error_code_
                             or errors.EINTERNAL,
                             "message": controller.error_text_ or "failed"}
        else:
            idl_result = getattr(controller, "idl_result", None)
            if idl_result is not None:
                item["result"] = idl_result
            item["result_params"] = {"res": pb_to_dict(pb_res)}
        response.body.append(mcpack_encode({"content": [item]}))


def _never_parse(source, socket, read_eof, arg):
    return ParseResult.try_others()


UBRPC_MCPACK2 = Protocol(
    name="ubrpc_mcpack2",
    parse=_never_parse,
    serialize_request=serialize_request,
    pack_request=pack_request,
    supported_connection_type=CONNECTION_TYPE_POOLED | CONNECTION_TYPE_SHORT,
    support_server=False,
    pipelined=True,
    make_pipeline_ctx=make_pipeline_ctx,
)

def pack_request_compack(payload: IOBuf, cid: int, cntl: Controller,
                         method_full_name: str) -> IOBuf:
    """FORMAT_COMPACK wire (ubrpc2pb_protocol.cpp:530): same envelope,
    primitive arrays serialized as isoarrays."""
    return pack_request(payload, cid, cntl, method_full_name,
                        _compack=True)


UBRPC_COMPACK = Protocol(
    name="ubrpc_compack",
    parse=_never_parse,
    serialize_request=serialize_request,
    pack_request=pack_request_compack,
    supported_connection_type=CONNECTION_TYPE_POOLED | CONNECTION_TYPE_SHORT,
    support_server=False,
    pipelined=True,
    make_pipeline_ctx=make_pipeline_ctx,
)


if find_protocol("ubrpc_mcpack2") is None:
    register_protocol(UBRPC_MCPACK2)
if find_protocol("ubrpc_compack") is None:
    register_protocol(UBRPC_COMPACK)
