"""Thrift framed-binary protocol (client + server).

Reference: src/brpc/policy/thrift_protocol.cpp + thrift_message.{h,cpp},
thrift_service.{h,cpp} (built under WITH_THRIFT).  Implements the Apache
Thrift framed transport (4-byte length prefix) with TBinaryProtocol
messages, no thrift library required: structs are described by field specs

    spec = {1: ("name", TType.STRING), 2: ("id", TType.I32)}

and travel as plain dicts.  Server side mirrors ThriftService: register a
method handler taking/returning dicts; client side calls through the normal
Channel machinery with protocol="thrift".
"""
from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import Protocol, ParseResult, register_protocol

VERSION_1 = 0x80010000

MSG_CALL = 1
MSG_REPLY = 2
MSG_EXCEPTION = 3
MSG_ONEWAY = 4


class TType:
    STOP = 0
    BOOL = 2
    BYTE = 3
    DOUBLE = 4
    I16 = 6
    I32 = 8
    I64 = 10
    STRING = 11
    STRUCT = 12
    MAP = 13
    SET = 14
    LIST = 15


# ---- TBinaryProtocol codec -------------------------------------------

class _Writer:
    def __init__(self):
        self.parts: List[bytes] = []

    def write(self, b: bytes) -> None:
        self.parts.append(b)

    def i8(self, v): self.write(struct.pack(">b", v))
    def i16(self, v): self.write(struct.pack(">h", v))
    def i32(self, v): self.write(struct.pack(">i", v))
    def u32(self, v): self.write(struct.pack(">I", v & 0xFFFFFFFF))
    def i64(self, v): self.write(struct.pack(">q", v))
    def double(self, v): self.write(struct.pack(">d", v))

    def string(self, v):
        if isinstance(v, str):
            v = v.encode()
        self.i32(len(v))
        self.write(v)

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


class _Reader:
    def __init__(self, data: bytes, pos: int = 0):
        self.data = data
        self.pos = pos

    def take(self, n: int) -> bytes:
        b = self.data[self.pos:self.pos + n]
        if len(b) < n:
            raise ValueError("truncated thrift data")
        self.pos += n
        return b

    def i8(self): return struct.unpack(">b", self.take(1))[0]
    def i16(self): return struct.unpack(">h", self.take(2))[0]
    def i32(self): return struct.unpack(">i", self.take(4))[0]
    def u32(self): return struct.unpack(">I", self.take(4))[0]
    def i64(self): return struct.unpack(">q", self.take(8))[0]
    def double(self): return struct.unpack(">d", self.take(8))[0]
    def string(self): return self.take(self.i32())


def _write_value(w: _Writer, ttype: int, value: Any, spec=None) -> None:
    if ttype == TType.BOOL:
        w.i8(1 if value else 0)
    elif ttype == TType.BYTE:
        w.i8(value)
    elif ttype == TType.I16:
        w.i16(value)
    elif ttype == TType.I32:
        w.i32(value)
    elif ttype == TType.I64:
        w.i64(value)
    elif ttype == TType.DOUBLE:
        w.double(value)
    elif ttype == TType.STRING:
        w.string(value)
    elif ttype == TType.STRUCT:
        write_struct(w, value, spec or {})
    elif ttype == TType.LIST or ttype == TType.SET:
        elem_type, elem_spec = spec
        w.i8(elem_type)
        w.i32(len(value))
        for item in value:
            _write_value(w, elem_type, item, elem_spec)
    elif ttype == TType.MAP:
        (ktype, kspec), (vtype, vspec) = spec
        w.i8(ktype); w.i8(vtype)
        w.i32(len(value))
        for k, v in value.items():
            _write_value(w, ktype, k, kspec)
            _write_value(w, vtype, v, vspec)
    else:
        raise TypeError(f"unsupported thrift type {ttype}")


def _read_value(r: _Reader, ttype: int, spec=None) -> Any:
    if ttype == TType.BOOL:
        return bool(r.i8())
    if ttype == TType.BYTE:
        return r.i8()
    if ttype == TType.I16:
        return r.i16()
    if ttype == TType.I32:
        return r.i32()
    if ttype == TType.I64:
        return r.i64()
    if ttype == TType.DOUBLE:
        return r.double()
    if ttype == TType.STRING:
        return r.string()
    if ttype == TType.STRUCT:
        return read_struct(r, spec or {})
    if ttype in (TType.LIST, TType.SET):
        elem_type = r.i8()
        n = r.i32()
        elem_spec = spec[1] if spec else None
        return [_read_value(r, elem_type, elem_spec) for _ in range(n)]
    if ttype == TType.MAP:
        ktype = r.i8(); vtype = r.i8()
        n = r.i32()
        kspec = spec[0][1] if spec else None
        vspec = spec[1][1] if spec else None
        return {_read_value(r, ktype, kspec): _read_value(r, vtype, vspec)
                for _ in range(n)}
    raise TypeError(f"unsupported thrift type {ttype}")


def write_struct(w: _Writer, values: Dict[str, Any],
                 spec: Dict[int, Tuple]) -> None:
    """spec: field_id -> (name, ttype[, sub_spec])."""
    for fid, field in spec.items():
        name, ttype = field[0], field[1]
        sub = field[2] if len(field) > 2 else None
        if name not in values or values[name] is None:
            continue
        w.i8(ttype)
        w.i16(fid)
        _write_value(w, ttype, values[name], sub)
    w.i8(TType.STOP)


def read_struct(r: _Reader, spec: Dict[int, Tuple]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    while True:
        ttype = r.i8()
        if ttype == TType.STOP:
            return out
        fid = r.i16()
        field = spec.get(fid)
        value = _read_value(r, ttype, field[2] if field and len(field) > 2
                            else None)
        if field is not None:
            out[field[0]] = value


def pack_message(name: str, msg_type: int, seqid: int,
                 payload: bytes) -> bytes:
    w = _Writer()
    w.u32(VERSION_1 | msg_type)
    w.string(name)
    w.i32(seqid)
    w.write(payload)
    body = w.getvalue()
    return struct.pack(">i", len(body)) + body


# ---- request/response objects ----------------------------------------

class ThriftMessage:
    """A call or reply: method name + struct dict + field spec."""

    def __init__(self, method: str = "", values: Optional[Dict] = None,
                 spec: Optional[Dict[int, Tuple]] = None,
                 response_spec: Optional[Dict[int, Tuple]] = None):
        self.method = method
        self.values = values or {}
        self.spec = spec or {}
        self.response_spec = response_spec or {}
        self.msg_type = MSG_CALL
        self.seqid = 0
        self.exception_text = ""


# ---- protocol callbacks ----------------------------------------------

def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    hdr = source.fetch(8)
    if hdr is None:
        return ParseResult.not_enough_data()
    frame_len = struct.unpack(">i", hdr[:4])[0]
    version = struct.unpack(">I", hdr[4:8])[0]
    if frame_len <= 0 or frame_len > (1 << 28) \
            or (version & 0xFFFF0000) != (VERSION_1 & 0xFFFF0000):
        return ParseResult.try_others()
    if len(source) < 4 + frame_len:
        return ParseResult.not_enough_data()
    source.pop_front(4)
    body = source.cut(frame_len).to_bytes()
    r = _Reader(body)
    ver = r.u32()
    msg = ThriftMessage()
    msg.msg_type = ver & 0xFF
    msg.method = r.string().decode()
    msg.seqid = r.i32()
    msg._raw_reader = r
    return ParseResult.ok(msg)


def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    if not isinstance(request, ThriftMessage):
        raise TypeError("thrift request must be a ThriftMessage")
    cntl._thrift_request = request
    w = _Writer()
    write_struct(w, request.values, request.spec)
    return IOBuf(w.getvalue())


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    req = cntl._thrift_request
    method = req.method or method_full_name.rpartition(".")[2]
    # thrift seqid is 31-bit; carry the low bits and correlate pipelined
    seqid = cid & 0x7FFFFFFF
    return IOBuf(pack_message(method, MSG_CALL, seqid,
                              payload.to_bytes()))


class _Ctx:
    __slots__ = ("cid", "response_spec")

    def __init__(self, cid, response_spec):
        self.cid = cid
        self.response_spec = response_spec


def _make_pipeline_ctx(cid: int, cntl: Controller):
    req = getattr(cntl, "_thrift_request", None)
    return _Ctx(cid, getattr(req, "response_spec", None) or {})


def process_response(msg: ThriftMessage, socket) -> None:
    from ..bthread import id as bthread_id
    # thrift replies carry a seqid: correlate by it (robust to reordering),
    # falling back to pipeline order for servers that zero the seqid
    with socket._pipeline_lock:
        ctx = None
        for i, c in enumerate(socket.pipelined_contexts):
            if (c.cid & 0x7FFFFFFF) == msg.seqid:
                ctx = socket.pipelined_contexts.pop(i)
                break
        if ctx is None and socket.pipelined_contexts:
            ctx = socket.pipelined_contexts.pop(0)
    if ctx is None:
        return
    rc, cntl = bthread_id.lock(ctx.cid)
    if rc != 0 or cntl is None:
        return
    cntl.remote_side = socket.remote_side
    if msg.msg_type == MSG_EXCEPTION:
        exc = read_struct(msg._raw_reader, {1: ("message", TType.STRING)})
        cntl.set_failed(errors.ERESPONSE,
                        (exc.get("message") or b"thrift exception").decode(
                            "utf-8", "replace"))
        cntl.finish_parsed_response(ctx.cid)
        return
    # standard thrift reply struct: field 0 = success
    reply = read_struct(msg._raw_reader,
                        {0: ("success", TType.STRUCT, ctx.response_spec)})
    out = ThriftMessage(msg.method, reply.get("success", {}),
                        ctx.response_spec)
    out.msg_type = msg.msg_type
    out.seqid = msg.seqid
    cntl.response = out
    cntl.finish_parsed_response(ctx.cid)


class ThriftService:
    """Server-side dispatcher (thrift_service.h NsheadService-style): one
    handler per method, dicts in/out."""

    def __init__(self):
        self._methods: Dict[str, Tuple[Callable, Dict, Dict]] = {}

    def add_method(self, name: str, fn: Callable[[Dict], Dict],
                   arg_spec: Dict[int, Tuple],
                   result_spec: Dict[int, Tuple]) -> None:
        self._methods[name] = (fn, arg_spec, result_spec)

    def handle(self, msg: ThriftMessage) -> bytes:
        entry = self._methods.get(msg.method)
        if entry is None:
            w = _Writer()
            write_struct(w, {"message": f"unknown method {msg.method}"},
                         {1: ("message", TType.STRING)})
            return pack_message(msg.method, MSG_EXCEPTION, msg.seqid,
                                w.getvalue())
        fn, arg_spec, result_spec = entry
        try:
            args = read_struct(msg._raw_reader, arg_spec)
            result = fn(args)
            w = _Writer()
            write_struct(w, {"success": result},
                         {0: ("success", TType.STRUCT, result_spec)})
            return pack_message(msg.method, MSG_REPLY, msg.seqid,
                                w.getvalue())
        except Exception as e:
            w = _Writer()
            write_struct(w, {"message": f"{type(e).__name__}: {e}"},
                         {1: ("message", TType.STRING)})
            return pack_message(msg.method, MSG_EXCEPTION, msg.seqid,
                                w.getvalue())


def process_request(msg: ThriftMessage, socket, server) -> None:
    svc = getattr(server, "thrift_service", None)
    if svc is None:
        w = _Writer()
        write_struct(w, {"message": "no ThriftService on this server"},
                     {1: ("message", TType.STRING)})
        socket.write(IOBuf(pack_message(msg.method, MSG_EXCEPTION,
                                        msg.seqid, w.getvalue())))
        return
    socket.write(IOBuf(svc.handle(msg)))


PROTOCOL = Protocol(
    name="thrift",
    parse=parse,
    process_request=process_request,
    process_response=process_response,
    serialize_request=serialize_request,
    pack_request=pack_request,
    pipelined=True,
    make_pipeline_ctx=_make_pipeline_ctx,
)


def _register() -> None:
    from ..rpc.protocol import find_protocol
    if find_protocol("thrift") is None:
        register_protocol(PROTOCOL)


_register()
