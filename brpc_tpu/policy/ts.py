"""MPEG-TS muxer — wraps H.264/AAC frames into transport-stream packets.

Reference: src/brpc/ts.{h,cpp} (TsMuxer/TsChannelGroup, ~1.2 k LoC) —
bRPC uses it to serve HLS out of RTMP streams.  This is a compact
TPU-build equivalent with the same capability: PAT/PMT program tables,
PES packetization with PTS/DTS, PCR on the video PID, per-PID continuity
counters, 188-byte fixed packets.  Output is standard ISO 13818-1 TS
playable by any demuxer.
"""
from __future__ import annotations

import struct
from typing import Optional

from ..butil.iobuf import IOBuf

TS_PACKET_SIZE = 188
PID_PAT = 0x0000
PID_PMT = 0x1000
PID_VIDEO = 0x0100
PID_AUDIO = 0x0101

STREAM_TYPE_H264 = 0x1B      # AVC video
STREAM_TYPE_AAC = 0x0F       # AAC ADTS audio

_SID_VIDEO = 0xE0            # PES stream ids
_SID_AUDIO = 0xC0


def crc32_mpeg(data: bytes) -> int:
    """CRC-32/MPEG-2 as used by PSI sections (ts.cpp crc table)."""
    crc = 0xFFFFFFFF
    for byte in data:
        crc ^= byte << 24
        for _ in range(8):
            crc = ((crc << 1) ^ 0x04C11DB7 if crc & 0x80000000
                   else crc << 1) & 0xFFFFFFFF
    return crc


class TsMuxer:
    """Feed encoded frames, collect TS packets from .buf (an IOBuf)."""

    def __init__(self, sink: Optional[IOBuf] = None,
                 has_video: bool = True, has_audio: bool = True,
                 psi_interval: int = 40):
        self.buf = sink if sink is not None else IOBuf()
        self.has_video = has_video
        self.has_audio = has_audio
        self._cc = {}                      # pid -> continuity counter
        self._frames_since_psi = None      # force PSI before first frame
        self._psi_interval = psi_interval

    # ---- PSI -----------------------------------------------------------

    def _psi_packet(self, pid: int, section: bytes) -> bytes:
        # pointer_field 0 + section, padded with 0xFF
        payload = b"\x00" + section
        head = struct.pack(">BHB", 0x47,
                           0x4000 | pid,               # PUSI set
                           0x10 | self._bump_cc(pid))  # payload only
        pkt = head + payload
        return pkt + b"\xff" * (TS_PACKET_SIZE - len(pkt))

    def _section(self, table_id: int, table_id_ext: int,
                 body: bytes) -> bytes:
        length = len(body) + 9             # after section_length field
        sec = struct.pack(">BHHBBB", table_id, 0xB000 | length,
                          table_id_ext, 0xC1, 0, 0) + body
        return sec + struct.pack(">I", crc32_mpeg(sec))

    def write_pat_pmt(self) -> None:
        pat_body = struct.pack(">HH", 1, 0xE000 | PID_PMT)
        self.buf.append(self._psi_packet(PID_PAT,
                                         self._section(0x00, 1, pat_body)))
        pcr_pid = PID_VIDEO if self.has_video else PID_AUDIO
        es = b""
        if self.has_video:
            es += struct.pack(">BHH", STREAM_TYPE_H264,
                              0xE000 | PID_VIDEO, 0xF000)
        if self.has_audio:
            es += struct.pack(">BHH", STREAM_TYPE_AAC,
                              0xE000 | PID_AUDIO, 0xF000)
        pmt_body = struct.pack(">HH", 0xE000 | pcr_pid, 0xF000) + es
        self.buf.append(self._psi_packet(PID_PMT,
                                         self._section(0x02, 1, pmt_body)))

    # ---- PES -----------------------------------------------------------

    @staticmethod
    def _pts_field(marker: int, t: int) -> bytes:
        t &= (1 << 33) - 1
        return bytes([
            (marker << 4) | (((t >> 30) & 0x7) << 1) | 1,
            (t >> 22) & 0xFF,
            (((t >> 15) & 0x7F) << 1) | 1,
            (t >> 7) & 0xFF,
            ((t & 0x7F) << 1) | 1,
        ])

    def _pes(self, sid: int, pts: int, dts: Optional[int],
             payload: bytes) -> bytes:
        flags = 0x80 if dts is None else 0xC0
        opt = self._pts_field(2 if dts is None else 3, pts)
        if dts is not None:
            opt += self._pts_field(1, dts)
        hdr_len = len(opt)
        total = 3 + hdr_len + len(payload)
        pes_len = total if total <= 0xFFFF and sid != _SID_VIDEO else 0
        return (b"\x00\x00\x01" + bytes([sid])
                + struct.pack(">H", pes_len)
                + bytes([0x80, flags, hdr_len]) + opt + payload)

    def _bump_cc(self, pid: int) -> int:
        cc = self._cc.get(pid, 0)
        self._cc[pid] = (cc + 1) & 0xF
        return cc

    def _write_pes_packets(self, pid: int, pes: bytes,
                           with_pcr: bool, pcr: int) -> None:
        off = 0
        first = True
        n = len(pes)
        while off < n or first:
            head = struct.pack(">BH", 0x47,
                               (0x4000 if first else 0) | pid)
            remaining = n - off
            adaptation = b""
            if first and with_pcr:
                base = pcr & ((1 << 33) - 1)
                # 33-bit base | 6 reserved bits (all 1) | 9-bit extension=0
                pcr_bytes = ((base << 15) | (0x3F << 9)).to_bytes(6, "big")
                adaptation = bytes([7, 0x10]) + pcr_bytes
            space = TS_PACKET_SIZE - 4 - len(adaptation)
            if remaining < space:
                # stuff via adaptation field to fill the packet
                stuff = space - remaining
                if not adaptation:
                    if stuff == 1:
                        adaptation = bytes([0])
                    else:
                        adaptation = bytes([stuff - 1, 0x00]) \
                            + b"\xff" * (stuff - 2)
                else:
                    adaptation = bytes([adaptation[0] + stuff]) \
                        + adaptation[1:] + b"\xff" * stuff
                space = remaining
            afc = 0x30 if adaptation else 0x10
            pkt = head + bytes([afc | self._bump_cc(pid)]) + adaptation \
                + pes[off:off + space]
            assert len(pkt) == TS_PACKET_SIZE, len(pkt)
            self.buf.append(pkt)
            off += space
            first = False

    # ---- public feed API (ts.h TsMuxer::Encode) ------------------------

    def _maybe_psi(self) -> None:
        if (self._frames_since_psi is None
                or self._frames_since_psi >= self._psi_interval):
            self.write_pat_pmt()
            self._frames_since_psi = 0
        self._frames_since_psi += 1

    def write_video(self, pts_90k: int, annexb: bytes,
                    dts_90k: Optional[int] = None) -> None:
        """H.264 access unit in Annex-B byte-stream form (with start
        codes); an AUD is prepended, matching the reference muxer."""
        self._maybe_psi()
        aud = b"\x00\x00\x00\x01\x09\xf0"
        pes = self._pes(_SID_VIDEO, pts_90k, dts_90k, aud + annexb)
        self._write_pes_packets(PID_VIDEO, pes, True, pts_90k)

    # audio PES_packet_length must be exact (only video may use 0, ISO
    # 13818-1 §2.4.3.7) — split oversized batches into multiple PES
    _MAX_AUDIO_PES_PAYLOAD = 0xFFFF - 8

    def write_audio(self, pts_90k: int, adts: bytes) -> None:
        """AAC frame(s) already wrapped in ADTS headers."""
        self._maybe_psi()
        for off in range(0, len(adts), self._MAX_AUDIO_PES_PAYLOAD):
            part = adts[off:off + self._MAX_AUDIO_PES_PAYLOAD]
            pes = self._pes(_SID_AUDIO, pts_90k, None, part)
            self._write_pes_packets(PID_AUDIO, pes,
                                    not self.has_video, pts_90k)
