"""FLV container reader/writer.

Reference: src/brpc/rtmp.h FlvWriter/FlvReader (rtmp.h:1050-1130) and the
FLV tag handling inside src/brpc/policy/rtmp_protocol.cpp.  FLV frames
the exact same audio/video/script payloads RTMP carries, so the two
modules share message-type constants; tags round-trip losslessly through
(type, timestamp, payload) triples.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..butil.misc import p24 as _p24, u24 as _u24
from . import amf

FLV_TAG_AUDIO = 8
FLV_TAG_VIDEO = 9
FLV_TAG_SCRIPT_DATA = 18

_HEADER = b"FLV\x01"
_HAS_AUDIO = 0x04
_HAS_VIDEO = 0x01


class FlvWriter:
    """Serialize (type, timestamp, payload) tags into an FLV byte stream.
    Writes into an IOBuf (or any object with .append(bytes))."""

    def __init__(self, sink: Optional[IOBuf] = None, has_audio: bool = True,
                 has_video: bool = True):
        self.buf = sink if sink is not None else IOBuf()
        flags = (_HAS_AUDIO if has_audio else 0) | \
            (_HAS_VIDEO if has_video else 0)
        self.buf.append(_HEADER + bytes([flags]) + struct.pack(">I", 9))
        self.buf.append(struct.pack(">I", 0))       # PreviousTagSize0

    def write_tag(self, tag_type: int, timestamp: int,
                  payload: bytes) -> None:
        ts = timestamp & 0xFFFFFFFF
        head = bytes([tag_type]) + _p24(len(payload)) \
            + _p24(ts & 0xFFFFFF) + bytes([(ts >> 24) & 0xFF]) \
            + b"\x00\x00\x00"                       # stream id, always 0
        self.buf.append(head + payload)
        self.buf.append(struct.pack(">I", 11 + len(payload)))

    def write_audio(self, timestamp: int, data: bytes) -> None:
        self.write_tag(FLV_TAG_AUDIO, timestamp, data)

    def write_video(self, timestamp: int, data: bytes) -> None:
        self.write_tag(FLV_TAG_VIDEO, timestamp, data)

    def write_meta_data(self, meta: Dict[str, Any],
                        name: str = "onMetaData",
                        timestamp: int = 0) -> None:
        self.write_tag(FLV_TAG_SCRIPT_DATA, timestamp,
                       amf.encode(name, amf.EcmaArray(meta)))


class FlvReader:
    """Incremental FLV parser: feed bytes, iterate (type, ts, payload)."""

    def __init__(self, data: bytes = b""):
        self._buf = bytearray(data)
        self._header_done = False
        self.has_audio = False
        self.has_video = False

    def feed(self, data: bytes) -> None:
        self._buf += data

    def read_tag(self) -> Optional[Tuple[int, int, bytes]]:
        b = self._buf
        if not self._header_done:
            if len(b) < 13:
                return None
            if bytes(b[:3]) != b"FLV":
                raise ValueError("not an FLV stream")
            data_off = struct.unpack_from(">I", b, 5)[0]
            if len(b) < data_off + 4:       # extended header not yet here
                return None
            flags = b[4]
            self.has_audio = bool(flags & _HAS_AUDIO)
            self.has_video = bool(flags & _HAS_VIDEO)
            del b[:data_off + 4]                    # header + PrevTagSize0
            self._header_done = True
        if len(b) < 11:
            return None
        size = _u24(b, 1)
        if len(b) < 11 + size + 4:
            return None
        tag_type = b[0]
        ts = _u24(b, 4) | (b[7] << 24)
        payload = bytes(b[11:11 + size])
        del b[:11 + size + 4]
        return tag_type, ts, payload

    def __iter__(self) -> Iterator[Tuple[int, int, bytes]]:
        while True:
            tag = self.read_tag()
            if tag is None:
                return
            yield tag

    def read_meta_data(self, payload: bytes) -> Tuple[str, Dict[str, Any]]:
        vals = amf.decode_all(payload)
        name = vals[0] if vals and isinstance(vals[0], str) else ""
        meta = next((v for v in vals[1:] if isinstance(v, dict)), {})
        return name, dict(meta)
