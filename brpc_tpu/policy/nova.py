"""nova-pbrpc: nshead-framed protobuf protocol (method index in the head).

Reference behavior: src/brpc/policy/nova_pbrpc_protocol.cpp — requests are
an nshead whose `reserved` field carries the method index and whose body is
the serialized pb request; responses are an nshead + serialized pb
response.  No correlation id on the wire → pooled/short connections only
(PackNovaRequest rejects CONNECTION_TYPE_SINGLE and stashes the id on the
socket; here the id rides the per-call pipeline context).  The server side
is an NsheadPbServiceAdaptor (NovaServiceAdaptor).
"""
from __future__ import annotations


from ..butil.iobuf import IOBuf
from ..bthread import id as bthread_id
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import (CONNECTION_TYPE_POOLED, CONNECTION_TYPE_SHORT,
                            Protocol, ParseResult, register_protocol,
                            find_protocol)
from .nshead import (NsheadCallCtx, NsheadHead, NsheadMessage,
                     NsheadPbServiceAdaptor)
from .legacy_pbrpc import _resp_meta_shim, _serialize_pb


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    head = NsheadHead()
    head.log_id = cntl.log_id
    head.reserved = getattr(cntl, "method_index", 0) or 0
    head.body_len = len(payload)
    out = IOBuf()
    out.append(head.pack())
    out.append(payload)
    return out


def _complete(msg: NsheadMessage, socket, ctx: NsheadCallCtx) -> None:
    rc, cntl = bthread_id.lock(ctx.cid)
    if rc != 0 or cntl is None:
        return
    cntl.remote_side = socket.remote_side
    cntl.handle_response(ctx.cid, _resp_meta_shim(0, "", 0), msg.body)


def make_pipeline_ctx(cid: int, cntl: Controller) -> NsheadCallCtx:
    return NsheadCallCtx(cid, _complete, "nova_pbrpc")


class NovaServiceAdaptor(NsheadPbServiceAdaptor):
    """Dispatch nshead.reserved as an index into `service_name`'s methods
    (name-sorted, the service's stable index space)."""

    def __init__(self, service_name: str):
        self.target_service = service_name

    def parse_nshead_meta(self, server, request, controller, meta) -> None:
        svc = server._services.get(self.target_service)
        if svc is None:
            controller.set_failed(errors.ENOSERVICE,
                                  f"no service {self.target_service}")
            return
        mds = list(svc.methods().values())
        idx = request.head.reserved
        if not (0 <= idx < len(mds)):
            controller.set_failed(errors.ENOMETHOD,
                                  f"bad method index {idx}")
            return
        meta.full_method_name = mds[idx].full_name
        meta.log_id = request.head.log_id

    def parse_request_from_iobuf(self, meta, request, controller,
                                 pb_req) -> None:
        try:
            pb_req.ParseFromString(request.body.to_bytes())
        except Exception as e:
            controller.set_failed(errors.EREQUEST,
                                  f"fail to parse request: {e}")

    def serialize_response_to_iobuf(self, meta, controller, pb_res,
                                    response) -> None:
        if not controller.failed() and pb_res is not None:
            response.body.append(pb_res.SerializeToString())


# parse never claims bytes: the shared `nshead` protocol cuts the frames
# and completes through the pipeline context installed above.
PROTOCOL = Protocol(
    name="nova_pbrpc",
    parse=lambda source, socket, read_eof, arg: ParseResult.try_others(),
    serialize_request=_serialize_pb,
    pack_request=pack_request,
    supported_connection_type=CONNECTION_TYPE_POOLED | CONNECTION_TYPE_SHORT,
    support_server=False,
    pipelined=True,
    make_pipeline_ctx=make_pipeline_ctx,
)


if find_protocol("nova_pbrpc") is None:
    register_protocol(PROTOCOL)
