"""Load balancers (reference: src/brpc/policy/*_load_balancer.cpp,
registered in global.cpp:141-150; interface load_balancer.h).

All nine reference strategies: round_robin, weighted_round_robin,
randomized, weighted_randomized, consistent hashing (murmur/md5/ketama),
locality-aware (LALB), dynpart.  Server lists live in DoublyBufferedData so
the selection hot path never contends with membership changes, and
``feedback`` closes the loop for LALB and the circuit breaker.
"""
from __future__ import annotations

import bisect
import hashlib
import weakref
from typing import Dict, List, Optional, Tuple

from ..butil import debug_sync as _dbg
from ..butil.doubly_buffered import DoublyBufferedData
from ..butil.endpoint import EndPoint
from ..butil.misc import fast_rand_less_than


class ServerEntry:
    __slots__ = ("endpoint", "weight", "tag")

    def __init__(self, endpoint: EndPoint, weight: int = 100, tag: str = ""):
        self.endpoint = endpoint
        self.weight = weight
        self.tag = tag


class LoadBalancer:
    """Interface (load_balancer.h): membership + selection + feedback."""

    name = "base"

    def add_server(self, ep: EndPoint, weight: int = 100, tag: str = "") -> bool:
        raise NotImplementedError

    def remove_server(self, ep: EndPoint) -> bool:
        raise NotImplementedError

    def reset_servers(self, entries: List[ServerEntry]) -> None:
        raise NotImplementedError

    def select_server(self, cntl=None) -> Optional[EndPoint]:
        raise NotImplementedError

    def feedback(self, ep: EndPoint, error_code: int, latency_us: int) -> None:
        pass

    def server_count(self) -> int:
        raise NotImplementedError

    def servers(self) -> List[ServerEntry]:
        """Membership snapshot (screens/tools — e.g. the collective
        fan-out screen resolving a single-server partition to its
        ici:// device)."""
        return []


# Every live LB, weakly held: the lame-duck registry uses this to pull a
# draining endpoint (GOODBYE) from ALL balancers at once — proactive
# removal, not per-channel discovery (rpc/lameduck.py).
_live_lbs: "weakref.WeakSet" = weakref.WeakSet()


def live_load_balancers() -> List["LoadBalancer"]:
    return list(_live_lbs)


class _ListLB(LoadBalancer):
    """Shared base: DoublyBufferedData<list[ServerEntry]>."""

    # fablint guarded-state contract (selection runs on every RPC
    # thread; exclusions mutate from breaker/lame-duck callbacks)
    _GUARDED_BY = {"_excluded": "_excl_lock"}

    def __init__(self):
        self._dbd: DoublyBufferedData[List[ServerEntry]] = DoublyBufferedData(list)
        self._excluded: Dict[EndPoint, float] = {}   # circuit-broken until ts
        self._excl_lock = _dbg.make_lock("_ListLB._excl_lock")
        _live_lbs.add(self)

    def add_server(self, ep, weight=100, tag="") -> bool:
        def doit(lst):
            if any(e.endpoint == ep for e in lst):
                return False
            lst.append(ServerEntry(ep, weight, tag))
            return True
        return self._dbd.modify(doit)

    def remove_server(self, ep) -> bool:
        def doit(lst):
            for i, e in enumerate(lst):
                if e.endpoint == ep:
                    lst.pop(i)
                    return True
            return False
        return self._dbd.modify(doit)

    def reset_servers(self, entries) -> None:
        def doit(lst):
            lst.clear()
            lst.extend(ServerEntry(e.endpoint, e.weight, e.tag)
                       for e in entries)
        self._dbd.modify(doit)

    def server_count(self) -> int:
        with self._dbd.read() as lst:
            return len(lst)

    def servers(self) -> List[ServerEntry]:
        with self._dbd.read() as lst:
            return list(lst)

    def exclude(self, ep: EndPoint, until_ts: float) -> None:
        with self._excl_lock:
            self._excluded[ep] = until_ts

    def _usable(self, lst, cntl) -> List[ServerEntry]:
        import time
        now = time.monotonic()
        with self._excl_lock:
            excl = {ep for ep, ts in self._excluded.items() if ts > now}
        per_call = getattr(cntl, "_excluded_servers", None) if cntl else None
        out = [e for e in lst if e.endpoint not in excl
               and (per_call is None or e.endpoint not in per_call)]
        # cluster-recover guard: if everything is excluded, serve anyway
        return out if out else list(lst)


class RoundRobinLB(_ListLB):
    name = "rr"
    _GUARDED_BY = {"_index": "_ilock"}

    def __init__(self):
        super().__init__()
        self._index = 0
        self._ilock = _dbg.make_lock("RoundRobinLB._ilock")

    def select_server(self, cntl=None):
        with self._dbd.read() as lst:
            usable = self._usable(lst, cntl)
            if not usable:
                return None
            with self._ilock:
                self._index = (self._index + 1) % len(usable)
                return usable[self._index].endpoint


class WeightedRoundRobinLB(_ListLB):
    name = "wrr"
    _GUARDED_BY = {"_current": "_lock"}

    def __init__(self):
        super().__init__()
        self._lock = _dbg.make_lock("WeightedRoundRobinLB._lock")
        self._current: Dict[EndPoint, int] = {}

    def select_server(self, cntl=None):
        """Smooth weighted RR (same distribution contract as
        weighted_round_robin_load_balancer.cpp)."""
        with self._dbd.read() as lst:
            usable = self._usable(lst, cntl)
            if not usable:
                return None
            with self._lock:
                total = 0
                best = None
                for e in usable:
                    cur = self._current.get(e.endpoint, 0) + e.weight
                    self._current[e.endpoint] = cur
                    total += e.weight
                    if best is None or cur > self._current[best.endpoint]:
                        best = e
                self._current[best.endpoint] -= total
                return best.endpoint


class RandomizedLB(_ListLB):
    name = "random"

    def select_server(self, cntl=None):
        with self._dbd.read() as lst:
            usable = self._usable(lst, cntl)
            if not usable:
                return None
            return usable[fast_rand_less_than(len(usable))].endpoint


class WeightedRandomizedLB(_ListLB):
    name = "wr"

    def select_server(self, cntl=None):
        with self._dbd.read() as lst:
            usable = self._usable(lst, cntl)
            if not usable:
                return None
            total = sum(e.weight for e in usable)
            r = fast_rand_less_than(max(total, 1))
            acc = 0
            for e in usable:
                acc += e.weight
                if r < acc:
                    return e.endpoint
            return usable[-1].endpoint


def _murmur32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86_32 (the reference's murmurhash3 third_party lib)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed
    rounded = len(data) & ~3
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3: k ^= tail[2] << 16
    if len(tail) >= 2: k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def _md5_32(data: bytes) -> int:
    return int.from_bytes(hashlib.md5(data).digest()[:4], "little")


class ConsistentHashingLB(_ListLB):
    """Ketama-style ring with virtual nodes
    (consistent_hashing_load_balancer.cpp + hasher.cpp).  ``kind`` selects
    the hash: murmur | md5 | ketama (md5-based multi-point)."""

    _GUARDED_BY = {"_ring": "_ring_lock"}

    def __init__(self, kind: str = "murmur", vnodes: int = 64):
        super().__init__()
        self.kind = kind
        self.name = "c_" + kind + "hash"
        self._vnodes = vnodes
        self._ring_lock = _dbg.make_lock("ConsistentHashingLB._ring_lock")
        self._ring: List[Tuple[int, EndPoint]] = []

    def _hash(self, data: bytes) -> int:
        if self.kind == "murmur":
            return _murmur32(data)
        return _md5_32(data)

    def _rebuild(self) -> None:
        with self._dbd.read() as lst:
            servers = list(lst)
        ring = []
        for e in servers:
            base = str(e.endpoint).encode()
            if self.kind == "ketama":
                # 4 points per md5 digest, ketama style
                for i in range((self._vnodes + 3) // 4):
                    d = hashlib.md5(base + b"-%d" % i).digest()
                    for j in range(4):
                        ring.append((int.from_bytes(d[j*4:j*4+4], "little"),
                                     e.endpoint))
            else:
                for i in range(self._vnodes):
                    ring.append((self._hash(base + b"-%d" % i), e.endpoint))
        ring.sort()
        with self._ring_lock:
            self._ring = ring

    def add_server(self, ep, weight=100, tag="") -> bool:
        ok = super().add_server(ep, weight, tag)
        if ok:
            self._rebuild()
        return ok

    def remove_server(self, ep) -> bool:
        ok = super().remove_server(ep)
        if ok:
            self._rebuild()
        return ok

    def reset_servers(self, entries) -> None:
        super().reset_servers(entries)
        self._rebuild()

    def select_server(self, cntl=None):
        code = getattr(cntl, "request_code", None) if cntl is not None else None
        if code is None:
            code = fast_rand_less_than(1 << 32)
        h = self._hash(str(code).encode()) if not isinstance(code, bytes) \
            else self._hash(code)
        with self._ring_lock:
            ring = self._ring
            if not ring:
                return None
            i = bisect.bisect_left(ring, (h,))
            return ring[i % len(ring)][1]


class _LaWeight:
    """Per-server divided-weight state (the reference's Weight class,
    locality_aware_load_balancer.h:80-120 / docs/cn/lalb.md).

    * ``base weight`` = WEIGHT_SCALE / avg_latency over a sliding window
      of the last RECV_QUEUE_SIZE samples — weight is proportional to
      the server's observed QPS capacity.
    * **error punishment**: a failed call contributes a PUNISHED sample
      (``avg_latency × PUNISH_RATIO``) instead of its real latency, so a
      flapping server's window fills with inflated latencies and its
      weight collapses multiplicatively; successful calls wash the
      punishment out of the window — the recovery half.
    * **in-flight extrapolation** (the "divided weight"): at selection
      time, a server whose oldest in-flight requests have ALREADY waited
      longer than its average latency is predicted slower than its
      window says — its weight is divided by elapsed/avg on the spot.
      This is what reroutes traffic within ONE request time of a server
      freezing, long before any timeout feedback arrives.
    """

    __slots__ = ("samples", "latency_sum", "begin_time_sum",
                 "begin_time_count")

    QUEUE_SIZE = 128            # reference RECV_QUEUE_SIZE
    PUNISH_RATIO = 4.0          # error sample = avg * ratio

    def __init__(self):
        import collections
        self.samples = collections.deque(maxlen=self.QUEUE_SIZE)
        self.latency_sum = 0.0
        self.begin_time_sum = 0.0    # sum of in-flight begin times (us)
        self.begin_time_count = 0

    def avg_latency(self) -> float:
        return (self.latency_sum / len(self.samples)
                if self.samples else 0.0)

    def push(self, latency_us: float) -> None:
        if len(self.samples) == self.samples.maxlen:
            self.latency_sum -= self.samples[0]
        self.samples.append(latency_us)
        self.latency_sum += latency_us


class LocalityAwareLB(_ListLB):
    """LALB — the reference's divided-weight algorithm
    (locality_aware_load_balancer.{h,cpp}, docs/cn/lalb.md): weight ∝
    WEIGHT_SCALE/avg_latency over a sample window, errors punished as
    inflated-latency samples (recovery = real samples washing them out),
    and in-flight latency extrapolation dividing a stuck server's weight
    at selection time.  Selection is weighted-random over the effective
    weights — the reference's weight tree is an O(log n) index over
    exactly this distribution; O(n) keeps the same distribution
    (acceptable per the rewrite brief) and MIN_WEIGHT keeps every
    usable server reachable (starvation-freedom: a punished server must
    keep receiving probe traffic or it could never recover)."""

    name = "la"
    # the per-server weight table AND each _LaWeight's interior state
    # (samples window, in-flight begin sums) mutate only under _w_lock
    _GUARDED_BY = {"_servers": "_w_lock"}
    WEIGHT_SCALE = 1e7
    INITIAL_WEIGHT = 1000.0     # until the first sample lands
    MIN_WEIGHT = 1.0

    def __init__(self):
        super().__init__()
        self._w_lock = _dbg.make_lock("LocalityAwareLB._w_lock")
        self._servers: Dict[EndPoint, _LaWeight] = {}

    # fablint: lock-held(_w_lock)
    def _weight_for(self, ep: EndPoint) -> _LaWeight:
        w = self._servers.get(ep)
        if w is None:
            w = self._servers[ep] = _LaWeight()
        return w

    def _effective_weight(self, w: _LaWeight, now_us: float) -> float:
        avg = w.avg_latency()
        if avg <= 0:
            return self.INITIAL_WEIGHT
        base = self.WEIGHT_SCALE / avg
        # in-flight extrapolation: requests outstanding longer than the
        # average latency predict a slower server than the window shows
        if w.begin_time_count > 0:
            avg_begin = w.begin_time_sum / w.begin_time_count
            elapsed = now_us - avg_begin
            if elapsed > avg:
                base = base * avg / elapsed         # the divided weight
        return max(base, self.MIN_WEIGHT)

    def select_server(self, cntl=None):
        import time as _time
        with self._dbd.read() as lst:
            usable = self._usable(lst, cntl)
        if not usable:
            return None
        now_us = _time.monotonic() * 1e6
        with self._w_lock:
            ws = [self._effective_weight(self._weight_for(e.endpoint),
                                         now_us) for e in usable]
            total = sum(ws)
            r = (fast_rand_less_than(1 << 30) / float(1 << 30)) * total
            acc = 0.0
            chosen = usable[-1].endpoint
            for e, w in zip(usable, ws):
                acc += w
                if r < acc:
                    chosen = e.endpoint
                    break
            # note the in-flight begin (reference Weight::AddInflight):
            # feedback() subtracts it back out
            cw = self._weight_for(chosen)
            cw.begin_time_sum += now_us
            cw.begin_time_count += 1
            return chosen

    def feedback(self, ep, error_code, latency_us) -> None:
        import time as _time
        now_us = _time.monotonic() * 1e6
        with self._w_lock:
            w = self._weight_for(ep)
            # retire one in-flight entry: remove this request's begin
            # time (≈ now - latency; the reference stores it exactly,
            # the approximation only skews extrapolation by queueing
            # delay).  Tolerates feedback without a matching select —
            # combo channels feed sub-call results directly.
            if w.begin_time_count > 0:
                w.begin_time_sum -= now_us - latency_us
                w.begin_time_count -= 1
                if w.begin_time_count == 0:
                    w.begin_time_sum = 0.0
            if error_code != 0:
                avg = w.avg_latency()
                punished = max(avg, float(latency_us), 1.0) \
                    * _LaWeight.PUNISH_RATIO
                w.push(punished)
            else:
                w.push(max(float(latency_us), 1.0))

    def cancel_inflight(self, ep) -> None:
        """Retire one in-flight entry WITHOUT a latency sample: a
        selection discarded before any request was issued (per-call
        exclusion retries).  The entry was just added, so subtracting
        the current time is exact to within the discard latency —
        without this, discarded draws accumulate phantom in-flight
        entries whose extrapolation pins the server near MIN_WEIGHT
        forever (a revived worker would never win traffic back)."""
        import time as _time
        now_us = _time.monotonic() * 1e6
        with self._w_lock:
            w = self._weight_for(ep)
            if w.begin_time_count > 0:
                w.begin_time_sum -= now_us
                w.begin_time_count -= 1
                if w.begin_time_count == 0:
                    w.begin_time_sum = 0.0

    def weight_of(self, ep) -> float:
        import time as _time
        with self._w_lock:
            return self._effective_weight(self._weight_for(ep),
                                          _time.monotonic() * 1e6)

    def describe(self) -> dict:
        """Per-server divided-weight snapshot (the serving router's
        /status block): effective weight, window average latency, and
        the in-flight count the extrapolation divides by."""
        import time as _time
        now_us = _time.monotonic() * 1e6
        out = {}
        with self._dbd.read() as lst:
            eps = [e.endpoint for e in lst]
        with self._w_lock:
            for ep in eps:
                w = self._weight_for(ep)
                out[str(ep)] = {
                    "weight": round(self._effective_weight(w, now_us), 1),
                    "avg_latency_us": round(w.avg_latency(), 1),
                    "inflight": w.begin_time_count,
                }
        return out


class DynPartLB(_ListLB):
    """dynpart (dynpart_load_balancer.cpp): selection proportional to each
    scheme's capacity; pairs with DynamicPartitionChannel."""

    name = "dynpart"

    def select_server(self, cntl=None):
        with self._dbd.read() as lst:
            usable = self._usable(lst, cntl)
            if not usable:
                return None
            total = sum(e.weight for e in usable)
            r = fast_rand_less_than(max(total, 1))
            acc = 0
            for e in usable:
                acc += e.weight
                if r < acc:
                    return e.endpoint
            return usable[-1].endpoint


_factories = {
    "rr": RoundRobinLB,
    "wrr": WeightedRoundRobinLB,
    "random": RandomizedLB,
    "wr": WeightedRandomizedLB,
    "c_murmurhash": lambda: ConsistentHashingLB("murmur"),
    "c_md5": lambda: ConsistentHashingLB("md5"),
    "c_ketama": lambda: ConsistentHashingLB("ketama"),
    "la": LocalityAwareLB,
    "dynpart": DynPartLB,
}


def create_load_balancer(name: str) -> LoadBalancer:
    try:
        return _factories[name]()
    except KeyError:
        raise ValueError(f"unknown load balancer {name!r}; "
                         f"have {sorted(_factories)}")


def list_load_balancers() -> List[str]:
    return sorted(_factories)
