"""HTTP/2 + gRPC protocol (client + server).

Reference: src/brpc/policy/http2_rpc_protocol.cpp + grpc.{h,cpp} +
details/hpack.cpp.  Self-contained implementation of the h2 framing layer
(RFC 7540: preface, SETTINGS/PING/WINDOW_UPDATE/HEADERS/DATA/RST/GOAWAY,
stream states) with HPACK (policy/hpack.py), carrying gRPC semantics
(RFC-style: 5-byte length-prefixed protobuf messages, ``:path`` =
/Service/Method, trailers with grpc-status/grpc-message).

Scope note: unary gRPC calls against our own client/server pair across all
transports; grpc streaming and interop against foreign stacks are untested
here (no grpc/h2 libraries in the image) — the frame and HPACK layers
follow the RFCs so foreign interop is a validation task, not a redesign.

Connection state (hpack tables, live streams, ids) hangs off the socket —
the per-connection context the reference keeps in H2Context.
"""
from __future__ import annotations

import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import Protocol, ParseResult, register_protocol
from . import hpack

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

FRAME_DATA = 0x0
FRAME_HEADERS = 0x1
FRAME_RST_STREAM = 0x3
FRAME_SETTINGS = 0x4
FRAME_PING = 0x6
FRAME_GOAWAY = 0x7
FRAME_WINDOW_UPDATE = 0x8
FRAME_CONTINUATION = 0x9

FLAG_END_STREAM = 0x1
FLAG_END_HEADERS = 0x4
FLAG_ACK = 0x1

GRPC_OK = 0
GRPC_UNKNOWN = 2
GRPC_UNIMPLEMENTED = 12
GRPC_INTERNAL = 13

_GRPC_TO_RPC = {GRPC_UNIMPLEMENTED: errors.ENOMETHOD,
                GRPC_INTERNAL: errors.EINTERNAL}


def frame(ftype: int, flags: int, stream_id: int, payload: bytes) -> bytes:
    return (struct.pack(">I", len(payload))[1:]
            + bytes([ftype, flags]) + struct.pack(">I", stream_id & 0x7FFFFFFF)
            + payload)


def grpc_message(pb_bytes: bytes) -> bytes:
    return b"\x00" + struct.pack(">I", len(pb_bytes)) + pb_bytes


def split_grpc_messages(data: bytes) -> List[bytes]:
    out = []
    pos = 0
    while pos + 5 <= len(data):
        _compressed = data[pos]
        n = struct.unpack(">I", data[pos + 1:pos + 5])[0]
        out.append(data[pos + 5:pos + 5 + n])
        pos += 5 + n
    return out


class _H2Stream:
    __slots__ = ("stream_id", "headers", "trailers", "data", "ended",
                 "headers_done")

    def __init__(self, stream_id: int):
        self.stream_id = stream_id
        self.headers: List[Tuple[bytes, bytes]] = []
        self.trailers: List[Tuple[bytes, bytes]] = []
        self.data = bytearray()
        self.ended = False
        self.headers_done = False

    def header(self, name: bytes, default: bytes = b"") -> bytes:
        for k, v in self.headers + self.trailers:
            if k == name:
                return v
        return default


class _H2Conn:
    """Per-socket connection context (the reference's H2Context)."""

    def __init__(self, is_server: bool):
        self.is_server = is_server
        self.preface_seen = not is_server
        self.preface_sent = False
        self.settings_sent = False
        self.enc = hpack.Encoder()
        self.dec = hpack.Decoder()
        self.streams: Dict[int, _H2Stream] = {}
        self.next_stream_id = 1          # client-initiated odd ids
        self.cid_by_stream: Dict[int, int] = {}
        self.lock = threading.Lock()


def _conn(socket, is_server: bool) -> _H2Conn:
    c = getattr(socket, "_h2_conn", None)
    if c is None:
        c = _H2Conn(is_server)
        socket._h2_conn = c
    return c


class CompletedCall:
    """A fully-received request or response stream."""

    __slots__ = ("stream", "is_request")

    def __init__(self, stream: _H2Stream, is_request: bool):
        self.stream = stream
        self.is_request = is_request


# ---- parse ------------------------------------------------------------

def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    """Consume every complete frame in order (HPACK state is sequential);
    returns the list of CompletedCalls that finished in this batch."""
    is_server = getattr(arg, "server", None) is not None
    head = source.fetch(min(len(source), len(PREFACE)))
    if head is None:
        return ParseResult.not_enough_data()
    conn = getattr(socket, "_h2_conn", None)
    if conn is None:
        if not is_server:
            return ParseResult.try_others()   # client conns init at pack time
        if len(head) < 4:
            if PREFACE.startswith(head):
                return ParseResult.not_enough_data()
            return ParseResult.try_others()
        if head[:4] != PREFACE[:4]:
            return ParseResult.try_others()
    conn = _conn(socket, is_server)
    data = source.fetch(len(source))
    pos = 0
    if is_server and not conn.preface_seen:
        if len(data) < len(PREFACE):
            return ParseResult.not_enough_data()
        if data[:len(PREFACE)] != PREFACE:
            return ParseResult.parse_error("bad h2 preface")
        conn.preface_seen = True
        pos = len(PREFACE)
        _server_send_settings(socket, conn)
    completed: List[CompletedCall] = []
    while pos + 9 <= len(data):
        length = int.from_bytes(data[pos:pos + 3], "big")
        ftype = data[pos + 3]
        flags = data[pos + 4]
        stream_id = int.from_bytes(data[pos + 5:pos + 9], "big") & 0x7FFFFFFF
        if pos + 9 + length > len(data):
            break
        payload = data[pos + 9:pos + 9 + length]
        pos += 9 + length
        _handle_frame(conn, socket, ftype, flags, stream_id, payload,
                      completed)
    source.pop_front(pos)
    if not completed:
        return ParseResult.not_enough_data()
    return ParseResult.ok(completed)


def _handle_frame(conn: _H2Conn, socket, ftype: int, flags: int,
                  stream_id: int, payload: bytes,
                  completed: List[CompletedCall]) -> None:
    if ftype == FRAME_SETTINGS:
        if not (flags & FLAG_ACK):
            socket.write(IOBuf(frame(FRAME_SETTINGS, FLAG_ACK, 0, b"")))
        return
    if ftype == FRAME_PING:
        if not (flags & FLAG_ACK):
            socket.write(IOBuf(frame(FRAME_PING, FLAG_ACK, 0, payload)))
        return
    if ftype in (FRAME_WINDOW_UPDATE, FRAME_GOAWAY):
        return
    if ftype == FRAME_RST_STREAM:
        conn.streams.pop(stream_id, None)
        return
    st = conn.streams.get(stream_id)
    if st is None:
        st = _H2Stream(stream_id)
        conn.streams[stream_id] = st
    if ftype in (FRAME_HEADERS, FRAME_CONTINUATION):
        hdrs = conn.dec.decode(payload)
        if st.headers_done:
            st.trailers.extend(hdrs)      # trailers
        else:
            st.headers.extend(hdrs)
            if flags & FLAG_END_HEADERS:
                st.headers_done = True
    elif ftype == FRAME_DATA:
        st.data.extend(payload)
        if payload:
            # auto-replenish flow-control windows
            inc = struct.pack(">I", len(payload))
            socket.write(IOBuf(frame(FRAME_WINDOW_UPDATE, 0, 0, inc)
                               + frame(FRAME_WINDOW_UPDATE, 0, stream_id,
                                       inc)))
    if flags & FLAG_END_STREAM:
        st.ended = True
        conn.streams.pop(stream_id, None)
        completed.append(CompletedCall(st, conn.is_server))


def _server_send_settings(socket, conn: _H2Conn) -> None:
    if not conn.settings_sent:
        conn.settings_sent = True
        socket.write(IOBuf(frame(FRAME_SETTINGS, 0, 0, b"")))


# ---- server side ------------------------------------------------------

def process_request(calls: List[CompletedCall], socket, server) -> None:
    for call in calls:
        _process_one_request(call.stream, socket, server)


def _process_one_request(st: _H2Stream, socket, server) -> None:
    path = st.header(b":path").decode()
    parts = [p for p in path.split("/") if p]
    full_name = ".".join(parts[-2:]) if len(parts) >= 2 else path
    md = server.find_method(full_name)
    if md is None:
        _send_grpc_response(socket, st.stream_id, None,
                            GRPC_UNIMPLEMENTED, f"unknown method {path}")
        return
    msgs = split_grpc_messages(bytes(st.data))
    try:
        request = md.request_cls()
        request.ParseFromString(msgs[0] if msgs else b"")
    except Exception as e:
        _send_grpc_response(socket, st.stream_id, None, GRPC_INTERNAL,
                            f"bad request: {e}")
        return
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = socket.remote_side
    response = md.response_cls()
    done_called = [False]

    def done() -> None:
        if done_called[0]:
            return
        done_called[0] = True
        if cntl.failed():
            _send_grpc_response(socket, st.stream_id, None, GRPC_INTERNAL,
                                cntl.error_text_)
        else:
            _send_grpc_response(socket, st.stream_id,
                                response.SerializeToString(), GRPC_OK, "")

    cntl.set_server_done(done)
    try:
        md.invoke(cntl, request, response, done)
    except Exception as e:
        if not done_called[0]:
            cntl.set_failed(errors.EINTERNAL, f"{type(e).__name__}: {e}")
            done()


def _send_grpc_response(socket, stream_id: int, pb_bytes: Optional[bytes],
                        status: int, message: str) -> None:
    conn = socket._h2_conn
    with conn.lock:
        out = IOBuf()
        hdr = conn.enc.encode([(b":status", b"200"),
                               (b"content-type", b"application/grpc+proto")])
        out.append(frame(FRAME_HEADERS, FLAG_END_HEADERS, stream_id, hdr))
        if pb_bytes is not None:
            out.append(frame(FRAME_DATA, 0, stream_id,
                             grpc_message(pb_bytes)))
        trailers = conn.enc.encode([
            (b"grpc-status", str(status).encode()),
            (b"grpc-message", message.encode()[:512])])
        out.append(frame(FRAME_HEADERS,
                         FLAG_END_HEADERS | FLAG_END_STREAM, stream_id,
                         trailers))
        socket.write(out)


# ---- client side ------------------------------------------------------

def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    buf = IOBuf()
    if request is None:
        return buf
    if hasattr(request, "SerializeToString"):
        buf.append(request.SerializeToString())
    else:
        buf.append(bytes(request))
    return buf


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    sock = cntl._pack_socket
    conn = _conn(sock, is_server=False)
    service, _, method = method_full_name.rpartition(".")
    with conn.lock:
        out = IOBuf()
        if not conn.preface_sent:
            conn.preface_sent = True
            out.append(PREFACE)
            out.append(frame(FRAME_SETTINGS, 0, 0, b""))
        stream_id = conn.next_stream_id
        conn.next_stream_id += 2
        conn.cid_by_stream[stream_id] = cid
        authority = str(cntl.remote_side or "").encode() or b"fabric"
        hdr = conn.enc.encode([
            (b":method", b"POST"),
            (b":scheme", b"http"),
            (b":path", f"/{service}/{method}".encode()),
            (b":authority", authority),
            (b"content-type", b"application/grpc+proto"),
            (b"te", b"trailers"),
        ])
        out.append(frame(FRAME_HEADERS, FLAG_END_HEADERS, stream_id, hdr))
        out.append(frame(FRAME_DATA, FLAG_END_STREAM, stream_id,
                         grpc_message(payload.to_bytes())))
        return out


def process_response(calls: List[CompletedCall], socket) -> None:
    from ..bthread import id as bthread_id
    conn = _conn(socket, is_server=False)
    for call in calls:
        st = call.stream
        with conn.lock:
            cid = conn.cid_by_stream.pop(st.stream_id, None)
        if cid is None:
            continue
        rc, cntl = bthread_id.lock(cid)
        if rc != 0 or cntl is None:
            continue
        cntl.remote_side = socket.remote_side
        status = int(st.header(b"grpc-status", b"0") or b"0")
        if status != GRPC_OK:
            cntl.set_failed(_GRPC_TO_RPC.get(status, errors.EINTERNAL),
                            st.header(b"grpc-message").decode("utf-8",
                                                              "replace")
                            or f"grpc-status {status}")
            cntl.finish_parsed_response(cid)
            continue
        msgs = split_grpc_messages(bytes(st.data))
        try:
            if cntl._response_cls is not None:
                resp = cntl._response_cls()
                resp.ParseFromString(msgs[0] if msgs else b"")
                cntl.response = resp
            else:
                cntl.response = msgs[0] if msgs else b""
        except Exception as e:
            cntl.set_failed(errors.ERESPONSE, f"bad grpc response: {e}")
        cntl.finish_parsed_response(cid)


PROTOCOL = Protocol(
    name="grpc",
    parse=parse,
    process_request=process_request,
    process_response=process_response,
    serialize_request=serialize_request,
    pack_request=pack_request,
)


def _register() -> None:
    from ..rpc.protocol import find_protocol
    if find_protocol("grpc") is None:
        register_protocol(PROTOCOL)


_register()
