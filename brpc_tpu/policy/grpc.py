"""HTTP/2 + gRPC protocol (client + server).

Reference: src/brpc/policy/http2_rpc_protocol.cpp + grpc.{h,cpp} +
details/hpack.cpp.  Self-contained implementation of the h2 framing layer
(RFC 7540: preface, SETTINGS/PING/WINDOW_UPDATE/HEADERS/DATA/RST/GOAWAY,
stream states) with HPACK (policy/hpack.py), carrying gRPC semantics
(RFC-style: 5-byte length-prefixed protobuf messages, ``:path`` =
/Service/Method, trailers with grpc-status/grpc-message).

Scope note: unary gRPC calls against our own client/server pair across all
transports; grpc streaming and interop against foreign stacks are untested
here (no grpc/h2 libraries in the image) — the frame and HPACK layers
follow the RFCs so foreign interop is a validation task, not a redesign.

Connection state (hpack tables, live streams, ids) hangs off the socket —
the per-connection context the reference keeps in H2Context.
"""
from __future__ import annotations

import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..butil.iobuf import IOBuf
from ..rpc import errors
from ..rpc.controller import Controller
from ..rpc.protocol import Protocol, ParseResult, register_protocol
from . import hpack

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

FRAME_DATA = 0x0
FRAME_HEADERS = 0x1
FRAME_RST_STREAM = 0x3
FRAME_SETTINGS = 0x4
FRAME_PING = 0x6
FRAME_GOAWAY = 0x7
FRAME_WINDOW_UPDATE = 0x8
FRAME_CONTINUATION = 0x9

FLAG_END_STREAM = 0x1
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20
FLAG_ACK = 0x1

SETTINGS_INITIAL_WINDOW_SIZE = 0x4
SETTINGS_MAX_FRAME_SIZE = 0x5

DEFAULT_WINDOW = 65535
DEFAULT_MAX_FRAME = 16384

GRPC_OK = 0
GRPC_UNKNOWN = 2
GRPC_INVALID_ARGUMENT = 3
GRPC_DEADLINE_EXCEEDED = 4
GRPC_RESOURCE_EXHAUSTED = 8
GRPC_UNIMPLEMENTED = 12
GRPC_INTERNAL = 13
GRPC_UNAVAILABLE = 14

GRPC_UNAUTHENTICATED = 16

# bidirectional status mapping (reference grpc.cpp ErrorCodeToGrpcStatus /
# GrpcStatusToErrorCode)
_GRPC_TO_RPC = {GRPC_INVALID_ARGUMENT: errors.EREQUEST,
                GRPC_DEADLINE_EXCEEDED: errors.ERPCTIMEDOUT,
                GRPC_RESOURCE_EXHAUSTED: errors.ELIMIT,
                GRPC_UNIMPLEMENTED: errors.ENOMETHOD,
                GRPC_INTERNAL: errors.EINTERNAL,
                GRPC_UNAVAILABLE: errors.EFAILEDSOCKET,
                GRPC_UNAUTHENTICATED: errors.ERPCAUTH}
_RPC_TO_GRPC = {v: k for k, v in _GRPC_TO_RPC.items()}   # bijective

# grpc-timeout header units (gRPC HTTP/2 spec): value is ASCII digits +
# one unit char
_TIMEOUT_UNITS_NS = {b"H": 3600 * 10**9, b"M": 60 * 10**9, b"S": 10**9,
                     b"m": 10**6, b"u": 10**3, b"n": 1}


def parse_grpc_timeout_ms(value: bytes) -> Optional[int]:
    """"100m" → 100; None when absent/malformed."""
    if not value or len(value) < 2:
        return None
    unit = value[-1:]
    mult = _TIMEOUT_UNITS_NS.get(unit)
    if mult is None or not value[:-1].isdigit():
        return None
    return max(1, int(value[:-1]) * mult // 10**6)


def frame(ftype: int, flags: int, stream_id: int, payload: bytes) -> bytes:
    return (struct.pack(">I", len(payload))[1:]
            + bytes([ftype, flags]) + struct.pack(">I", stream_id & 0x7FFFFFFF)
            + payload)


def grpc_message(pb_bytes: bytes) -> bytes:
    return b"\x00" + struct.pack(">I", len(pb_bytes)) + pb_bytes


def split_grpc_messages(data: bytes) -> List[bytes]:
    out = []
    pos = 0
    while pos + 5 <= len(data):
        _compressed = data[pos]
        n = struct.unpack(">I", data[pos + 1:pos + 5])[0]
        out.append(data[pos + 5:pos + 5 + n])
        pos += 5 + n
    return out


class _H2Stream:
    __slots__ = ("stream_id", "headers", "trailers", "data", "ended",
                 "headers_done", "hdr_frag", "end_after_headers")

    def __init__(self, stream_id: int):
        self.stream_id = stream_id
        self.headers: List[Tuple[bytes, bytes]] = []
        self.trailers: List[Tuple[bytes, bytes]] = []
        self.data = bytearray()
        self.ended = False
        self.headers_done = False
        # header-block fragments accumulate here until END_HEADERS: an
        # HPACK block is one unit — decoding per-fragment corrupts any
        # string split across a CONTINUATION boundary (RFC 7540 §4.3)
        self.hdr_frag = bytearray()
        self.end_after_headers = False

    def header(self, name: bytes, default: bytes = b"") -> bytes:
        for k, v in self.headers + self.trailers:
            if k == name:
                return v
        return default


class _H2Conn:
    """Per-socket connection context (the reference's H2Context):
    hpack tables, live streams, and BOTH flow-control directions —
    the send windows here gate our DATA (RFC 7540 §5.2; reference
    http2_rpc_protocol.cpp H2Context::_remote_window_left)."""

    def __init__(self, is_server: bool):
        self.is_server = is_server
        self.preface_seen = not is_server
        self.preface_sent = False
        self.settings_sent = False
        self.enc = hpack.Encoder()
        self.dec = hpack.Decoder()
        self.streams: Dict[int, _H2Stream] = {}
        self.next_stream_id = 1          # client-initiated odd ids
        self.cid_by_stream: Dict[int, int] = {}
        # REENTRANT: with a stateful hpack encoder, header blocks must
        # hit the wire in ENCODE order — every path that encodes a block
        # holds this lock across encode AND write.  Reentrancy matters on
        # the loopback transport, where a write can deliver inline and
        # the peer's processing re-enters this side's conn.
        self.lock = threading.RLock()
        # peer-granted send windows (ours to spend)
        self.send_window = DEFAULT_WINDOW
        self.stream_send: Dict[int, int] = {}
        self.initial_window = DEFAULT_WINDOW
        self.max_frame_size = DEFAULT_MAX_FRAME
        # DATA waiting for window: stream_id -> list of [bytes, end_flag]
        self.pending: Dict[int, List] = {}
        # server: streams whose request completed but whose response
        # hasn't finished sending — the window between conn.streams pop
        # and the first response DATA, where early credit must be kept
        self.serving: set = set()
        # WINDOW_UPDATE credit granted before our first DATA on a
        # stream (a peer funding a large response upfront).  Kept OUT of
        # stream_send — booking it there would leak one entry per
        # completed call (review finding r5) — and consumed at the
        # stream's first _send_data
        self.early_credit: Dict[int, int] = {}
        self.expect_continuation: Optional[int] = None
        self.last_processed_sid = 0      # server: for GOAWAY on stop
        # client: peer's GOAWAY last_stream_id (None = no GOAWAY seen);
        # once set, no new stream may be packed on this connection
        self.goaway_last_sid: Optional[int] = None


def _conn(socket, is_server: bool) -> _H2Conn:
    c = getattr(socket, "_h2_conn", None)
    if c is None:
        c = _H2Conn(is_server)
        socket._h2_conn = c
        if not is_server:
            # a dead h2 connection can never deliver its responses: fail
            # every outstanding stream's call (retryably) the moment the
            # socket fails, whatever killed it — GOAWAY, TCP reset,
            # server stop.  Without this, every in-flight h2 call burns
            # its full deadline on any connection death.
            cbs = getattr(socket, "on_failed_callbacks", None)
            if cbs is not None:
                cbs.append(lambda _s, conn=c: _fail_all_client_streams(conn))
    return c


def _fail_all_client_streams(conn: "_H2Conn") -> None:
    from ..bthread import id as bthread_id
    with conn.lock:
        cids = list(conn.cid_by_stream.values())
        conn.cid_by_stream.clear()
        conn.pending.clear()
    for cid in cids:
        bthread_id.error(cid, errors.EFAILEDSOCKET)


class CompletedCall:
    """A fully-received request or response stream."""

    __slots__ = ("stream", "is_request")

    def __init__(self, stream: _H2Stream, is_request: bool):
        self.stream = stream
        self.is_request = is_request


# ---- parse ------------------------------------------------------------

def parse(source: IOBuf, socket, read_eof: bool, arg) -> ParseResult:
    """Consume every complete frame in order (HPACK state is sequential);
    returns the list of CompletedCalls that finished in this batch."""
    is_server = getattr(arg, "server", None) is not None
    head = source.fetch(min(len(source), len(PREFACE)))
    if head is None:
        return ParseResult.not_enough_data()
    conn = getattr(socket, "_h2_conn", None)
    if conn is None:
        if not is_server:
            return ParseResult.try_others()   # client conns init at pack time
        if len(head) < 4:
            if PREFACE.startswith(head):
                return ParseResult.not_enough_data()
            return ParseResult.try_others()
        if head[:4] != PREFACE[:4]:
            return ParseResult.try_others()
    conn = _conn(socket, is_server)
    data = source.fetch(len(source))
    pos = 0
    if is_server and not conn.preface_seen:
        if len(data) < len(PREFACE):
            return ParseResult.not_enough_data()
        if data[:len(PREFACE)] != PREFACE:
            return ParseResult.parse_error("bad h2 preface")
        conn.preface_seen = True
        pos = len(PREFACE)
        _server_send_settings(socket, conn)
    completed: List[CompletedCall] = []
    while pos + 9 <= len(data):
        length = int.from_bytes(data[pos:pos + 3], "big")
        ftype = data[pos + 3]
        flags = data[pos + 4]
        stream_id = int.from_bytes(data[pos + 5:pos + 9], "big") & 0x7FFFFFFF
        if pos + 9 + length > len(data):
            break
        payload = data[pos + 9:pos + 9 + length]
        pos += 9 + length
        _handle_frame(conn, socket, ftype, flags, stream_id, payload,
                      completed)
    source.pop_front(pos)
    if not completed:
        return ParseResult.not_enough_data()
    return ParseResult.ok(completed)


def _handle_frame(conn: _H2Conn, socket, ftype: int, flags: int,
                  stream_id: int, payload: bytes,
                  completed: List[CompletedCall]) -> None:
    # RFC 7540 §6.2: an unterminated header block admits ONLY CONTINUATION
    # frames on the same stream — ANY other frame (including control
    # frames and RST_STREAM) is a connection error, checked before every
    # early return below or the shared hpack decoder desyncs
    if conn.expect_continuation is not None and (
            ftype != FRAME_CONTINUATION
            or stream_id != conn.expect_continuation):
        _fail_h2_conn(socket,
                      "h2: frame interleaved inside a header block")
        return
    if ftype == FRAME_SETTINGS:
        if not (flags & FLAG_ACK):
            _apply_settings(conn, socket, payload)
            socket.write(IOBuf(frame(FRAME_SETTINGS, FLAG_ACK, 0, b"")))
        return
    if ftype == FRAME_PING:
        if not (flags & FLAG_ACK):
            socket.write(IOBuf(frame(FRAME_PING, FLAG_ACK, 0, payload)))
        return
    if ftype == FRAME_WINDOW_UPDATE:
        if len(payload) >= 4:
            inc = struct.unpack(">I", payload[:4])[0] & 0x7FFFFFFF
            _on_window_update(conn, socket, stream_id, inc)
        return
    if ftype == FRAME_GOAWAY:
        if not conn.is_server:
            last_sid = (struct.unpack(">I", payload[:4])[0] & 0x7FFFFFFF) \
                if len(payload) >= 4 else 0
            _on_goaway(conn, socket, last_sid)
        return
    if ftype == FRAME_RST_STREAM:
        err = struct.unpack(">I", payload[:4])[0] if len(payload) >= 4 \
            else 0
        with conn.lock:
            conn.streams.pop(stream_id, None)
            conn.pending.pop(stream_id, None)
            _retire_stream_send(conn, stream_id)
        # a reset stream will never carry a response: complete the call
        # now instead of letting it burn its whole deadline.
        # REFUSED_STREAM (0x7) guarantees the request was NOT processed
        # (§8.1.4) → a retryable code; anything else → canceled.
        if not conn.is_server:
            _fail_client_stream(
                conn, stream_id,
                errors.EAGAIN if err == 0x7 else errors.ECANCELED)
            _close_if_drained(conn, socket)
        return
    st = conn.streams.get(stream_id)
    if st is None:
        st = _H2Stream(stream_id)
        conn.streams[stream_id] = st
    if ftype in (FRAME_HEADERS, FRAME_CONTINUATION):
        frag = payload
        if ftype == FRAME_HEADERS:
            # strip padding + priority per RFC 7540 §6.2; a pad length
            # that meets or exceeds the remaining payload is a
            # connection-level PROTOCOL_ERROR (§6.1/§6.2)
            if flags & FLAG_PADDED:
                # the 5-byte PRIORITY field also lives inside the
                # payload: pad + padlen byte + priority must all fit
                prio = 5 if flags & FLAG_PRIORITY else 0
                if not frag or frag[0] + 1 + prio > len(frag):
                    _fail_h2_conn(socket,
                                  "h2: HEADERS pad exceeds payload")
                    return
                pad = frag[0]
                frag = frag[1:len(frag) - pad]
            if flags & FLAG_PRIORITY:
                frag = frag[5:]
            st.end_after_headers = bool(flags & FLAG_END_STREAM)
        st.hdr_frag.extend(frag)
        if flags & FLAG_END_HEADERS:
            # an HPACK block decodes as ONE unit, only now that every
            # CONTINUATION fragment arrived (RFC 7540 §4.3)
            hdrs = conn.dec.decode(bytes(st.hdr_frag))
            st.hdr_frag.clear()
            conn.expect_continuation = None
            if st.headers_done:
                st.trailers.extend(hdrs)      # trailers
            else:
                st.headers.extend(hdrs)
                st.headers_done = True
        else:
            conn.expect_continuation = stream_id
        if ftype == FRAME_CONTINUATION and \
                not (flags & FLAG_END_HEADERS):
            return
        # END_STREAM on the HEADERS frame takes effect once the block
        # completes (trailers case: HEADERS+END_STREAM after DATA)
        flags = (flags & ~FLAG_END_STREAM) | (
            FLAG_END_STREAM if (st.end_after_headers
                                and not st.hdr_frag) else 0)
    elif ftype == FRAME_DATA:
        body = payload
        if flags & FLAG_PADDED:
            if not body or body[0] >= len(body):
                _fail_h2_conn(socket, "h2: DATA pad exceeds payload")
                return
            pad = body[0]
            body = body[1:len(body) - pad]
        st.data.extend(body)
        if payload:
            # auto-replenish OUR receive windows (we buffer whole
            # messages, so the window never back-pressures the peer)
            inc = struct.pack(">I", len(payload))
            socket.write(IOBuf(frame(FRAME_WINDOW_UPDATE, 0, 0, inc)
                               + frame(FRAME_WINDOW_UPDATE, 0, stream_id,
                                       inc)))
    if flags & FLAG_END_STREAM:
        st.ended = True
        conn.streams.pop(stream_id, None)
        if conn.is_server:
            # request complete, response pending: keep accepting the
            # peer's upfront response credit until the response sends
            conn.serving.add(stream_id)
        else:
            # response complete: we will never send on this stream again
            conn.early_credit.pop(stream_id, None)
        completed.append(CompletedCall(st, conn.is_server))


def _on_goaway(conn: _H2Conn, socket, last_sid: int) -> None:
    """Graceful GOAWAY (RFC 7540 §6.8).  Streams with id > last_stream_id
    were NOT processed by the peer — fail them retryably (§8.1.4) so they
    re-run on a fresh connection.  Streams ≤ last_stream_id may still get
    their responses: they keep waiting, and the socket-failure hook
    completes them if the transport actually closes.  The connection is
    logged off — no NEW stream packs onto it (pack_request refuses, the
    SocketMap replaces it on next use) — NOT set_failed: failing the whole
    conn here would discard in-flight responses the server already
    executed and auto-retry non-idempotent RPCs (reference
    http2_rpc_protocol.cpp OnGoAway/RemoveGoAwayStreams + SetLogOff)."""
    from ..bthread import id as bthread_id
    with conn.lock:
        conn.goaway_last_sid = last_sid
        refused = [(sid, cid) for sid, cid in conn.cid_by_stream.items()
                   if sid > last_sid]
        for sid, _cid in refused:
            conn.cid_by_stream.pop(sid, None)
            conn.streams.pop(sid, None)
            conn.pending.pop(sid, None)
            _retire_stream_send(conn, sid)
    socket.logoff = True
    for _sid, cid in refused:
        bthread_id.error(cid, errors.EAGAIN)
    _close_if_drained(conn, socket)


def _close_if_drained(conn: _H2Conn, socket) -> None:
    """A logged-off connection whose last awaited response has arrived
    has no further use — close it, or one orphaned fd (plus hpack state)
    accumulates per GOAWAY cycle, e.g. per rolling server deploy, on a
    long-lived client (review finding r5).  The peer may legally hold
    the conn open forever after GOAWAY (RFC 7540 §6.8), so WE close."""
    if getattr(socket, "logoff", False) and not conn.cid_by_stream:
        fail = getattr(socket, "set_failed", None)
        if fail is not None:
            fail(errors.EFAILEDSOCKET, "h2 GOAWAY drained")


def _fail_client_stream(conn: _H2Conn, stream_id: int, code: int) -> None:
    """Deliver a dead-stream failure through the correlation machinery
    (bthread_id.error → Controller._on_rpc_event — the socket.py:218
    discipline): retryable codes actually retry, and a straggler try
    under hedging cannot destroy the live hedge's correlation id."""
    from ..bthread import id as bthread_id
    with conn.lock:
        cid = conn.cid_by_stream.pop(stream_id, None)
    if cid is None:
        return
    bthread_id.error(cid, code)


def _fail_h2_conn(socket, why: str) -> None:
    """Connection-fatal h2 condition (protocol violation or a write that
    didn't reach the wire): with a stateful hpack encoder the connection
    is unrecoverable — fail the socket so callers reconnect fresh."""
    fail = getattr(socket, "set_failed", None)
    if fail is not None:
        fail(errors.EFAILEDSOCKET, why)


def _h2_write(socket, out: IOBuf, why: str) -> int:
    """Write h2 frames; a failed write after hpack encoding desyncs the
    peer's dynamic table permanently, so the connection dies with it."""
    rc = socket.write(out)
    if rc != 0:
        _fail_h2_conn(socket, f"h2: {why} write failed ({rc}) — "
                              "hpack state unrecoverable")
    return rc


def _apply_settings(conn: _H2Conn, socket, payload: bytes) -> None:
    """Peer SETTINGS: INITIAL_WINDOW_SIZE retro-adjusts every open
    stream's send window by the delta (RFC 7540 §6.9.2)."""
    flush = False
    with conn.lock:
        for off in range(0, len(payload) - 5, 6):
            ident, value = struct.unpack_from(">HI", payload, off)
            if ident == SETTINGS_INITIAL_WINDOW_SIZE:
                delta = value - conn.initial_window
                conn.initial_window = value
                for sid in conn.stream_send:
                    conn.stream_send[sid] += delta
                flush = delta > 0
            elif ident == SETTINGS_MAX_FRAME_SIZE:
                if 16384 <= value <= (1 << 24) - 1:
                    conn.max_frame_size = value
    if flush:
        _flush_pending(conn, socket)


def _on_window_update(conn: _H2Conn, socket, stream_id: int,
                      inc: int) -> None:
    with conn.lock:
        if stream_id == 0:
            conn.send_window += inc
        elif stream_id in conn.stream_send:
            conn.stream_send[stream_id] += inc
        elif stream_id in conn.streams or stream_id in conn.serving:
            # credit granted before our first DATA on this stream
            # (receiving the request, or serving it and not yet
            # responding): book it aside — _send_data's
            # setdefault(initial_window) would forget the grant and
            # under-credit the stream, parking DATA the peer had funded
            conn.early_credit[stream_id] = \
                conn.early_credit.get(stream_id, 0) + inc
    _flush_pending(conn, socket)


def _send_data(conn: _H2Conn, out: IOBuf, stream_id: int, data: bytes,
               end_stream: bool) -> None:
    """Emit DATA within the peer's flow-control windows (both levels,
    RFC 7540 §6.9: the lower of connection and stream window gates every
    byte), splitting at max_frame_size; what doesn't fit queues on the
    conn and drains when WINDOW_UPDATE/SETTINGS credit arrives.  Caller
    holds conn.lock."""
    if stream_id not in conn.stream_send:
        # first send on this stream: base window + any credit the peer
        # granted before we started sending
        conn.stream_send[stream_id] = conn.initial_window + \
            conn.early_credit.pop(stream_id, 0)
    if not data:
        if end_stream:                   # empty DATA costs no window
            out.append(frame(FRAME_DATA, FLAG_END_STREAM, stream_id, b""))
            _retire_stream_send(conn, stream_id)
        return
    pos = 0
    n = len(data)
    while pos < n:
        left = min(conn.send_window, conn.stream_send[stream_id],
                   conn.max_frame_size)
        if left <= 0:
            # window exhausted: park the tail (ordered per stream)
            conn.pending.setdefault(stream_id, []).append(
                [data[pos:], end_stream])
            return
        take = min(left, n - pos)
        last = (pos + take == n)
        out.append(frame(FRAME_DATA,
                         FLAG_END_STREAM if (last and end_stream) else 0,
                         stream_id, bytes(data[pos:pos + take])))
        conn.send_window -= take
        conn.stream_send[stream_id] -= take
        pos += take
    if end_stream:
        # stream fully sent: retire its window entry (a long-lived conn
        # must not accumulate one dict entry per finished stream)
        _retire_stream_send(conn, stream_id)


def _retire_stream_send(conn: _H2Conn, stream_id: int) -> None:
    """Our side of the stream is done sending: drop every per-stream
    send-side record (caller holds conn.lock)."""
    conn.stream_send.pop(stream_id, None)
    conn.early_credit.pop(stream_id, None)
    conn.serving.discard(stream_id)


def _flush_pending(conn: _H2Conn, socket) -> None:
    """Drain parked DATA now that credit returned.  Every chunk either
    emits into ``out`` or re-parks via _send_data — nothing is lost.
    The write happens UNDER conn.lock: parked trailers are hpack-encoded
    at emission time, and that block must reach the wire before any
    block encoded after it."""
    with conn.lock:
        out = IOBuf()
        parked, conn.pending = conn.pending, {}
        for sid, chunks in parked.items():
            for i, (data, end) in enumerate(chunks):
                if data is None:
                    # parked trailers ([None, header_list]): encode NOW —
                    # encoding at park time would let later blocks refer
                    # to table entries the peer hasn't seen yet
                    block = conn.enc.encode(end)
                    _append_header_block(conn, out, sid, block,
                                         end_stream=True)
                    _retire_stream_send(conn, sid)
                    continue
                _send_data(conn, out, sid, data, end)
                if sid in conn.pending:          # still blocked: keep the
                    conn.pending[sid].extend(chunks[i + 1:])   # rest, in
                    break                                      # order
        if len(out):
            _h2_write(socket, out, "flush")


def _server_send_settings(socket, conn: _H2Conn) -> None:
    if not conn.settings_sent:
        conn.settings_sent = True
        socket.write(IOBuf(frame(FRAME_SETTINGS, 0, 0, b"")))


# ---- server side ------------------------------------------------------

def process_request(calls: List[CompletedCall], socket, server) -> None:
    for call in calls:
        _process_one_request(call.stream, socket, server)


def send_goaway(socket) -> None:
    """Graceful-shutdown courtesy (RFC 7540 §6.8): tell the peer which
    streams were processed.  Called by Server.stop() on h2 connections
    just before failing them — best-effort: a backpressured transport
    may drop it with the rest of the write queue, and correctness does
    not depend on it (the client's socket-failure hook fails all
    outstanding calls retryably on any connection death)."""
    conn = getattr(socket, "_h2_conn", None)
    if conn is None:
        return
    payload = struct.pack(">II", conn.last_processed_sid & 0x7FFFFFFF, 0)
    socket.write(IOBuf(frame(FRAME_GOAWAY, 0, 0, payload)))


def _process_one_request(st: _H2Stream, socket, server) -> None:
    conn = getattr(socket, "_h2_conn", None)
    if conn is not None and st.stream_id > conn.last_processed_sid:
        conn.last_processed_sid = st.stream_id
    path = st.header(b":path").decode()
    parts = [p for p in path.split("/") if p]
    full_name = ".".join(parts[-2:]) if len(parts) >= 2 else path
    start_us = time.monotonic_ns() // 1000
    cntl = Controller()
    cntl.server = server
    cntl.remote_side = socket.remote_side
    # grpc-timeout propagation (gRPC-over-HTTP/2 spec): the client's
    # deadline lands on cntl.method_deadline — the SAME server-side field
    # every other protocol uses (tpu_std.py:183), so handler code is
    # transport-independent
    deadline_ms = parse_grpc_timeout_ms(st.header(b"grpc-timeout"))
    if deadline_ms is not None:
        cntl.method_deadline = time.monotonic() + deadline_ms / 1000.0
    # one request discipline for BOTH content types on h2 — switching
    # content-type must bypass neither the authenticator nor the
    # server-level overload guard (review finding r4)
    is_grpc = st.header(b"content-type").startswith(b"application/grpc")

    def reject_early(code: int, text: str, http_code: int) -> None:
        if is_grpc:
            _send_grpc_response(socket, st.stream_id, None,
                                _RPC_TO_GRPC.get(code, GRPC_INTERNAL), text)
        else:
            import json as _json
            _send_h2_http_response(socket, st.stream_id, http_code,
                                   _json.dumps({"error": text}).encode())

    if not server.on_request_in():
        reject_early(errors.ELIMIT, "server max_concurrency reached", 503)
        return
    # counted from here on: every exit path must on_request_out
    if server.options.auth is not None:
        cntl.auth_token = st.header(b"authorization").decode(
            "utf-8", "replace")
        if not server.options.auth.verify(cntl.auth_token, socket):
            server.on_request_out()
            reject_early(errors.ERPCAUTH, "authentication failed", 401)
            return
    if not is_grpc:
        # the REST side of the reference's h2 protocol
        # (http2_rpc_protocol.cpp serves both): JSON in, JSON out, plain
        # HTTP response semantics (no grpc trailers); dispatch shared
        # with policy/http.py so the two REST planes cannot drift
        from .http import json_rpc_dispatch
        md = server.find_method(full_name)
        if md is None:
            server.on_request_out()
            import json as _json
            _send_h2_http_response(
                socket, st.stream_id, 404,
                _json.dumps({"error": f"no handler for {path}"}).encode())
            return

        def send(code: int, body_bytes: bytes) -> None:
            _send_h2_http_response(socket, st.stream_id, code, body_bytes)
            server.on_request_out()

        body = bytes(st.data).decode("utf-8", "replace") or "{}"
        json_rpc_dispatch(server, md, full_name, body, send, start_us,
                          cntl)
        return
    md = server.find_method(full_name)
    status = server.method_status(full_name) if md is not None else None

    def reply_error(code: int, text: str) -> None:
        _send_grpc_response(socket, st.stream_id, None,
                            _RPC_TO_GRPC.get(code, GRPC_INTERNAL), text)
        server.on_request_out()

    if md is None:
        reply_error(errors.ENOMETHOD, f"unknown method {path}")
        return
    if status is not None and not status.on_requested():
        status = None             # don't on_responded a rejected request
        reply_error(errors.ELIMIT,
                    f"method {full_name} max_concurrency reached")
        return
    msgs = split_grpc_messages(bytes(st.data))
    try:
        request = md.request_cls()
        request.ParseFromString(msgs[0] if msgs else b"")
    except Exception as e:
        if status is not None:
            status.on_responded(errors.EREQUEST, 0)
        reply_error(errors.EREQUEST, f"bad request: {e}")
        return
    response = md.response_cls()
    done_called = [False]

    def done() -> None:
        if done_called[0]:
            return
        done_called[0] = True
        if cntl.failed():
            _send_grpc_response(
                socket, st.stream_id, None,
                _RPC_TO_GRPC.get(cntl.error_code_, GRPC_INTERNAL),
                cntl.error_text_)
        else:
            _send_grpc_response(socket, st.stream_id,
                                response.SerializeToString(), GRPC_OK, "")
        if status is not None:
            status.on_responded(cntl.error_code_,
                                time.monotonic_ns() // 1000 - start_us)
        server.on_request_out()

    cntl.set_server_done(done)
    try:
        md.invoke(cntl, request, response, done)
    except Exception as e:
        if not done_called[0]:
            cntl.set_failed(errors.EINTERNAL, f"{type(e).__name__}: {e}")
            done()


def _send_h2_http_response(socket, stream_id: int, status_code: int,
                           body: bytes,
                           content_type: bytes = b"application/json"
                           ) -> None:
    """Plain HTTP semantics over h2 (REST responses): :status + body,
    END_STREAM on the last frame, no grpc trailers."""
    conn = socket._h2_conn
    with conn.lock:
        out = IOBuf()
        hdr = conn.enc.encode([
            (b":status", str(status_code).encode()),
            (b"content-type", content_type),
            (b"content-length", str(len(body)).encode())])
        _append_header_block(conn, out, stream_id, hdr,
                             end_stream=not body)
        if body:
            _send_data(conn, out, stream_id, body, end_stream=True)
        else:
            _retire_stream_send(conn, stream_id)
        _h2_write(socket, out, "h2 rest response")


def _append_header_block(conn: _H2Conn, out: IOBuf, stream_id: int,
                         block: bytes, end_stream: bool) -> None:
    """HEADERS (+CONTINUATIONs when the block exceeds max_frame_size,
    RFC 7540 §6.10).  Caller holds conn.lock."""
    mfs = conn.max_frame_size
    first, rest = block[:mfs], block[mfs:]
    flags = (FLAG_END_STREAM if end_stream else 0) | \
        (0 if rest else FLAG_END_HEADERS)
    out.append(frame(FRAME_HEADERS, flags, stream_id, first))
    while rest:
        frag, rest = rest[:mfs], rest[mfs:]
        out.append(frame(FRAME_CONTINUATION,
                         0 if rest else FLAG_END_HEADERS, stream_id, frag))


def _send_grpc_response(socket, stream_id: int, pb_bytes: Optional[bytes],
                        status: int, message: str) -> None:
    conn = socket._h2_conn
    with conn.lock:
        out = IOBuf()
        hdr = conn.enc.encode([(b":status", b"200"),
                               (b"content-type", b"application/grpc+proto")])
        _append_header_block(conn, out, stream_id, hdr, end_stream=False)
        if pb_bytes is not None:
            _send_data(conn, out, stream_id, grpc_message(pb_bytes),
                       end_stream=False)
        trailer_list = [
            (b"grpc-status", str(status).encode()),
            (b"grpc-message", message.encode()[:512])]
        if stream_id in conn.pending:
            # DATA is parked behind the window: the trailers must follow
            # it, not jump ahead.  Park the header LIST — hpack encoding
            # happens at emission so table references stay in wire order.
            conn.pending[stream_id].append([None, trailer_list])
        else:
            _append_header_block(conn, out, stream_id,
                                 conn.enc.encode(trailer_list),
                                 end_stream=True)
            _retire_stream_send(conn, stream_id)
        _h2_write(socket, out, "response")


# ---- client side ------------------------------------------------------

def serialize_request(request: Any, cntl: Controller) -> IOBuf:
    buf = IOBuf()
    if request is None:
        return buf
    if hasattr(request, "SerializeToString"):
        buf.append(request.SerializeToString())
    else:
        buf.append(bytes(request))
    return buf


def pack_request(payload: IOBuf, cid: int, cntl: Controller,
                 method_full_name: str) -> IOBuf:
    """Builds AND writes the request frames under conn.lock, returning an
    empty packet for the generic write path.  The direct write is what
    makes hpack safe under concurrency: with a stateful encoder, a block
    encoded first must reach the wire first, and a parked DATA tail must
    never be flushed (by a racing WINDOW_UPDATE) ahead of its own head."""
    sock = cntl._pack_socket
    conn = _conn(sock, is_server=False)
    service, _, method = method_full_name.rpartition(".")
    with conn.lock:
        if conn.goaway_last_sid is not None:
            # peer is going away: this conn takes no new streams.  The
            # raise maps to a retryable EFAILEDSOCKET (controller.py:192)
            # and the retry's _select_socket sees socket.logoff and
            # connects fresh.
            raise ConnectionError("h2 connection going away (GOAWAY)")
        out = IOBuf()
        if not conn.preface_sent:
            conn.preface_sent = True
            out.append(PREFACE)
            out.append(frame(FRAME_SETTINGS, 0, 0, b""))
        stream_id = conn.next_stream_id
        conn.next_stream_id += 2
        conn.cid_by_stream[stream_id] = cid
        authority = str(cntl.remote_side or "").encode() or b"fabric"
        req_headers = [
            (b":method", b"POST"),
            (b":scheme", b"http"),
            (b":path", f"/{service}/{method}".encode()),
            (b":authority", authority),
            (b"content-type", b"application/grpc+proto"),
            (b"te", b"trailers"),
        ]
        auth_token = getattr(cntl, "auth_token", "")
        if auth_token:
            req_headers.append((b"authorization",
                                auth_token if isinstance(auth_token, bytes)
                                else auth_token.encode()))
        timeout_ms = getattr(cntl, "timeout_ms", None)
        if timeout_ms and timeout_ms > 0:
            # deadline crosses the wire (gRPC spec grpc-timeout header) as
            # the REMAINING budget: a retry/hedge must not re-advertise
            # the full original timeout (the server would over-budget
            # work the client has already given up on)
            start_us = getattr(cntl, "_start_us", 0)
            if start_us:
                elapsed_ms = (time.monotonic_ns() // 1000
                              - start_us) / 1000.0
                timeout_ms = max(1, int(timeout_ms - elapsed_ms))
            req_headers.append(
                (b"grpc-timeout", b"%dm" % int(timeout_ms)))
        hdr = conn.enc.encode(req_headers)
        _append_header_block(conn, out, stream_id, hdr, end_stream=False)
        _send_data(conn, out, stream_id,
                   grpc_message(payload.to_bytes()), end_stream=True)
        rc = _h2_write(sock, out, "request")
        if rc != 0:
            raise ConnectionError(f"h2 write failed: {rc}")
    return IOBuf()


def process_response(calls: List[CompletedCall], socket) -> None:
    from ..bthread import id as bthread_id
    conn = _conn(socket, is_server=False)
    for call in calls:
        st = call.stream
        with conn.lock:
            cid = conn.cid_by_stream.pop(st.stream_id, None)
        if cid is None:
            continue
        rc, cntl = bthread_id.lock(cid)
        if rc != 0 or cntl is None:
            continue
        cntl.remote_side = socket.remote_side
        status = int(st.header(b"grpc-status", b"0") or b"0")
        if status != GRPC_OK:
            cntl.set_failed(_GRPC_TO_RPC.get(status, errors.EINTERNAL),
                            st.header(b"grpc-message").decode("utf-8",
                                                              "replace")
                            or f"grpc-status {status}")
            cntl.finish_parsed_response(cid)
            continue
        msgs = split_grpc_messages(bytes(st.data))
        try:
            if cntl._response_cls is not None:
                resp = cntl._response_cls()
                resp.ParseFromString(msgs[0] if msgs else b"")
                cntl.response = resp
            else:
                cntl.response = msgs[0] if msgs else b""
        except Exception as e:
            cntl.set_failed(errors.ERESPONSE, f"bad grpc response: {e}")
        cntl.finish_parsed_response(cid)
    _close_if_drained(conn, socket)


PROTOCOL = Protocol(
    name="grpc",
    parse=parse,
    process_request=process_request,
    process_response=process_response,
    serialize_request=serialize_request,
    pack_request=pack_request,
)


def _register() -> None:
    from ..rpc.protocol import find_protocol
    if find_protocol("grpc") is None:
        register_protocol(PROTOCOL)


_register()
